"""Battery for stateful solve sessions (ISSUE 13):

- the DynamicMaxSumEngine mutation ladder: remove_factor →
  add_factor with name reuse on a freed slack row (zero recompiles),
  add_factor past the slack budget and add_variable (the
  recompile-carrying-messages path — warm cycle counter survives),
  checkpoint/restore mid-mutation equal to uninterrupted;
- decimation clamps: pinning, release on TOUCHED variables only,
  clamp survival across a recompile;
- the acceptance pair: in-shape events apply with ZERO recompiles
  (the ``recompiles`` metric asserts it) and post-event session
  assignments are cost-equivalent (≤ 1e-6 rel) to a fresh
  ``api.solve`` of the mutated problem on integer tables;
- the session service: open → events → close in-process and over
  real HTTP (PATCH durability, SSE stream, DELETE final, 404/409/400
  surfaces, session limit 429, idempotent close);
- journal + crash replay: pending_sessions bookkeeping, compaction
  retention of open sessions, SIGKILL-equivalent replay equal to the
  uninterrupted run, checkpointed-state restore, graceful park →
  recover;
- session-scoped tracing: ``pydcop trace query --request`` material —
  one well-nested tagged tree per session;
- scenario replay (``pydcop solve --scenario`` machinery) over
  generated factor scenarios, and the sentinel's session families.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pydcop_tpu import api
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.dynamic import (
    apply_action,
    build_dynamic_engine,
    replay_scenario,
)
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.serving import journal as journal_mod
from pydcop_tpu.serving.journal import (
    pending_sessions,
    scan_journal,
    session_ckpt_record,
    session_close_record,
    session_event_record,
    session_open_record,
)
from pydcop_tpu.serving.service import SolveService
from pydcop_tpu.serving.sessions import (
    SessionClosed,
    SessionLimit,
    normalize_session_params,
    scenario_yaml_to_events,
    validate_events,
)

# Strict-parity session parameters: tree topologies + a tight
# stability threshold make warm re-convergence land on exactly the
# fresh solve's fixpoint (the approx-match suppression otherwise
# tolerates up to ``stability`` of per-edge drift, which can flip
# near-tie argmins on integer tables).
PARITY_PARAMS = {"noise": 0.01, "stability": 0.001,
                 "max_cycles": 600, "segment_cycles": 100}


def _ring(n: int, seed: int, name: str = "ring") -> DCOP:
    """Ring coloring, integer tables (the serve-plane's stock
    instance)."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"{name}{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % n]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0"), AgentDef("a1")])
    return dcop


def _path(n: int, seed: int) -> DCOP:
    """Path (tree) coloring: max-sum is exact here, so warm and fresh
    solves must agree to the last ulp on integer tables."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"path{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n - 1):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _table(rng, shape=(3, 3)):
    return rng.integers(0, 10, size=shape).astype(float)


def _mutated_dcop(engine) -> DCOP:
    mutated = DCOP("mutated", objective="min")
    for v in engine.variables:
        mutated.add_variable(v)
    for c in engine.factors.values():
        mutated.add_constraint(c)
    mutated.add_agents([AgentDef("a0")])
    return mutated


def _fresh_cost(engine, max_cycles=600, noise=0.01,
                stability=0.001) -> float:
    """Cost of a FRESH api.solve over the engine's current (mutated)
    factor set — the acceptance comparison's right-hand side."""
    res = api.solve(_mutated_dcop(engine), "maxsum",
                    max_cycles=max_cycles,
                    algo_params={"noise": noise,
                                 "stability": stability})
    return res["cost"]


def _exact_cost(engine) -> float:
    """DPOP (exact) optimum of the mutated problem — the warm
    session's quality reference on tree topologies."""
    return api.solve(_mutated_dcop(engine), "dpop")["cost"]


@pytest.fixture(autouse=True)
def _restore_observability_flags():
    """The crash-simulation tests kill a started service's scheduler
    directly (no ``stop()``) — exactly how a real crash looks, but
    ``SolveService.start()`` latches ``metrics_registry.active`` and
    ``profiler.enabled`` process-wide and only ``stop()`` restores
    them.  Without this restore the flags leak ``True`` into every
    battery that runs after this one (test_perf_intel_battery's
    session-leak test was the first casualty)."""
    from pydcop_tpu.observability.metrics import (
        registry as global_registry,
    )
    from pydcop_tpu.observability.profiler import profiler

    was_active = global_registry.active
    was_profiling = profiler.enabled
    yield
    global_registry.active = was_active
    profiler.enabled = was_profiling


def _service(**kw) -> SolveService:
    kw.setdefault("batch_window_s", 0.02)
    kw.setdefault("max_batch", 8)
    return SolveService(**kw)


def _wait_converged(svc, sid, timeout=30.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        st = svc.sessions.status(sid)
        if st["last"] is not None and st["last"].get("converged"):
            return st
        time.sleep(0.05)
    raise AssertionError(f"session {sid} never converged")


# ------------------------------------------------------------------ #
# engine mutation ladder


class TestMutationLadder:
    def test_remove_then_add_reuses_name_and_slack_row(self):
        rng = np.random.default_rng(1)
        eng = build_dynamic_engine(_ring(8, 1), {"noise": 0.0})
        eng.run(max_cycles=300)
        before = eng.recompile_count
        old_slot = eng.slots["c3"]
        eng.remove_factor("c3")
        assert "c3" not in eng.slots
        scope = [eng.variables[eng.var_index[n]]
                 for n in ("v3", "v4")]
        eng.add_factor(NAryMatrixRelation(scope, _table(rng), "c3"))
        assert eng.recompile_count == before, \
            "name-reuse add_factor must take a slack row, not " \
            "recompile"
        assert eng.slots["c3"][0] == old_slot[0]
        res = eng.run(max_cycles=300)
        assert res.converged
        assert res.metrics["recompiles"] == 0

    def test_add_factor_past_slack_budget_recompiles(self):
        rng = np.random.default_rng(2)
        eng = build_dynamic_engine(_ring(8, 2),
                                   {"noise": 0.0, "slack": 0.0})
        eng.run(max_cycles=200)
        # slack=0 still leaves >= 1 spare row (the +1 floor); burn
        # the free list, then one more forces the recompile path.
        bi = eng._arity_bucket[2]
        free = len(eng._free[bi])
        before = eng.recompile_count
        for i in range(free + 1):
            a, b = eng.variables[i], eng.variables[(i + 3) % 8]
            eng.add_factor(NAryMatrixRelation(
                [a, b], _table(rng), f"extra{i}"))
        assert eng.recompile_count == before + 1, \
            "only the past-slack add may recompile"
        res = eng.run(max_cycles=300)
        assert res.converged

    def test_add_variable_recompiles_carrying_messages(self):
        rng = np.random.default_rng(3)
        eng = build_dynamic_engine(_ring(8, 3), {"noise": 0.0})
        first = eng.run(max_cycles=300)
        assert first.converged
        cycle_before = int(first.cycles)
        before = eng.recompile_count
        new_var = Variable("v8", Domain("d", "", [0, 1, 2]))
        eng.add_variable(new_var)
        assert eng.recompile_count == before + 1
        # Warm carry-over: the trajectory continues, it does not
        # restart at cycle 0.
        res = eng.run(max_cycles=300)
        assert res.cycles > cycle_before
        anchor = eng.variables[eng.var_index["v0"]]
        eng.add_factor(NAryMatrixRelation(
            [anchor, new_var], _table(rng), "tie"))
        res = eng.run(max_cycles=300)
        assert res.converged
        assert "v8" in res.assignment

    def test_checkpoint_restore_mid_mutation_equals_uninterrupted(
            self, tmp_path):
        rng = np.random.default_rng(4)
        t1, t2 = _table(rng), _table(rng)
        base = _ring(10, 4)

        def run_a():
            eng = build_dynamic_engine(base, {"noise": 0.0})
            eng.run(max_cycles=300)
            eng.change_factor("c2", NAryMatrixRelation(
                list(eng.factors["c2"].dimensions), t1, "c2"))
            eng.run(max_cycles=300)
            return eng

        uninterrupted = run_a()
        path = str(tmp_path / "mid.npz")
        uninterrupted.checkpoint(path)
        uninterrupted.change_factor("c5", NAryMatrixRelation(
            list(uninterrupted.factors["c5"].dimensions), t2, "c5"))
        final_a = uninterrupted.run(max_cycles=300)

        # Interrupted twin: rebuild, re-apply the pre-checkpoint
        # mutation structurally, restore the snapshot, continue.
        eng_b = build_dynamic_engine(base, {"noise": 0.0})
        eng_b.change_factor("c2", NAryMatrixRelation(
            list(eng_b.factors["c2"].dimensions), t1, "c2"))
        eng_b.restore(path)
        eng_b.change_factor("c5", NAryMatrixRelation(
            list(eng_b.factors["c5"].dimensions), t2, "c5"))
        final_b = eng_b.run(max_cycles=300)
        assert final_a.assignment == final_b.assignment
        assert uninterrupted.cost(final_a.assignment) == \
            eng_b.cost(final_b.assignment)

    def test_restore_rejects_mismatched_factor_set(self, tmp_path):
        eng = build_dynamic_engine(_ring(8, 5), {"noise": 0.0})
        eng.run(max_cycles=100)
        path = str(tmp_path / "ck.npz")
        eng.checkpoint(path)
        eng.remove_factor("c1")
        with pytest.raises(ValueError, match="only in checkpoint"):
            eng.restore(path)


# ------------------------------------------------------------------ #
# decimation clamps


class TestDecimationClamps:
    def test_clamp_pins_variable_through_the_solve(self):
        eng = build_dynamic_engine(_ring(8, 6), {"noise": 0.0})
        eng.run(max_cycles=200)
        eng.clamp_variables({"v2": 1})
        res = eng.run(max_cycles=200)
        assert res.assignment["v2"] == \
            eng.variables[eng.var_index["v2"]].domain[1]

    def test_release_touched_only(self):
        rng = np.random.default_rng(7)
        eng = build_dynamic_engine(_ring(8, 7), {"noise": 0.0})
        eng.run(max_cycles=300)
        clamped = eng.decimate(margin=0.0, max_fraction=1.0)
        assert clamped, "decimate clamped nothing on a converged run"
        info = apply_action(eng, "change_factor", {
            "name": "c0", "table": _table(rng).tolist()})
        released = eng.release_clamps(info["touched"])
        assert set(released) == set(info["touched"]) & set(clamped)
        still = set(clamped) - set(info["touched"])
        assert still <= set(eng.clamps), \
            "untouched clamps must survive the event"
        for name in info["touched"]:
            assert name not in eng.clamps

    def test_clamps_survive_recompile(self):
        eng = build_dynamic_engine(_ring(8, 8), {"noise": 0.0})
        eng.run(max_cycles=200)
        eng.clamp_variables({"v1": 2})
        eng.add_variable(Variable("v8", Domain("d", "", [0, 1, 2])))
        assert "v1" in eng.clamps
        res = eng.run(max_cycles=200)
        assert res.assignment["v1"] == \
            eng.variables[eng.var_index["v1"]].domain[2]

    def test_clamp_validation_is_all_or_nothing(self):
        eng = build_dynamic_engine(_ring(8, 58), {"noise": 0.0})
        eng.run(max_cycles=100)
        with pytest.raises(ValueError, match="out of domain"):
            eng.clamp_variables({"v0": 1, "v1": 99})
        assert eng.clamps == {}, \
            "a rejected mapping must not record partial clamps"

    def test_cost_skips_hard_violations_like_solution_cost(self):
        dom = Domain("c", "", [0, 1])
        dcop = DCOP("hardcost", objective="min")
        a, b = Variable("a", dom), Variable("b", dom)
        dcop.add_variable(a)
        dcop.add_variable(b)
        hard = np.array([[float("inf"), 1.0], [1.0, 2.0]])
        dcop.add_constraint(NAryMatrixRelation([a, b], hard, "h"))
        dcop.add_agents([AgentDef("a0")])
        eng = build_dynamic_engine(dcop, {"noise": 0.0})
        # The violated-hard assignment: cost finite (inf skipped —
        # the DCOP.solution_cost convention), so session JSON/SSE
        # surfaces never carry an unserializable Infinity.
        assert eng.cost({"a": 0, "b": 0}) == 0.0
        assert eng.cost({"a": 0, "b": 1}) == 1.0
        ref_cost, _viol = dcop.solution_cost({"a": 0, "b": 0})
        assert eng.cost({"a": 0, "b": 0}) == ref_cost

    def test_beliefs_shape_and_clamp_bias(self):
        eng = build_dynamic_engine(_ring(8, 9), {"noise": 0.0})
        eng.run(max_cycles=100)
        bel = eng.beliefs()
        assert bel.shape == (8, 3)
        eng.clamp_variables({"v0": 0})
        bel = eng.beliefs()
        assert np.argmin(bel[0]) == 0


# ------------------------------------------------------------------ #
# acceptance: zero recompiles + cost parity with a fresh solve


class TestInShapeParityAcceptance:
    def test_events_zero_recompiles_and_fresh_solve_cost_parity(self):
        """ISSUE-13 acceptance: five in-shape change_factor events
        through a real session — every one applies with ZERO
        recompiles (the ``recompiles`` metric) and the post-event
        session assignment is cost-equivalent (≤ 1e-6 rel) to a
        fresh ``api.solve`` of the mutated problem on integer
        tables — equivalent OR BETTER: a cold max-sum start can land
        in a worse fixpoint than the warm one (measured: fresh 21 vs
        warm 15 on a seeded tree), so the warm session is
        additionally held to the EXACT (DPOP) optimum, the stronger
        bound that makes 'better' checkable rather than a shrug."""
        rng = np.random.default_rng(10)
        svc = _service().start()
        try:
            sess = svc.sessions.open(_path(12, 10),
                                     params=PARITY_PARAMS,
                                     session_id="parity")
            for i in range(5):
                out = svc.sessions.apply_events("parity", [{
                    "type": "change_factor",
                    "name": f"c{int(rng.integers(11))}",
                    "table": _table(rng).tolist(),
                }], wait=30.0)
                assert out["applied"] is True
                assert out["recompiles"] == 0, \
                    "in-shape event must not recompile"
                st = _wait_converged(svc, "parity")
                session_cost = st["last"]["cost"]
                fresh = _fresh_cost(sess.engine)
                exact = _exact_cost(sess.engine)
                tol = 1e-6 * max(1.0, abs(fresh))
                assert session_cost <= fresh + tol, \
                    f"event {i}: session {session_cost} worse than " \
                    f"fresh {fresh}"
                assert session_cost == pytest.approx(exact), \
                    f"event {i}: session {session_cost} != exact " \
                    f"{exact}"
            final = svc.sessions.close("parity")
            assert final["recompiles"] == 0
            assert final["event_batches"] == 5
        finally:
            svc.stop(drain=False)

    def test_growth_event_recompiles_and_still_matches(self):
        """The re-key path: add_variable + a tying factor recompiles
        exactly once, carries messages, and the re-converged session
        still matches a fresh solve of the grown problem."""
        rng = np.random.default_rng(11)
        svc = _service().start()
        try:
            sess = svc.sessions.open(_path(10, 11),
                                     params=PARITY_PARAMS,
                                     session_id="grow")
            out = svc.sessions.apply_events("grow", [
                {"type": "add_variable", "name": "nv",
                 "domain": [0, 1, 2]},
                {"type": "add_factor", "name": "nc",
                 "variables": ["v9", "nv"],
                 "table": _table(rng).tolist()},
            ], wait=30.0)
            assert out["applied"] is True
            assert out["recompiles"] == 1
            st = _wait_converged(svc, "grow")
            fresh = _fresh_cost(sess.engine)
            tol = 1e-6 * max(1.0, abs(fresh))
            assert st["last"]["cost"] <= fresh + tol
            assert st["last"]["cost"] == pytest.approx(
                _exact_cost(sess.engine))
        finally:
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# session service, in-process


class TestSessionService:
    def test_open_events_close_lifecycle(self):
        rng = np.random.default_rng(12)
        svc = _service().start()
        try:
            svc.sessions.open(_ring(8, 12), params={"noise": 0.0},
                              session_id="life")
            st = _wait_converged(svc, "life")
            assert st["status"] == "OPEN"
            out = svc.sessions.apply_events("life", [{
                "type": "change_factor", "name": "c1",
                "table": _table(rng).tolist()}], wait=30.0)
            assert out["seq"] == 1 and out["applied"] is True
            assert out["result"]["cost"] is not None
            final = svc.sessions.close("life")
            assert final["status"] == "CLOSED"
            assert final["event_batches"] == 1
            assert final["events_applied"] == 1
            # Idempotent close.
            again = svc.sessions.close("life")
            assert again == final
            stats = svc.stats()["sessions"]
            assert stats["opened"] == 1 and stats["closed"] == 1
            assert stats["active"] == 0
        finally:
            svc.stop(drain=False)

    def test_wire_validation_rejects_malformed_batches(self):
        with pytest.raises(ValueError, match="non-empty"):
            validate_events([])
        with pytest.raises(ValueError, match="unknown type"):
            validate_events([{"type": "explode"}])
        with pytest.raises(ValueError, match="'table' or an"):
            validate_events([{"type": "change_factor", "name": "c"}])
        with pytest.raises(ValueError, match="'domain'"):
            validate_events([{"type": "add_variable", "name": "x"}])
        with pytest.raises(ValueError, match="'agent'"):
            validate_events([{"type": "remove_agent"}])

    def test_semantic_event_error_fails_batch_not_session(self):
        rng = np.random.default_rng(13)
        svc = _service().start()
        try:
            svc.sessions.open(_ring(8, 13), params={"noise": 0.0},
                              session_id="sem")
            out = svc.sessions.apply_events("sem", [{
                "type": "change_factor", "name": "no_such",
                "table": _table(rng).tolist()}], wait=30.0)
            assert out["applied"] is False
            assert "error" in out
            # The session survives and keeps serving.
            st = svc.sessions.status("sem")
            assert st["status"] == "OPEN"
            out = svc.sessions.apply_events("sem", [{
                "type": "change_factor", "name": "c0",
                "table": _table(rng).tolist()}], wait=30.0)
            assert out["applied"] is True
        finally:
            svc.stop(drain=False)

    def test_failed_batch_still_serves_fresh_state(self):
        """A batch whose second action fails semantically has its
        FIRST action live in the engine: the post-batch segment must
        still run so the session never serves the stale pre-event
        assignment (review regression)."""
        rng = np.random.default_rng(61)
        svc = _service().start()
        try:
            sess = svc.sessions.open(_ring(8, 61),
                                     params={"noise": 0.0},
                                     session_id="partial")
            out = svc.sessions.apply_events("partial", [
                {"type": "change_factor", "name": "c0",
                 "table": _table(rng).tolist()},
                {"type": "change_factor", "name": "no_such",
                 "table": _table(rng).tolist()},
            ], wait=30.0)
            assert out["applied"] is False and "error" in out
            # The partial batch still produced a segment result
            # computed AFTER c0's new table landed.
            assert out["result"] is not None
            assert out["result"]["batch_seq"] == 1
            assert sess.events_applied == 1
        finally:
            svc.stop(drain=False)

    def test_terminal_sessions_evicted_past_session_keep(self):
        svc = _service(session_keep=2).start()
        try:
            for i in range(4):
                svc.sessions.open(_ring(6, 70 + i),
                                  params={"noise": 0.0},
                                  session_id=f"evict{i}")
                svc.sessions.close(f"evict{i}")
            with pytest.raises(KeyError):
                svc.sessions.status("evict0")
            # Newest terminal results stay pollable.
            assert svc.sessions.status("evict3")["status"] == "CLOSED"
            with svc.sessions._lock:
                assert len(svc.sessions._sessions) <= 2
        finally:
            svc.stop(drain=False)

    def test_open_limit_is_atomic_under_concurrent_opens(self):
        svc = _service(session_max=3).start()
        try:
            opened, rejected = [], []
            lock = threading.Lock()

            def worker(i):
                try:
                    sess = svc.sessions.open(
                        _ring(6, 80 + i), params={"noise": 0.0})
                    with lock:
                        opened.append(sess.id)
                except SessionLimit:
                    with lock:
                        rejected.append(i)

            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=30)
            assert len(opened) == 3 and len(rejected) == 5, \
                (opened, rejected)
            assert svc.sessions.active_count() == 3
        finally:
            svc.stop(drain=False)

    def test_session_limit_and_unknown_ids(self):
        svc = _service(session_max=1).start()
        try:
            svc.sessions.open(_ring(6, 14), params={"noise": 0.0})
            with pytest.raises(SessionLimit):
                svc.sessions.open(_ring(6, 15),
                                  params={"noise": 0.0})
            with pytest.raises(KeyError):
                svc.sessions.status("ghost")
            with pytest.raises(KeyError):
                svc.sessions.apply_events("ghost", [
                    {"type": "remove_factor", "name": "c0"}])
        finally:
            svc.stop(drain=False)

    def test_events_against_closed_session_409(self):
        rng = np.random.default_rng(16)
        svc = _service().start()
        try:
            svc.sessions.open(_ring(6, 16), params={"noise": 0.0},
                              session_id="done")
            svc.sessions.close("done")
            with pytest.raises(SessionClosed):
                svc.sessions.apply_events("done", [{
                    "type": "change_factor", "name": "c0",
                    "table": _table(rng).tolist()}])
        finally:
            svc.stop(drain=False)

    def test_scenario_yaml_spelling(self):
        from pydcop_tpu.dcop.yamldcop import yaml_scenario
        from pydcop_tpu.generators.scenario_gen import (
            generate_factor_scenario,
        )

        dcop = _ring(8, 17)
        scenario = generate_factor_scenario(dcop, 4, seed=17)
        events = scenario_yaml_to_events(yaml_scenario(scenario))
        assert events, "flattened scenario lost its actions"
        assert validate_events(events) == events
        svc = _service().start()
        try:
            svc.sessions.open(dcop, params={"noise": 0.0},
                              session_id="scen")
            out = svc.sessions.apply_events("scen", events,
                                            wait=30.0)
            assert out["applied"] is True
            assert out["events"] == len(events)
        finally:
            svc.stop(drain=False)

    def test_param_normalization_rejects_junk(self):
        with pytest.raises(ValueError, match="unknown session"):
            normalize_session_params({"frobnicate": 1})
        with pytest.raises(ValueError, match="bad session"):
            normalize_session_params({"damping": "high"})
        with pytest.raises(ValueError, match="positive"):
            normalize_session_params({"segment_cycles": 0})
        params = normalize_session_params(
            {"decimation_margin": "1.5"})
        assert params["decimation_margin"] == 1.5
        # margin <= 0 is the knob's documented OFF value (same
        # contract as maxsum decimation_plan_from_params) — it must
        # not flip to clamp-everything on the session surface.
        assert normalize_session_params(
            {"decimation_margin": 0.0})["decimation_margin"] is None
        assert normalize_session_params(
            {"decimation_margin": -1})["decimation_margin"] is None

    def test_decimation_session_clamps_and_event_releases(self):
        rng = np.random.default_rng(18)
        svc = _service().start()
        try:
            sess = svc.sessions.open(
                _ring(10, 18),
                params={"noise": 0.0, "decimation_margin": 0.5},
                session_id="dec")
            _wait_converged(svc, "dec")
            deadline = time.monotonic() + 10
            while not sess.engine.clamps \
                    and time.monotonic() < deadline:
                time.sleep(0.05)
            assert sess.engine.clamps, \
                "converged decimation session never clamped"
            before = dict(sess.engine.clamps)
            out = svc.sessions.apply_events("dec", [{
                "type": "change_factor", "name": "c0",
                "table": _table(rng).tolist()}], wait=30.0)
            assert out["applied"] is True
            touched = {"v0", "v1"}
            for name in touched:
                assert name not in sess.engine.clamps or \
                    name not in before, \
                    "touched clamp survived the event"
        finally:
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# HTTP wire


class TestSessionHTTP:
    def _request(self, url, method="GET", body=None, timeout=30):
        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(
            url, data=data, method=method,
            headers={"Content-Type": "application/json"}
            if data else {})
        try:
            with urllib.request.urlopen(req, timeout=timeout) as r:
                return r.status, json.loads(r.read())
        except urllib.error.HTTPError as err:
            return err.code, json.loads(err.read())

    def test_full_wire_lifecycle_with_sse(self):
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        rng = np.random.default_rng(19)
        handle = api.serve(port=0, batch_window_s=0.02)
        url = handle.url
        try:
            code, ack = self._request(
                url + "/session", "POST",
                {"dcop": dcop_yaml(_ring(8, 19)),
                 "params": {"noise": 0.0, "max_cycles": 300}})
            assert code == 201 and ack["session_id"]
            sid, tid = ack["session_id"], ack["trace_id"]
            assert tid

            events = []
            stream_done = threading.Event()

            def reader():
                try:
                    with urllib.request.urlopen(
                            url + f"/session/{sid}/events",
                            timeout=60) as r:
                        for line in r:
                            if line.startswith(b"data: "):
                                events.append(json.loads(line[6:]))
                                if events[-1].get("status") in (
                                        "CLOSED", "ERROR"):
                                    break
                finally:
                    stream_done.set()

            t = threading.Thread(target=reader, daemon=True)
            t.start()
            code, out = self._request(
                url + f"/session/{sid}/events", "PATCH",
                {"events": [{"type": "change_factor", "name": "c0",
                             "table": _table(rng).tolist()}],
                 "wait": True})
            assert code == 200 and out["applied"] is True
            assert out["recompiles"] == 0
            code, st = self._request(url + f"/session/{sid}")
            assert code == 200 and st["seq"] == 1
            code, final = self._request(
                url + f"/session/{sid}", "DELETE")
            assert code == 200 and final["status"] == "CLOSED"
            assert stream_done.wait(20), "SSE stream never ended"
            phases = {e.get("phase") for e in events}
            assert "segment" in phases and "closed" in phases
            assert any("assignment" in e for e in events
                       if e.get("phase") == "segment"), \
                "SSE segments must carry anytime assignments"
        finally:
            handle.stop()

    def test_wire_error_surfaces(self):
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        handle = api.serve(port=0, batch_window_s=0.02)
        url = handle.url
        try:
            code, _ = self._request(url + "/session/ghost")
            assert code == 404
            code, _ = self._request(url + "/session/ghost", "DELETE")
            assert code == 404
            code, _ = self._request(
                url + "/session/ghost/events", "PATCH",
                {"events": [{"type": "remove_factor",
                             "name": "c0"}]})
            assert code == 404
            code, _ = self._request(url + "/session", "POST",
                                    {"dcop": "   "})
            assert code == 400
            code, ack = self._request(
                url + "/session", "POST",
                {"dcop": dcop_yaml(_ring(6, 20)),
                 "params": {"noise": 0.0}})
            assert code == 201
            sid = ack["session_id"]
            code, _ = self._request(
                url + f"/session/{sid}/events", "PATCH",
                {"events": [{"type": "explode"}]})
            assert code == 400
            # Malformed scenario yaml is a 400 'bad events', never a
            # 404 — the loader's KeyError must not masquerade as an
            # unknown session (review regression).
            code, body = self._request(
                url + f"/session/{sid}/events", "PATCH",
                {"scenario": "events:\n - actions:\n    - name: c1"})
            assert code == 400, (code, body)
            assert "bad events" in body["error"]
            code, _ = self._request(
                url + f"/session/{sid}", "DELETE")
            assert code == 200
        finally:
            handle.stop()


# ------------------------------------------------------------------ #
# journal records + crash replay


class TestSessionJournal:
    def test_pending_sessions_bookkeeping(self):
        records = [
            session_open_record("a", "yaml-a", {}),
            session_event_record("a", 1, [{"type": "x"}]),
            session_open_record("b", "yaml-b", {}),
            session_ckpt_record("a", 1, "/p/a.npz", cycle=40),
            session_event_record("a", 2, [{"type": "y"}]),
            session_close_record("b", "CLOSED"),
        ]
        pending = pending_sessions(records)
        assert [p["open"]["id"] for p in pending] == ["a"]
        (sess,) = pending
        assert sess["ckpt"]["seq"] == 1
        # Events at AND past the checkpoint seq both survive: the
        # pre-ckpt ones rebuild the factor layout structurally.
        assert [r["seq"] for r in sess["events"]] == [1, 2]

    def test_newest_checkpoint_wins(self):
        records = [
            session_open_record("a", "y", {}),
            session_ckpt_record("a", 1, "/p/1.npz"),
            session_ckpt_record("a", 3, "/p/3.npz"),
            session_ckpt_record("a", 2, "/p/2.npz"),
        ]
        (sess,) = pending_sessions(records)
        assert sess["ckpt"]["seq"] == 3

    def test_compaction_preserves_open_drops_closed(self, tmp_path):
        journal_dir = str(tmp_path)
        jnl = journal_mod.RequestJournal(journal_dir)
        jnl.append(session_open_record("keep", "y1", {}))
        jnl.append(session_event_record("keep", 1, [{"type": "t"}]))
        jnl.append(session_open_record("gone", "y2", {}))
        jnl.append(session_close_record("gone", "CLOSED"))
        jnl.close()
        jnl2, pending, sessions, _results = \
            journal_mod.RequestJournal.recover_full(journal_dir)
        jnl2.close()
        assert pending == []
        assert [s["open"]["id"] for s in sessions] == ["keep"]
        records, _, torn = scan_journal(jnl2.path)
        assert not torn
        assert [(r["kind"], r["id"]) for r in records] == [
            ("session_open", "keep"), ("session_event", "keep")]

    def test_crash_replay_equals_uninterrupted(self):
        """The ISSUE-13 crash acceptance, in-process: a journaled
        session absorbs 3 event batches, the process 'dies' (the
        scheduler is killed and the journal handle slammed shut with
        no close record), and a recover=True start resumes the
        session, applies nothing twice, and lands on exactly the
        uninterrupted run's final cost."""
        import tempfile

        rng = np.random.default_rng(21)
        tables = [_table(rng).tolist() for _ in range(3)]
        journal_dir = tempfile.mkdtemp(prefix="sess_battery_")
        svc = _service(journal_dir=journal_dir).start()
        svc.sessions.open(_path(10, 21), params=PARITY_PARAMS,
                          session_id="crash")
        for i, tb in enumerate(tables):
            out = svc.sessions.apply_events("crash", [{
                "type": "change_factor", "name": f"c{i}",
                "table": tb}], wait=30.0)
            assert out["applied"] is True
        st = _wait_converged(svc, "crash")
        uninterrupted = st["last"]["cost"]
        # kill -9 equivalent: no close record, no park, no drain.
        svc._scheduler.shutdown(timeout=10)
        svc._journal._f.close()

        svc2 = _service(journal_dir=journal_dir,
                        recover=True).start()
        try:
            st = svc2.sessions.status("crash")
            assert st["replayed"] is True
            assert st["seq"] == 3 and st["applied_seq"] == 3
            st = _wait_converged(svc2, "crash")
            assert st["last"]["cost"] == uninterrupted
            final = svc2.sessions.close("crash")
            assert final["cost"] == uninterrupted
        finally:
            svc2.stop(drain=False)
        # Closed is closed: a third recover must not resurrect it.
        svc3 = _service(journal_dir=journal_dir,
                        recover=True).start()
        try:
            with pytest.raises(KeyError):
                svc3.sessions.status("crash")
        finally:
            svc3.stop(drain=False)

    def test_checkpointed_recovery_restores_warm_state(self):
        import tempfile

        rng = np.random.default_rng(22)
        journal_dir = tempfile.mkdtemp(prefix="sess_ck_battery_")
        svc = _service(journal_dir=journal_dir,
                       session_checkpoint_every_events=1).start()
        svc.sessions.open(_path(10, 22), params=PARITY_PARAMS,
                          session_id="warm")
        for i in range(2):
            svc.sessions.apply_events("warm", [{
                "type": "change_factor", "name": f"c{i}",
                "table": _table(rng).tolist()}], wait=30.0)
        st = _wait_converged(svc, "warm")
        expected = st["last"]["cost"]
        ckpt = os.path.join(journal_dir, "session_warm.npz")
        assert os.path.exists(ckpt), "per-event checkpoint missing"
        kinds = [r["kind"] for r in
                 scan_journal(svc._journal.path)[0]]
        assert kinds.count("session_ckpt") >= 2
        svc._scheduler.shutdown(timeout=10)
        svc._journal._f.close()

        svc2 = _service(journal_dir=journal_dir,
                        recover=True).start()
        try:
            sess = svc2.sessions._sessions["warm"]
            # The restored engine starts from the checkpointed
            # cycle count, not from zero.
            assert sess.last_cycle > 0, \
                "recovery ignored the engine-state checkpoint"
            st = _wait_converged(svc2, "warm")
            assert st["last"]["cost"] == expected
        finally:
            svc2.stop(drain=False)

    def test_graceful_park_then_recover(self):
        import tempfile

        journal_dir = tempfile.mkdtemp(prefix="sess_park_")
        svc = _service(journal_dir=journal_dir).start()
        svc.sessions.open(_ring(8, 23), params={"noise": 0.0},
                          session_id="park")
        _wait_converged(svc, "park")
        summary = svc.stop()
        assert summary["parked_sessions"] == 1
        st = svc.sessions.status("park")
        assert st["status"] == "REPLAYABLE"
        svc2 = _service(journal_dir=journal_dir,
                        recover=True).start()
        try:
            st = svc2.sessions.status("park")
            assert st["status"] == "OPEN" and st["replayed"]
            final = svc2.sessions.close("park")
            assert final["status"] == "CLOSED"
        finally:
            svc2.stop(drain=False)

    def test_journal_less_stop_fails_open_sessions(self):
        svc = _service().start()
        svc.sessions.open(_ring(6, 24), params={"noise": 0.0},
                          session_id="lost")
        _wait_converged(svc, "lost")
        summary = svc.stop()
        assert summary["parked_sessions"] == 1
        st = svc.sessions.status("lost")
        assert st["status"] == "ERROR"

    def test_replay_tolerates_failed_batch_like_live(self):
        """A batch that failed semantically in live operation (acked,
        journaled, batch-scoped error) must fail IDENTICALLY on
        crash replay — earlier actions stand, later acked batches
        still apply, and the recovered final equals the
        uninterrupted run (review regression: replay used to abort
        the whole session at the first bad batch)."""
        import tempfile

        rng = np.random.default_rng(62)
        good1 = _table(rng).tolist()
        good2 = _table(rng).tolist()
        journal_dir = tempfile.mkdtemp(prefix="sess_tol_")
        svc = _service(journal_dir=journal_dir).start()
        svc.sessions.open(_path(10, 62), params=PARITY_PARAMS,
                          session_id="tol")
        out = svc.sessions.apply_events("tol", [{
            "type": "change_factor", "name": "c0",
            "table": good1}], wait=30.0)
        assert out["applied"] is True
        out = svc.sessions.apply_events("tol", [{
            "type": "change_factor", "name": "ghost",
            "table": good1}], wait=30.0)
        assert out["applied"] is False
        out = svc.sessions.apply_events("tol", [{
            "type": "change_factor", "name": "c1",
            "table": good2}], wait=30.0)
        assert out["applied"] is True
        st = _wait_converged(svc, "tol")
        uninterrupted = st["last"]["cost"]
        svc._scheduler.shutdown(timeout=10)
        svc._journal._f.close()

        svc2 = _service(journal_dir=journal_dir,
                        recover=True).start()
        try:
            st = svc2.sessions.status("tol")
            assert st["status"] == "OPEN", \
                "failed batch aborted the whole session replay"
            assert st["applied_seq"] == 3
            st = _wait_converged(svc2, "tol")
            assert st["last"]["cost"] == uninterrupted
        finally:
            svc2.stop(drain=False)

    def test_concurrent_patches_journal_in_seq_order(self):
        """Racing PATCH threads must reach the journal in seq order
        (review regression: seq was assigned under the lock but
        journaled outside it, so replay order could diverge from
        live apply order) — and the recovered state must equal the
        crashed process's."""
        import tempfile

        rng = np.random.default_rng(63)
        tables = [_table(rng).tolist() for _ in range(6)]
        journal_dir = tempfile.mkdtemp(prefix="sess_race_")
        svc = _service(journal_dir=journal_dir).start()
        svc.sessions.open(_path(10, 63), params=PARITY_PARAMS,
                          session_id="race")
        threads = [
            threading.Thread(
                target=svc.sessions.apply_events,
                args=("race", [{"type": "change_factor",
                                "name": f"c{i}",
                                "table": tables[i]}]))
            for i in range(6)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        records, _, _ = scan_journal(svc._journal.path)
        seqs = [r["seq"] for r in records
                if r["kind"] == "session_event"]
        assert seqs == sorted(seqs) and len(set(seqs)) == 6, \
            f"journal seq order broken: {seqs}"
        st = _wait_converged(svc, "race")
        live_cost = st["last"]["cost"]
        svc._scheduler.shutdown(timeout=10)
        svc._journal._f.close()
        svc2 = _service(journal_dir=journal_dir,
                        recover=True).start()
        try:
            st = _wait_converged(svc2, "race")
            assert st["last"]["cost"] == live_cost
        finally:
            svc2.stop(drain=False)

    def test_patch_ack_is_durable_before_return(self):
        import tempfile

        rng = np.random.default_rng(25)
        journal_dir = tempfile.mkdtemp(prefix="sess_dur_")
        svc = _service(journal_dir=journal_dir).start()
        try:
            svc.sessions.open(_ring(8, 25), params={"noise": 0.0},
                              session_id="dur")
            svc.sessions.apply_events("dur", [{
                "type": "change_factor", "name": "c0",
                "table": _table(rng).tolist()}])
            # No wait: the record must ALREADY be on disk when
            # apply_events returned, applied or not.
            records, _, _ = scan_journal(svc._journal.path)
            kinds = [r["kind"] for r in records]
            assert "session_event" in kinds
        finally:
            svc.stop(drain=False)


# ------------------------------------------------------------------ #
# tracing


class TestSessionTracing:
    def test_session_tree_is_queryable_by_trace_id(self):
        from pydcop_tpu.observability.trace import query_request

        rng = np.random.default_rng(26)
        tracer.enable()
        svc = _service().start()
        try:
            sess = svc.sessions.open(_ring(8, 26),
                                     params={"noise": 0.0},
                                     session_id="traced")
            svc.sessions.apply_events("traced", [{
                "type": "change_factor", "name": "c0",
                "table": _table(rng).tolist()}], wait=30.0)
            svc.sessions.close("traced")
            events = tracer.events()
            tid = sess.trace_id
        finally:
            svc.stop(drain=False)
            tracer.disable()
        tree = query_request(events, tid)
        assert tree["events"] > 0
        names = set(tree["names"])
        assert {"session_open", "session_events",
                "session_segment"} <= names, names

        def _flat(nodes):
            for node in nodes:
                yield node
                yield from _flat(node["children"])

        for node in _flat(tree["tree"]):
            args = node["args"]
            assert (args.get("trace_id") == tid
                    or tid in (args.get("trace_ids") or [])), \
                f"{node['name']} span missing the session tag"

    def test_event_batch_has_its_own_queryable_id(self):
        from pydcop_tpu.observability.trace import query_request

        rng = np.random.default_rng(27)
        tracer.enable()
        svc = _service().start()
        try:
            svc.sessions.open(_ring(8, 27), params={"noise": 0.0},
                              session_id="batchtid")
            out = svc.sessions.apply_events("batchtid", [{
                "type": "change_factor", "name": "c0",
                "table": _table(rng).tolist()}], wait=30.0)
            events = tracer.events()
        finally:
            svc.stop(drain=False)
            tracer.disable()
        tree = query_request(events, out["trace_id"])
        assert "session_events" in tree["names"]


# ------------------------------------------------------------------ #
# scenario replay (the --scenario machinery)


class TestScenarioReplay:
    def test_generated_factor_scenario_replays(self):
        from pydcop_tpu.generators.scenario_gen import (
            generate_factor_scenario,
        )

        dcop = _ring(10, 28)
        scenario = generate_factor_scenario(dcop, 8, seed=28)
        out = replay_scenario(dcop, scenario,
                              params={"noise": 0.0},
                              max_cycles=300)
        assert out["event_count"] == 8
        assert len(out["events"]) == 8
        # In-shape events never recompile; only grow events may.
        for rec in out["events"]:
            if set(rec["actions"]) <= {"change_factor",
                                       "remove_factor"}:
                assert rec["recompiles"] == 0, rec
        assert np.isfinite(out["cost"])
        # Every original variable (plus any grown ones) is assigned.
        assert set(v for v in dcop.variables) <= \
            set(out["assignment"])

    def test_agent_removal_scenario_re_homes(self):
        from pydcop_tpu.dcop.scenario import (
            DcopEvent,
            EventAction,
            Scenario,
        )

        dcop = _ring(8, 29)
        scenario = Scenario([
            DcopEvent("e0", actions=[
                EventAction("remove_agent", agent="a1")]),
            DcopEvent("d0", delay=5.0),
        ])
        out = replay_scenario(dcop, scenario,
                              params={"noise": 0.0},
                              max_cycles=200)
        assert out["orphaned"] == []
        assert out["converged"]

    def test_all_agents_removed_orphans_not_crashes(self):
        from pydcop_tpu.dcop.scenario import (
            DcopEvent,
            EventAction,
            Scenario,
        )

        dcop = _ring(6, 30)
        scenario = Scenario([
            DcopEvent("e0", actions=[
                EventAction("remove_agent", agent="a0"),
                EventAction("remove_agent", agent="a1")]),
        ])
        out = replay_scenario(dcop, scenario,
                              params={"noise": 0.0},
                              max_cycles=200)
        assert out["orphaned"], \
            "orphaned computations must be reported"
        assert out["converged"]

    def test_removed_hard_constraint_not_counted_as_violation(self):
        """A hard (inf) constraint the scenario removes no longer
        binds the solution: the replay's violation count must come
        from the LIVE factor set, not the original problem's tables
        (review regression)."""
        from pydcop_tpu.dcop.scenario import (
            DcopEvent,
            EventAction,
            Scenario,
        )

        dom = Domain("c", "", [0, 1])
        dcop = DCOP("hard", objective="min")
        a, b = Variable("a", dom), Variable("b", dom)
        dcop.add_variable(a)
        dcop.add_variable(b)
        # Hard: a and b must differ.  Soft: both prefer value 0.
        hard = np.array([[float("inf"), 0.0], [0.0, float("inf")]])
        dcop.add_constraint(NAryMatrixRelation([a, b], hard, "hard"))
        dcop.add_constraint(NAryMatrixRelation(
            [a, b], np.array([[0.0, 1.0], [1.0, 2.0]]), "soft"))
        dcop.add_agents([AgentDef("a0")])
        scenario = Scenario([DcopEvent("e0", actions=[
            EventAction("remove_factor", name="hard")])])
        out = replay_scenario(dcop, scenario, params={"noise": 0.0},
                              max_cycles=200)
        # Without the hard constraint, (0, 0) is optimal — it would
        # violate the REMOVED constraint, and must not count.
        assert out["assignment"] == {"a": 0, "b": 0}
        assert out["violations"] == 0
        assert out["factors"] == ["soft"]

    def test_scenario_yaml_round_trip(self):
        from pydcop_tpu.dcop.yamldcop import (
            load_scenario,
            yaml_scenario,
        )
        from pydcop_tpu.generators.scenario_gen import (
            generate_factor_scenario,
        )

        dcop = _ring(8, 31)
        scenario = generate_factor_scenario(dcop, 5, seed=31)
        loaded = load_scenario(yaml_scenario(scenario))
        assert len(loaded) == len(scenario)
        out = replay_scenario(dcop, loaded, params={"noise": 0.0},
                              max_cycles=200)
        assert out["event_count"] == 5


# ------------------------------------------------------------------ #
# sentinel: session families


class TestSessionSentinelFamilies:
    def _sentinel(self):
        import sys

        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), "tools"))
        import bench_sentinel

        return bench_sentinel

    def _write(self, root, ttr, eps):
        for i, (t, e) in enumerate(zip(ttr, eps)):
            doc = {"n": i, "parsed": {
                "value": 800.0 + i, "backend": "cpu",
                "session_time_to_recovered_cost_ms": t,
                "session_events_per_sec": e,
            }}
            with open(os.path.join(
                    root, f"BENCH_r{i:02d}.json"), "w") as f:
                json.dump(doc, f)

    def test_session_families_ok(self, tmp_path):
        bench_sentinel = self._sentinel()
        d = str(tmp_path / "ok")
        os.makedirs(d)
        self._write(d, [2.0, 2.1, 1.9, 2.0, 1.5],
                    [80, 82, 78, 81, 90])
        report = bench_sentinel.run_check(d)
        assert report["series"]["session_recovery:cpu"]["verdict"] \
            == "ok"
        assert report["series"]["session_events:cpu"]["verdict"] \
            == "ok"
        assert not report["failed"]

    def test_session_recovery_spike_regresses(self, tmp_path):
        bench_sentinel = self._sentinel()
        d = str(tmp_path / "bad")
        os.makedirs(d)
        self._write(d, [2.0, 2.1, 1.9, 2.0, 9.0],
                    [80, 82, 78, 81, 80])
        report = bench_sentinel.run_check(d)
        assert report["series"]["session_recovery:cpu"]["verdict"] \
            == "regressed"
        assert report["failed"]
        assert any("session_recovery[cpu]" in line
                   and "ceiling" in line
                   for line in report["lines"])

    def test_session_throughput_drop_regresses(self, tmp_path):
        bench_sentinel = self._sentinel()
        d = str(tmp_path / "slow")
        os.makedirs(d)
        self._write(d, [2.0, 2.1, 1.9, 2.0, 2.0],
                    [80, 82, 78, 81, 20])
        report = bench_sentinel.run_check(d)
        assert report["series"]["session_events:cpu"]["verdict"] \
            == "regressed"
        assert report["failed"]

    def test_history_without_session_metrics_unaffected(
            self, tmp_path):
        bench_sentinel = self._sentinel()
        d = str(tmp_path / "old")
        os.makedirs(d)
        for i in range(4):
            doc = {"n": i, "parsed": {
                "value": 800.0 + i, "backend": "cpu"}}
            with open(os.path.join(d, f"BENCH_r{i:02d}.json"),
                      "w") as f:
                json.dump(doc, f)
        report = bench_sentinel.run_check(d)
        assert "session_recovery:cpu" not in report["series"]
        assert "session_events:cpu" not in report["series"]

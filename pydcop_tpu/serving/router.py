"""Fleet router: N solve-service worker replicas behind one HTTP port.

One scheduler thread owning one device cannot serve the ROADMAP's
"millions of users" north star (open item 2).  This module scales the
serve plane OUT: ``pydcop serve --replicas N`` (api.serve(replicas=N))
spawns N worker processes — each a full ``pydcop serve`` instance with
its own SolveService scheduler thread, its own journal segment
(``<journal_dir>/replica-<k>/``), its own /metrics — behind a
stdlib-HTTP router that speaks the existing wire protocol unchanged:
clients POST /solve and poll /result/<id> exactly as against a single
service and never know the fleet exists.

**Structure-affinity routing.**  The router computes the structure
bin key at admission (serving/binning.affinity_key — the PR-3/6
structure signature without the cost-table fill) and routes by
RENDEZVOUS HASHING on it: every replica scores
``sha1(key || replica_id)`` and the highest healthy scorer wins, so
same-structure traffic deterministically lands where the compiled
program (and the batch-mates to coalesce with) is already warm —
cache-affinity beats round-robin, and the bench proves it
(bench.py bench_serving_fleet, ``affinity_hit_fraction`` in /stats).
Rendezvous keeps the map stable under membership change: a replica
death remaps ONLY the keys it owned.  Two escape hatches keep
affinity from becoming a liability: **least-loaded spillover** (a
primary more than ``spill_slack`` requests deeper in flight than the
idlest healthy replica loses the request to it — hot-spot structures
overflow instead of queueing) and **breaker-aware shedding** (a
replica whose admission breaker reports open is dropped from the
candidate set; if every replica sheds, the router answers 503 like a
single service would).

**Fleet lifecycle.**  A heartbeat prober GETs every replica's
/healthz on a short cadence and scores silence with the PR-4
phi-accrual estimator (resilience/health.PhiAccrualEstimator):
suspicion is advisory, ``dead_misses`` expected intervals of silence
(or the worker process exiting) is the death verdict.  A dead
replica's journal segment is handed to its replacement: the router
respawns worker k on ``<journal_dir>/replica-<k>/`` with
``--recover``, so every request the dead worker acknowledged replays
through the PR-8 machinery — SIGKILL mid-burst loses zero
acknowledged requests (tools/chaos_soak.py ``replica_kill``).
Requests are PINNED: the router mints the request id, remembers which
replica owns it, and routes /result polls there (a restarted replica
answers for its predecessor's journal).  Sessions pin the same way.
Fleet SIGTERM drains every worker (each drains its own queue, journals
the rest replayable) and exits 0.

The router process itself never jits: compile work lives in the
workers, warmed across restarts by the persistent AOT compile cache
(engine/aotcache.py) whose directory the router exports to every
worker it spawns.

**Elastic fleet (ISSUE 16).**  Three extensions turn the unit cell
into a control plane:

- *Multi-host membership*: locally-spawned replicas get a simulated
  host identity (``hosts=H`` stripes them ``host0..host{H-1}`` — a
  two-host topology runs as socket-distinct processes on one box for
  CI), and REMOTE replicas join over the wire: a worker started with
  ``--join <router_url>`` announces its address at
  ``POST /fleet/join`` and is probed/phi-scored exactly like a local
  one — the router never restarts what it didn't spawn, it just
  routes around the silence until the replica re-announces.
- *Live session migration* (serving/migration.py): drain-checkpoint
  a warm session on its replica, hand the bundle to another, repoint
  the pin.  Triggers: operator ``POST /admin/migrate``, scale-down
  drain, and replica DEATH — the restart path first compacts the
  dead segment's journal and ADOPTS its open sessions onto survivors
  (bundle built from the compacted records) so warm sessions resume
  in seconds instead of waiting out a worker respawn.
- *SLO autoscaling + fairness*: the monitor compares rolling
  forwarded-request p99 and queue depth against ``--slo_p99_ms`` and
  spawns (prewarmed from the admission exemplar cache, backed by the
  shared AOT disk cache) or drains replicas between
  ``--min_replicas`` and ``--max_replicas``; a weighted-fair
  admission queue (:class:`FairScheduler`, virtual-time WFQ keyed on
  the request's ``tenant``) keeps one tenant's burst from starving
  another's.
"""

import contextlib
import hashlib
import heapq
import itertools
import json
import logging
import os
import random
import signal
import subprocess
import sys
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

from pydcop_tpu.observability import fleettrace
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.serving import netfault
from pydcop_tpu.observability.server import (
    TelemetryServer,
    _Handler,
    get_health_provider,
    set_health_provider,
)

logger = logging.getLogger("pydcop.serving.router")

# Wire limits mirror the single-service front end (serving/http.py).
MAX_BODY_BYTES = 8 << 20
# Forward timeout headroom over the client's own wait window.
FORWARD_TIMEOUT_S = 330.0
# Bounded pin tables: oldest request pins evicted first (the same
# retention philosophy as SolveService.result_keep).
PIN_KEEP = 65536

UP = "up"
STARTING = "starting"
RESTARTING = "restarting"
DOWN = "down"
# Scale-down: out of the candidate set while its sessions migrate to
# survivors; resolves to DOWN (retired) or back to UP on a failed
# drain.
DRAINING = "draining"

# Fair-queue admission wait before a 429: long enough to absorb a
# burst, short enough that a starved client learns it is being
# shaped.
FAIR_WAIT_S = 30.0
# Ambiguous-forward retry budget when the client sent no deadline_s:
# a few backed-off resends, never minutes of hidden spinning.
DEFAULT_RETRY_BUDGET_S = 10.0
# How long a /result poll waits out a mid-restart pin before telling
# the client to retry: the journal-recovered twin usually answers
# within a couple of heartbeats.
RESULT_HEDGE_S = 2.0


class FleetUnavailable(Exception):
    """No healthy, non-shedding replica can take the request (503)."""


# A forward that failed BEFORE any request bytes were written (the
# connect itself was refused/reset — or the netfault plane injected a
# drop/partition).  The worker cannot have seen — let alone acked —
# the request, so re-picking a healthy replica and resending the
# identical body is unconditionally safe.  Any OSError past this
# point is ambiguous (bytes may have reached a worker that journaled
# the request before dying mid-response) and is retried only against
# the SAME replica, where the submit is idempotent on the minted id.
ForwardNotSent = netfault.NotSent


class FairScheduler:
    """Weighted fair queuing over request tenants (virtual-time WFQ,
    the classic start-time fair queue collapsed to unit-cost
    requests): each admission gets a finish tag
    ``max(vtime, tenant's last tag) + 1/weight`` and admissions leave
    the queue in tag order, so a tenant flooding N requests only
    advances its OWN tag N steps — a quiet tenant's next request tags
    just past the current virtual time and overtakes the flood's
    tail.  Capacity (concurrent admitted requests) scales with live
    replicas: ``up * fair_share``.  A request that can't get a slot
    within its wait window is rejected (429) — shaping, not failure.

    Deliberately tiny and lock-simple: the router's forward path is
    hundreds of requests per second, not millions, and the property
    that matters — one tenant's zipf storm cannot starve another's
    sessions — is a tag-ordering property, not a throughput one."""

    def __init__(self, fair_share: int = 8):
        self.fair_share = int(fair_share)
        self._cond = threading.Condition()
        self._vtime = 0.0
        self._last_tag: Dict[str, float] = {}
        self._heap: List[Tuple[float, int, str]] = []
        self._seq = itertools.count()
        self._active = 0
        self.admitted = 0
        self.rejected = 0
        self.queued_peak = 0

    def acquire(self, tenant: str, up: int,
                timeout: float = FAIR_WAIT_S,
                weight: float = 1.0) -> bool:
        deadline = time.monotonic() + timeout
        with self._cond:
            tag = (max(self._vtime,
                       self._last_tag.get(tenant, 0.0))
                   + 1.0 / max(weight, 1e-6))
            self._last_tag[tenant] = tag
            me = (tag, next(self._seq), tenant)
            heapq.heappush(self._heap, me)
            self.queued_peak = max(self.queued_peak,
                                   len(self._heap))
            while True:
                cap = max(up, 1) * self.fair_share
                if self._heap[0] == me and self._active < cap:
                    heapq.heappop(self._heap)
                    self._active += 1
                    self._vtime = max(self._vtime, tag)
                    self.admitted += 1
                    self._cond.notify_all()
                    return True
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._heap.remove(me)
                    heapq.heapify(self._heap)
                    self.rejected += 1
                    self._cond.notify_all()
                    return False
                self._cond.wait(min(remaining, 0.1))

    def release(self) -> None:
        with self._cond:
            self._active = max(self._active - 1, 0)
            self._cond.notify_all()

    def stats(self) -> Dict[str, Any]:
        with self._cond:
            return {
                "fair_share": self.fair_share,
                "active": self._active,
                "queued": len(self._heap),
                "queued_peak": self.queued_peak,
                "admitted": self.admitted,
                "rejected": self.rejected,
                "tenants": len(self._last_tag),
            }


class Replica:
    """One worker slot: the process handle (local spawns), its URL,
    health bookkeeping and the warm-structure set affinity accounting
    reads.  A slot survives its process — a restarted worker reuses
    the slot (same index, same journal segment), which is what keeps
    request pins valid across a replica death.

    ``managed=False`` marks a REMOTE replica that joined over the
    wire (``POST /fleet/join``): no process handle, no journal
    segment the router can touch — a dead remote goes DOWN and stays
    there until it re-announces.  ``host_id`` is the (possibly
    simulated) host identity used by the multi-host chaos proof;
    ``retired`` marks a slot drained away by scale-down — terminal
    for the slot, the prober must not resurrect it."""

    def __init__(self, index: int, journal_dir: Optional[str],
                 log_path: str, host: str = "127.0.0.1",
                 managed: bool = True,
                 host_id: Optional[str] = None):
        self.index = index
        self.journal_dir = journal_dir
        self.log_path = log_path
        self.host = host
        self.managed = managed
        self.host_id = host_id
        self.retired = False
        self.proc: Optional[subprocess.Popen] = None
        self.port: Optional[int] = None
        self.status = STARTING
        self.estimator = None           # PhiAccrualEstimator, set on up
        self.anchor = 0.0
        self.breaker_open = False
        self.queue_depth = 0
        self.in_flight = 0
        self.forwarded = 0
        self.errors = 0
        self.restarts = 0
        self.warm: set = set()
        # Gray-failure scoring: EWMA of /healthz probe round-trip.
        # A link can be slow-but-alive (injected delay, a saturated
        # box) — that is suspicion, not death, and must neither kill
        # the replica nor hide on /healthz.
        self.probe_ewma_ms: Optional[float] = None
        self.gray = False
        # One death verdict per down-episode: mark_forward_error may
        # flip the slot DOWN before the prober's verdict, and a
        # verdict already acted on (restart/adoption) must not re-run
        # every beat while the slot stays dark.
        self.death_handled = False

    @property
    def url(self) -> Optional[str]:
        if self.port is None:
            return None
        return f"http://{self.host}:{self.port}"

    def summary(self) -> Dict[str, Any]:
        return {
            "index": self.index,
            "url": self.url,
            "status": self.status,
            "host_id": self.host_id,
            "managed": self.managed,
            "retired": self.retired,
            "pid": self.proc.pid if self.proc else None,
            "breaker_open": self.breaker_open,
            "queue_depth": self.queue_depth,
            "in_flight": self.in_flight,
            "forwarded": self.forwarded,
            "errors": self.errors,
            "restarts": self.restarts,
            "warm_structures": len(self.warm),
            "journal_dir": self.journal_dir,
            "probe_ms": (round(self.probe_ewma_ms, 2)
                         if self.probe_ewma_ms is not None else None),
            "gray": self.gray,
        }


def _rendezvous_score(digest: str, index: int) -> int:
    """Highest-random-weight score of one (structure, replica) pair —
    deterministic across processes and restarts (hash() is seeded per
    process and would reshuffle the whole map on every router
    restart, defeating the disk-warmed affinity)."""
    h = hashlib.sha1(f"{digest}|{index}".encode()).digest()
    return int.from_bytes(h[:8], "big")


class FleetRouter:
    """Spawn, monitor and route over N serve-worker replicas.

    ``worker_args`` is the raw ``pydcop serve`` CLI argument tail
    every worker is spawned with (batching/admission/session knobs —
    built by api.serve from its kwargs, so the single-service and
    fleet paths cannot drift).  ``journal_dir`` enables per-replica
    durable journals (``replica-<k>/`` segments) and crash handoff;
    ``compile_cache_dir`` is exported to every worker as the
    persistent AOT compile cache.  ``affinity`` is ``"structure"``
    (rendezvous on the bin key, the default) or ``"round_robin"``
    (the A/B baseline the bench measures against)."""

    def __init__(self, replicas: int = 2,
                 worker_args: Optional[List[str]] = None,
                 journal_dir: Optional[str] = None,
                 compile_cache_dir: Optional[str] = None,
                 affinity: str = "structure",
                 heartbeat_s: float = 0.25,
                 probe_timeout_s: Optional[float] = None,
                 dead_misses: float = 8.0,
                 spill_slack: int = 4,
                 restart_dead: bool = True,
                 worker_ready_timeout_s: float = 120.0,
                 default_params: Optional[Dict[str, Any]] = None,
                 hosts: int = 1,
                 slo_p99_ms: Optional[float] = None,
                 min_replicas: Optional[int] = None,
                 max_replicas: Optional[int] = None,
                 fair_share: int = 8,
                 autoscale_interval_s: float = 2.0,
                 scale_down_quiet_checks: int = 10):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        if affinity not in ("structure", "round_robin"):
            raise ValueError(
                f"affinity must be 'structure' or 'round_robin', "
                f"got {affinity!r}")
        if hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {hosts}")
        if max_replicas is not None and max_replicas < replicas:
            raise ValueError(
                f"max_replicas ({max_replicas}) must be >= replicas "
                f"({replicas})")
        if min_replicas is not None and min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {min_replicas}")
        self.n_replicas = int(replicas)
        self.worker_args = list(worker_args or [])
        self.journal_dir = journal_dir
        self.compile_cache_dir = compile_cache_dir
        self.affinity = affinity
        self.heartbeat_s = float(heartbeat_s)
        # Probe timeout scales with the heartbeat instead of a
        # hardcoded constant: injected link delay should raise
        # SUSPICION (gray verdicts), not instantly false-kill a
        # replica whose answers arrive late but arrive.
        self.probe_timeout_s = (float(probe_timeout_s)
                                if probe_timeout_s
                                else max(self.heartbeat_s * 4, 1.0))
        self.dead_misses = float(dead_misses)
        self.spill_slack = int(spill_slack)
        self.restart_dead = bool(restart_dead)
        self.worker_ready_timeout_s = float(worker_ready_timeout_s)
        # The fleet's service-wide solver defaults: the affinity key
        # must normalize request params exactly the way the WORKERS
        # will (their SolveService merges over these same defaults).
        # Hashing against the module defaults instead would split
        # same-bin traffic whenever a client spells a service default
        # explicitly — e.g. params={} vs params={"max_cycles": 60}
        # on a --cycles 60 fleet.
        self.default_params = dict(default_params or {})
        self.replicas: List[Replica] = []
        self._lock = threading.Lock()
        self._rr = itertools.count()
        self._pins: "OrderedDict[str, int]" = OrderedDict()
        self._session_pins: "OrderedDict[str, int]" = OrderedDict()
        # Epoch-fenced session ownership: the router is the epoch
        # authority.  Every repoint (migration, adoption) bumps the
        # session's epoch; PATCHes carry it; a replica still holding
        # the pre-repoint copy rejects/gets fenced instead of
        # double-applying events after a healed partition.
        self._session_epochs: "OrderedDict[str, int]" = OrderedDict()
        # replica index -> {session_id: epoch}: stale copies to fence
        # the moment that replica answers the prober again.
        self._fences: Dict[int, Dict[str, int]] = {}
        self._monitor: Optional[threading.Thread] = None
        self._stopping = threading.Event()
        self._started = False
        self._run_dir: Optional[str] = None
        # Elastic-fleet control plane (ISSUE 16).  Autoscaling is
        # armed only when BOTH slo_p99_ms and max_replicas are set;
        # no control-loop thread starts in __init__ (policy unit
        # tests construct routers without start()).
        self.hosts = int(hosts)
        self.slo_p99_ms = (float(slo_p99_ms)
                           if slo_p99_ms else None)
        self.min_replicas = (int(min_replicas)
                             if min_replicas else None)
        self.max_replicas = (int(max_replicas)
                             if max_replicas else None)
        self.autoscale_interval_s = float(autoscale_interval_s)
        self.scale_down_quiet_checks = int(scale_down_quiet_checks)
        self.fair = FairScheduler(fair_share)
        self._lat: "deque[float]" = deque(maxlen=512)
        self._scaling = False
        self._quiet_checks = 0
        self._last_autoscale = 0.0
        # Admission exemplars for prewarming scaled-up replicas: the
        # most recent (dcop yaml, params) per structure digest, LRU-
        # bounded — replayed against a fresh worker before it takes
        # traffic, so its first client request meets a warm jit cache
        # (fed from the shared AOT disk cache, so the prewarm itself
        # is a disk retrieval, not a cold compile).
        self._exemplars: "OrderedDict[str, Tuple[str, Any]]" = (
            OrderedDict())
        self.exemplar_keep = 8
        self.migrations = 0
        self.adopted_sessions = 0
        self.scale_ups = 0
        self.scale_downs = 0
        # Routing ledger (all mirrored on /stats).
        self.routed = 0
        self.affinity_hits = 0
        self.spillovers = 0
        self.shed = 0
        self.reroutes = 0
        self.deaths = 0
        self.retries = 0
        self.retry_budget_exceeded = 0
        self.fenced_sessions = 0
        # Fleet trace plane (ISSUE 20): the collector exists once a
        # front end attaches (attach_collector — it needs the bound
        # URL to push to worker span shippers); the trace tables map
        # router-minted request/session ids to their trace contexts
        # for the /fleet/forensics lookup.
        self.collector: Optional[fleettrace.FleetCollector] = None
        self.collector_url: Optional[str] = None
        self._request_traces: "OrderedDict[str, str]" = OrderedDict()
        self._session_traces: "OrderedDict[str, str]" = OrderedDict()
        reg = metrics_registry
        self._routed_total = reg.counter(
            "pydcop_router_requests_total",
            "Requests routed to replicas, by outcome")
        self._affinity_total = reg.counter(
            "pydcop_router_affinity_hits_total",
            "Routed requests that landed on a structure-warm replica")
        self._up_gauge = reg.gauge(
            "pydcop_router_replicas_up",
            "Live (heartbeat-passing) worker replicas")
        self._restarts_total = reg.counter(
            "pydcop_router_replica_restarts_total",
            "Worker replicas restarted after a death verdict")
        self._burn_gauge = reg.gauge(
            "pydcop_slo_burn_rate",
            "Rolling forwarded p99 over the --slo_p99_ms target "
            "(>1 means the fleet is burning error budget)")

    # -- lifecycle ----------------------------------------------------- #

    def start(self) -> "FleetRouter":
        import tempfile

        if self._started:
            return self
        self._was_active = metrics_registry.active
        metrics_registry.active = True
        self._run_dir = tempfile.mkdtemp(prefix="pydcop_fleet_")
        try:
            for k in range(self.n_replicas):
                journal = (os.path.join(self.journal_dir,
                                        f"replica-{k}")
                           if self.journal_dir else None)
                replica = Replica(
                    k, journal,
                    os.path.join(self._run_dir, f"replica-{k}.log"),
                    # Striped simulated host identity: replicas of
                    # one "host" share a fate in the host_kill chaos
                    # scenario while remaining socket-distinct
                    # processes.
                    host_id=f"host{k % self.hosts}")
                self.replicas.append(replica)
                self._spawn(replica, recover=False)
            deadline = time.monotonic() + self.worker_ready_timeout_s
            for replica in self.replicas:
                self._wait_ready(replica, deadline)
        except BaseException:
            # Partial startup must not orphan detached workers: one
            # replica failing to come up kills every one already
            # spawned (stop() is a no-op before _started flips).
            for replica in self.replicas:
                if replica.proc is not None \
                        and replica.proc.poll() is None:
                    try:
                        replica.proc.kill()
                        replica.proc.wait(timeout=10.0)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
            self.replicas = []
            metrics_registry.active = self._was_active
            raise
        self._stopping.clear()
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="pydcop-fleet-monitor",
            daemon=True)
        self._monitor.start()
        self._started = True
        self._up_gauge.set(self.up_count())
        return self

    def stop(self, drain: bool = True,
             timeout: float = 120.0) -> Dict[str, Any]:
        """Drain and stop the whole fleet: SIGTERM every worker (each
        drains its queue and journals leftovers replayable — the
        single-service contract), wait for clean exits, reap
        stragglers.  Returns per-worker exit codes."""
        if not self._started:
            return {"workers": []}
        self._stopping.set()
        if self._monitor is not None:
            self._monitor.join(timeout=max(self.heartbeat_s * 4, 2.0))
            self._monitor = None
        sig = signal.SIGTERM if drain else signal.SIGKILL
        for replica in self.replicas:
            if replica.proc is not None and replica.proc.poll() is None:
                try:
                    replica.proc.send_signal(sig)
                except OSError:
                    pass
        exits = []
        deadline = time.monotonic() + timeout
        for replica in self.replicas:
            code = None
            if replica.proc is not None:
                try:
                    code = replica.proc.wait(
                        timeout=max(deadline - time.monotonic(), 1.0))
                except subprocess.TimeoutExpired:
                    replica.proc.kill()
                    try:
                        code = replica.proc.wait(timeout=10.0)
                    except subprocess.TimeoutExpired:
                        code = None
            replica.status = DOWN
            exits.append({"index": replica.index, "exit": code,
                          "restarts": replica.restarts})
        # Final sweep: a restart thread that raced the signal loop
        # above may have spawned a replacement after its slot was
        # signaled — nothing it spawns may outlive the fleet.
        for replica in self.replicas:
            if replica.proc is not None \
                    and replica.proc.poll() is None:
                try:
                    replica.proc.kill()
                    replica.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        self._started = False
        metrics_registry.active = self._was_active
        return {"workers": exits}

    def _spawn(self, replica: Replica, recover: bool) -> None:
        """Start (or restart) worker k.  ``recover`` replays the
        slot's journal segment — the handoff: the restarted process
        owns its predecessor's acknowledged requests."""
        port_file = os.path.join(self._run_dir,
                                 f"replica-{replica.index}.port")
        try:
            os.unlink(port_file)
        except OSError:
            pass
        cmd = [sys.executable, "-m", "pydcop_tpu.dcop_cli", "serve",
               "--port", "0", "--host", "127.0.0.1",
               "--port_file", port_file]
        if replica.journal_dir:
            cmd += ["--journal_dir", replica.journal_dir]
            if recover or os.path.exists(os.path.join(
                    replica.journal_dir, "requests.jnl")):
                cmd += ["--recover"]
        cmd += self.worker_args
        env = dict(os.environ)
        if self.compile_cache_dir:
            # The worker enables the persistent AOT cache at spawn,
            # before its first jit (engine/aotcache latch).
            env["PYDCOP_COMPILE_CACHE_DIR"] = self.compile_cache_dir
        log = open(replica.log_path, "ab")
        try:
            replica.proc = subprocess.Popen(
                cmd, env=env, stdout=log, stderr=log,
                start_new_session=True)
        finally:
            log.close()
        replica.port = None
        replica.status = STARTING if replica.restarts == 0 \
            else RESTARTING
        replica.breaker_open = False
        # A fresh process is NOT warm, whatever its predecessor
        # compiled: affinity hit accounting must restart from zero
        # (the disk compile cache softens the restarted replica's
        # cold calls, but a disk retrieval is still not a warm jit
        # cache — counting it as a hit would inflate
        # affinity_hit_fraction after every death).
        replica.warm = set()
        logger.info("replica %d spawned (pid %d%s)", replica.index,
                    replica.proc.pid,
                    ", recover" if recover else "")

    def _wait_ready(self, replica: Replica, deadline: float) -> None:
        port_file = os.path.join(self._run_dir,
                                 f"replica-{replica.index}.port")
        while time.monotonic() < deadline:
            if replica.proc.poll() is not None:
                raise RuntimeError(
                    f"fleet worker {replica.index} died on startup "
                    f"(exit {replica.proc.returncode}); log: "
                    f"{replica.log_path}")
            try:
                with open(port_file, encoding="utf-8") as f:
                    replica.port = int(f.read().strip())
            except (OSError, ValueError):
                time.sleep(0.05)
                continue
            try:
                status, _ctype, _body = self._forward(
                    replica, "GET", "/healthz", None,
                    timeout=self.probe_timeout_s, trace=None)
            except OSError:
                time.sleep(0.05)
                continue
            if status in (200, 503):
                from pydcop_tpu.resilience.health import (
                    PhiAccrualEstimator,
                )

                now = time.monotonic()
                replica.estimator = PhiAccrualEstimator(
                    expected=self.heartbeat_s)
                replica.anchor = now
                replica.estimator.beat(now)
                replica.status = UP
                replica.death_handled = False
                # A (re)started worker's span shipper starts blank:
                # re-push the collector address so its spans keep
                # landing in the fleet trace (no-op before a front
                # end attaches).
                self.push_trace_config(replica)
                logger.info("replica %d ready on %s", replica.index,
                            replica.url)
                return
            time.sleep(0.05)
        raise RuntimeError(
            f"fleet worker {replica.index} never became ready; "
            f"log: {replica.log_path}")

    # -- health & restarts --------------------------------------------- #

    def up_count(self) -> int:
        return sum(1 for r in self.replicas if r.status == UP)

    def _monitor_loop(self) -> None:
        while not self._stopping.wait(self.heartbeat_s):
            # Snapshot: the autoscaler appends replicas concurrently.
            for replica in list(self.replicas):
                if self._stopping.is_set():
                    return
                try:
                    self._probe(replica)
                except Exception:  # noqa: BLE001 — the prober must
                    # outlive any single replica's weirdness.
                    logger.exception("heartbeat probe crashed for "
                                     "replica %d", replica.index)
            self._up_gauge.set(self.up_count())
            if self.slo_p99_ms:
                # SLO burn rate: rolling p99 over the target.  A
                # fleet with no recent traffic burns nothing.
                p99 = self.rolling_p99()
                self._burn_gauge.set(
                    round(p99 / self.slo_p99_ms, 6) if p99 else 0.0)
            try:
                self._maybe_autoscale()
            except Exception:  # noqa: BLE001 — the control loop must
                # never take the prober down with it.
                logger.exception("autoscale check crashed")

    def _probe(self, replica: Replica) -> None:
        if replica.retired:
            return  # scaled away on purpose — not a death
        if replica.status not in (UP, DOWN):
            return  # mid-(re)start/drain — that path owns it
        proc_dead = (replica.proc is not None
                     and replica.proc.poll() is not None)
        beat_ok = False
        t_probe = time.monotonic()
        if not proc_dead and replica.port is not None:
            try:
                status, _ctype, body = self._forward(
                    replica, "GET", "/healthz", None,
                    timeout=self.probe_timeout_s, trace=None)
                beat_ok = status in (200, 503)
                if beat_ok:
                    doc = json.loads(body)
                    serving = doc.get("serving") or {}
                    replica.breaker_open = (
                        serving.get("breaker_state") == "open")
                    replica.queue_depth = int(
                        serving.get("queue_depth") or 0)
            except (OSError, ValueError):
                beat_ok = False
        now = time.monotonic()
        if beat_ok:
            # Latency-aware scoring: an answer that took a large
            # fraction of the probe timeout marks the link GRAY
            # (slow-but-alive).  Gray is a /healthz verdict, not a
            # routing change — suspicion is advisory (PR-4).
            dt_ms = (now - t_probe) * 1000.0
            replica.probe_ewma_ms = (
                dt_ms if replica.probe_ewma_ms is None
                else 0.7 * replica.probe_ewma_ms + 0.3 * dt_ms)
            replica.gray = (replica.probe_ewma_ms
                            > self.gray_threshold_ms())
            if replica.status == DOWN:
                # A replica marked down on a forward error but whose
                # process lived: it answered again — back in service.
                # A healed partition heals HERE, which is exactly
                # where its stale session copies must be fenced
                # before any client byte can reach them.
                replica.status = UP
                replica.death_handled = False
                self._flush_fences(replica)
            replica.estimator.beat(now)
            return
        replica.gray = False
        missed = (replica.estimator.missed(now, replica.anchor)
                  if replica.estimator else float("inf"))
        # One verdict per down-episode: re-declaring every beat would
        # inflate the death count and re-run adoption against an
        # already-drained segment.  The episode ends at the beat_ok
        # revival above.
        if not replica.death_handled \
                and (proc_dead or missed >= self.dead_misses):
            self._declare_dead(replica, proc_dead=proc_dead,
                               missed=missed)

    def _declare_dead(self, replica: Replica, proc_dead: bool,
                      missed: float) -> None:
        if replica.status == RESTARTING or self._stopping.is_set():
            # A fleet mid-shutdown SIGTERMs its own workers; the
            # monitor must not mistake those exits for deaths and
            # restart what stop() is draining.
            return
        replica.death_handled = True
        self.deaths += 1
        logger.warning(
            "replica %d declared dead (%s, %.1f expected heartbeats "
            "silent)", replica.index,
            "process exited" if proc_dead else "heartbeat silence",
            missed if missed != float("inf") else -1.0)
        replica.status = RESTARTING
        if replica.proc is not None and replica.proc.poll() is None:
            try:
                replica.proc.kill()
                replica.proc.wait(timeout=10.0)
            except (OSError, subprocess.TimeoutExpired):
                pass
        if not replica.managed:
            # A remote replica is not ours to restart: route around
            # it.  The DOWN slot revives when it answers the prober
            # again or re-announces at /fleet/join.  If it announced
            # a reachable journal segment (same-box remote), its warm
            # sessions are adoptable exactly like a managed death —
            # and the adoption's epoch bump is what fences the
            # partitioned original when it heals.
            replica.status = DOWN
            if replica.journal_dir:
                threading.Thread(
                    target=self._adopt_from, args=(replica,),
                    name=f"pydcop-fleet-adopt-{replica.index}",
                    daemon=True).start()
            return
        if not self.restart_dead:
            replica.status = DOWN
            return
        replica.restarts += 1
        self._restarts_total.inc()
        # Restart OFF the monitor thread: a replacement worker takes
        # seconds to import and become ready, and the prober must keep
        # watching the OTHER replicas meanwhile (a second simultaneous
        # death must still be detected within the advertised bound).
        # The status is already RESTARTING, so the monitor skips this
        # slot until the restart thread resolves it to UP or DOWN.
        threading.Thread(
            target=self._restart, args=(replica,),
            name=f"pydcop-fleet-restart-{replica.index}",
            daemon=True).start()

    def _adopt_from(self, replica: Replica) -> None:
        """Compact a dead replica's journal segment and ADOPT its
        open sessions onto survivors (serving/migration.py).  Safe to
        fail: whatever doesn't adopt stays in the segment for a
        restart-in-place replay."""
        try:
            from pydcop_tpu.serving import migration as migration_mod

            adopted = migration_mod.adopt_dead_sessions(self, replica)
            if adopted:
                with self._lock:
                    self.adopted_sessions += adopted
        except Exception:  # noqa: BLE001 — adoption is an
            # optimization over restart-in-place, never a
            # precondition for it.
            logger.exception(
                "replica %d: dead-session adoption failed; "
                "falling back to restart-in-place replay",
                replica.index)

    def _restart(self, replica: Replica) -> None:
        if self._stopping.is_set():
            replica.status = DOWN
            return
        if replica.journal_dir:
            # Before the replacement replays anything: compact the
            # dead segment (torn tail truncated, completed records
            # dropped — the --recover replay visits only pending
            # records) and ADOPT its open sessions onto survivors.
            # Adopted sessions resume warm on a live replica in
            # seconds; whatever fails to adopt stays in the segment
            # for the restart-in-place replay — strictly the old
            # behavior, never worse.
            self._adopt_from(replica)
        try:
            # The journal handoff: --recover replays the dead
            # worker's acknowledged-but-unfinished requests and open
            # sessions through the fresh process.
            self._spawn(replica, recover=True)
            self._wait_ready(
                replica,
                time.monotonic() + self.worker_ready_timeout_s)
            # The fresh process recovered a journal whose adopted
            # sessions carry a MIGRATED close — but if that append
            # raced the death, the fence table still knows.
            self._flush_fences(replica)
        except Exception:  # noqa: BLE001
            logger.exception("replica %d restart failed",
                             replica.index)
            replica.status = DOWN

    # -- routing -------------------------------------------------------- #

    def candidates(self) -> List[Replica]:
        return [r for r in self.replicas
                if r.status == UP and not r.breaker_open]

    def pick(self, digest: Optional[str],
             detail: Optional[Dict[str, Any]] = None
             ) -> Tuple[Replica, bool]:
        """Choose the replica for one admission.  Returns
        ``(replica, affinity_hit)``; raises :class:`FleetUnavailable`
        when every replica is down or shedding.  ``detail`` (an
        optional caller-owned dict) is filled with the route-pick
        reason — chosen replica, affinity hit, spillover — so the
        trace plane can record WHY without a second lock trip."""
        with self._lock:
            live = self.candidates()
            if not live:
                self.shed += 1
                self._routed_total.inc(outcome="shed")
                raise FleetUnavailable(
                    "no healthy replica available (all down or "
                    "breaker-open)")
            if self.affinity == "round_robin" or digest is None:
                chosen = live[next(self._rr) % len(live)]
                spilled = False
            else:
                ranked = sorted(
                    live, key=lambda r: _rendezvous_score(
                        digest, r.index),
                    reverse=True)
                chosen = ranked[0]
                idlest = min(live, key=lambda r: r.in_flight)
                spilled = (chosen.in_flight
                           >= idlest.in_flight + self.spill_slack)
                if spilled:
                    # Hot-spot overflow: a structure-warm replica
                    # deep in flight loses to the idlest one — the
                    # cold compile there costs less than queueing
                    # behind the backlog (and warms a second home for
                    # the structure while it's hot).
                    chosen = idlest
                    self.spillovers += 1
            hit = digest is not None and digest in chosen.warm
            if digest is not None:
                chosen.warm.add(digest)
            chosen.in_flight += 1
            chosen.forwarded += 1
            self.routed += 1
            if hit:
                self.affinity_hits += 1
        if detail is not None:
            detail.update({
                "replica": chosen.index,
                "host_id": chosen.host_id,
                "affinity_hit": hit,
                "spilled": spilled,
                "reason": ("spillover" if spilled
                           else "affinity" if hit
                           else "round_robin"
                           if (self.affinity == "round_robin"
                               or digest is None)
                           else "rendezvous"),
            })
        self._routed_total.inc(outcome="spillover" if spilled
                               else "affinity" if hit else "routed")
        if hit:
            self._affinity_total.inc()
        return chosen, hit

    def release(self, replica: Replica) -> None:
        with self._lock:
            replica.in_flight = max(replica.in_flight - 1, 0)

    def pin(self, request_id: str, replica: Replica,
            table: Optional["OrderedDict[str, int]"] = None) -> None:
        table = self._pins if table is None else table
        with self._lock:
            table[request_id] = replica.index
            while len(table) > PIN_KEEP:
                table.popitem(last=False)

    def pinned(self, request_id: str,
               table: Optional["OrderedDict[str, int]"] = None
               ) -> Optional[Replica]:
        table = self._pins if table is None else table
        with self._lock:
            index = table.get(request_id)
        return self.replicas[index] if index is not None else None

    def mark_forward_error(self, replica: Replica) -> None:
        """A live forward failed at the socket: stop routing there
        NOW; the heartbeat prober (or the process reaper) confirms
        death and owns the restart."""
        with self._lock:
            replica.errors += 1
            if replica.status == UP:
                replica.status = DOWN

    def gray_threshold_ms(self) -> float:
        """Probe EWMA above this marks a link gray: a healthy
        in-box probe answers in single-digit milliseconds, so a
        sustained large fraction of the probe timeout is a slow link,
        not noise."""
        return max(0.35 * self.probe_timeout_s * 1000.0, 120.0)

    # -- epoch-fenced session ownership --------------------------------- #

    def session_epoch(self, session_id: str) -> int:
        with self._lock:
            return self._session_epochs.get(session_id, 1)

    def note_session(self, session_id: str) -> None:
        """A session opened through the router: epoch authority
        starts at 1 (what the worker journaled)."""
        with self._lock:
            self._session_epochs.setdefault(session_id, 1)
            while len(self._session_epochs) > PIN_KEEP:
                self._session_epochs.popitem(last=False)

    def bump_epoch(self, session_id: str, floor: int = 0) -> int:
        """Advance a session's ownership epoch — called by every
        repoint (migration, dead-session adoption) BEFORE the new
        owner takes traffic.  Monotonic for the session's lifetime:
        the returned epoch is journaled by the new owner and carried
        on every PATCH the router forwards.  ``floor`` lets a caller
        that saw a higher epoch in a journal (adoption of a copy that
        itself migrated in) keep the advance strictly past it."""
        with self._lock:
            epoch = max(self._session_epochs.get(session_id, 1) + 1,
                        int(floor))
            self._session_epochs[session_id] = epoch
            self._session_epochs.move_to_end(session_id)
            while len(self._session_epochs) > PIN_KEEP:
                self._session_epochs.popitem(last=False)
            return epoch

    def record_fence(self, index: int, session_id: str,
                     epoch: int) -> None:
        """Remember that replica ``index`` holds a STALE copy of the
        session as of ``epoch``: the moment that replica answers the
        prober again (healed partition, revived slot) it gets fenced
        before a client byte can reach the stale copy."""
        with self._lock:
            table = self._fences.setdefault(index, {})
            table[session_id] = max(epoch,
                                    table.get(session_id, 0))

    def _flush_fences(self, replica: Replica) -> None:
        with self._lock:
            pending = self._fences.pop(replica.index, None)
        if not pending:
            return
        for sid, epoch in pending.items():
            # The fence travels in the session's own fleet trace:
            # forensics on a migrated session shows WHEN its stale
            # copy was revoked, not just that it was.
            ctx = fleettrace.TraceContext(
                self.trace_for(sid) or uuid.uuid4().hex[:16])
            try:
                self._forward(
                    replica, "POST", "/admin/fence_session",
                    json.dumps({"session_id": sid,
                                "epoch": epoch}).encode(),
                    timeout=self.probe_timeout_s, trace=ctx)
                with self._lock:
                    self.fenced_sessions += 1
                if tracer.active:
                    tracer.instant("router_fence_flush", "fleet",
                                   trace_id=ctx.trace_id, session=sid,
                                   epoch=epoch, replica=replica.index)
                logger.info("fenced stale session %s (epoch %d) on "
                            "replica %d", sid, epoch, replica.index)
            except OSError:
                # It answered once, it will answer the prober again —
                # re-arm so the next heal attempt retries the fence.
                self.record_fence(replica.index, sid, epoch)

    # -- fleet trace plane (ISSUE 20) ----------------------------------- #

    def attach_collector(self, url: str) -> None:
        """Arm the fleet trace plane: create the collector, tap the
        router's own flight recorder into it (route-pick/retry/fence
        spans land in the merged trace's ``router`` lane), and push
        the collector address to every live replica's span shipper.
        The front end calls this once it knows its bound URL;
        idempotent, and a no-op with ``PYDCOP_FLEET_TRACE=0``."""
        self.collector_url = url
        if not fleettrace.enabled():
            return
        if self.collector is None:
            self.collector = fleettrace.FleetCollector()
        self.collector.attach_router_tap()
        for replica in list(self.replicas):
            if replica.status == UP:
                self.push_trace_config(replica)

    def detach_collector(self) -> None:
        """Disarm the plane: stop observing router spans, tell live
        replicas to stop shipping.  Collected events stay queryable
        (a stopped fleet's trace is still forensics material)."""
        if self.collector is not None:
            self.collector.detach_router_tap()
        for replica in list(self.replicas):
            if replica.status == UP:
                self.push_trace_config(replica, enable=False)

    def set_fleet_trace(self, on: bool) -> None:
        """Runtime toggle (the perf-smoke pairwise gate flips this
        between timed phases): sets the env knob gating this
        process's header stamping and minting, then re-arms or
        disarms the collector and every worker's shipper."""
        os.environ[fleettrace.ENV_KNOB] = "1" if on else "0"
        if on and self.collector_url:
            self.attach_collector(self.collector_url)
        elif not on:
            self.detach_collector()

    def push_trace_config(self, replica: Replica,
                          enable: bool = True) -> None:
        """Tell one replica where to ship completed spans.  Best
        effort by contract: telemetry config must never become a
        lifecycle dependency — a failed push just means that
        replica's lane stays empty until the next heal/restart."""
        if self.collector_url is None:
            return
        body = json.dumps({
            "url": self.collector_url,
            "source": f"replica-{replica.index}",
            "enable": bool(enable and fleettrace.enabled()),
        }).encode()
        try:
            self._forward(replica, "POST", "/admin/trace_collector",
                          body, timeout=10.0, trace=None)
        except OSError:
            logger.debug("replica %d trace-collector config push "
                         "failed", replica.index)

    def note_request_trace(self, rid: str, trace_id: str) -> None:
        with self._lock:
            self._request_traces[rid] = trace_id
            while len(self._request_traces) > PIN_KEEP:
                self._request_traces.popitem(last=False)

    def note_session_trace(self, sid: str, trace_id: str) -> None:
        with self._lock:
            self._session_traces[sid] = trace_id
            while len(self._session_traces) > PIN_KEEP:
                self._session_traces.popitem(last=False)

    def trace_for(self, handle: str) -> Optional[str]:
        """The trace id behind a router-minted request id or a
        session id — the ``/fleet/forensics/<id>`` entry point."""
        with self._lock:
            return (self._request_traces.get(handle)
                    or self._session_traces.get(handle))

    # -- multi-host membership ------------------------------------------ #

    def register_remote(self, url: str,
                        host_id: Optional[str] = None,
                        journal_dir: Optional[str] = None
                        ) -> Dict[str, Any]:
        """Admit a remote replica that announced itself (``POST
        /fleet/join`` — a worker started with ``--join``).  The slot
        is probed before admission and then heartbeat-scored exactly
        like a local one; a re-announce of the same address revives
        its existing slot (same index → existing pins stay valid).
        ``journal_dir`` is the worker's own journal segment when the
        router can reach it on disk (same-box remotes, the CI
        topology): it makes the remote's sessions adoptable after a
        death/partition verdict.  Raises ValueError for a bad
        address, RuntimeError when the announced endpoint doesn't
        answer /healthz."""
        from urllib.parse import urlparse

        parsed = urlparse(url if "//" in url else f"http://{url}")
        host, port = parsed.hostname, parsed.port
        if not host or not port:
            raise ValueError(
                f"bad replica url {url!r} (need host:port)")
        with self._lock:
            replica = next(
                (r for r in self.replicas
                 if not r.managed and r.host == host
                 and r.port == port), None)
            if replica is None:
                import tempfile

                index = len(self.replicas)
                log_path = os.path.join(
                    self._run_dir or tempfile.gettempdir(),
                    f"remote-{index}.log")
                replica = Replica(index, None, log_path, host=host,
                                  managed=False, host_id=host_id)
                replica.port = int(port)
                self.replicas.append(replica)
            if journal_dir and os.path.isdir(journal_dir):
                replica.journal_dir = journal_dir
        try:
            status, _ctype, _body = self._forward(
                replica, "GET", "/healthz", None, timeout=5.0,
                trace=None)
        except OSError as exc:
            with self._lock:
                if replica.status != UP:
                    replica.status = DOWN
            raise RuntimeError(
                f"joining replica {url} failed its admission probe: "
                f"{exc}")
        if status not in (200, 503):
            raise RuntimeError(
                f"joining replica {url} answered /healthz with "
                f"{status}")
        from pydcop_tpu.resilience.health import PhiAccrualEstimator

        now = time.monotonic()
        with self._lock:
            replica.estimator = PhiAccrualEstimator(
                expected=self.heartbeat_s)
            replica.anchor = now
            replica.estimator.beat(now)
            replica.retired = False
            if host_id:
                replica.host_id = host_id
            replica.status = UP
            replica.death_handled = False
        # A re-announce is a heal: stale session copies recorded
        # against this slot get fenced before it serves.
        self._flush_fences(replica)
        # Joined replicas ship spans like spawned ones: hand the
        # fresh member the collector address.
        self.push_trace_config(replica)
        self._up_gauge.set(self.up_count())
        logger.info("remote replica %d joined from %s (host %s)",
                    replica.index, replica.url, replica.host_id)
        return {"index": replica.index, "status": UP,
                "heartbeat_s": self.heartbeat_s}

    # -- SLO autoscaling ------------------------------------------------ #

    def record_latency(self, ms: float) -> None:
        with self._lock:
            self._lat.append(float(ms))

    def rolling_p99(self) -> Optional[float]:
        with self._lock:
            lat = sorted(self._lat)
        if not lat:
            return None
        return lat[min(int(0.99 * len(lat)), len(lat) - 1)]

    def note_exemplar(self, digest: Optional[str], dcop_yaml: str,
                      params: Optional[Dict[str, Any]]) -> None:
        """Remember one admission per structure digest for replica
        prewarming (LRU over ``exemplar_keep`` structures)."""
        if digest is None:
            return
        with self._lock:
            self._exemplars[digest] = (dcop_yaml, params)
            self._exemplars.move_to_end(digest)
            while len(self._exemplars) > self.exemplar_keep:
                self._exemplars.popitem(last=False)

    def autoscale_decision(self) -> Optional[str]:
        """The scaling policy, side-effect-free except for the quiet-
        streak counter: ``"up"`` when the rolling p99 breaches the
        SLO (or queues run deep) with headroom below max_replicas;
        ``"down"`` after ``scale_down_quiet_checks`` consecutive
        checks comfortably under it with an idle replica above the
        floor; None otherwise.  Inert unless both ``slo_p99_ms`` and
        ``max_replicas`` are configured."""
        if not self.slo_p99_ms or not self.max_replicas:
            return None
        p99 = self.rolling_p99()
        with self._lock:
            managed = [r for r in self.replicas
                       if r.managed and not r.retired]
            live = [r for r in managed if r.status == UP]
            n_active = len([r for r in managed
                            if r.status in (UP, STARTING,
                                            RESTARTING, DRAINING)])
            queue_depth = sum(r.queue_depth for r in live)
        floor = self.min_replicas or 1
        if n_active < self.max_replicas and (
                (p99 is not None and p99 > self.slo_p99_ms)
                or queue_depth > 2 * max(len(live), 1)):
            self._quiet_checks = 0
            return "up"
        if n_active > floor and (
                (p99 is None or p99 < self.slo_p99_ms / 2)
                and queue_depth == 0
                and any(r.in_flight == 0 for r in live)):
            self._quiet_checks += 1
            if self._quiet_checks >= self.scale_down_quiet_checks:
                self._quiet_checks = 0
                return "down"
            return None
        self._quiet_checks = 0
        return None

    def _maybe_autoscale(self) -> None:
        if not self.slo_p99_ms or not self.max_replicas:
            return
        if self._scaling or self._stopping.is_set():
            return
        now = time.monotonic()
        if now - self._last_autoscale < self.autoscale_interval_s:
            return
        decision = self.autoscale_decision()
        if decision is None:
            return
        self._last_autoscale = now
        self._scaling = True
        # Off the monitor thread: a spawn takes seconds of import
        # and the prober must keep watching the fleet meanwhile.
        threading.Thread(
            target=self._scale, args=(decision,),
            name="pydcop-fleet-scale", daemon=True).start()

    def _scale(self, decision: str) -> None:
        try:
            if decision == "up":
                self._scale_up()
            else:
                self._scale_down()
        except Exception:  # noqa: BLE001
            logger.exception("autoscale %s failed", decision)
        finally:
            self._scaling = False

    def _scale_up(self) -> None:
        with self._lock:
            index = len(self.replicas)
            journal = (os.path.join(self.journal_dir,
                                    f"replica-{index}")
                       if self.journal_dir else None)
            replica = Replica(
                index, journal,
                os.path.join(self._run_dir, f"replica-{index}.log"),
                host_id=f"host{index % self.hosts}")
            self.replicas.append(replica)
            self.n_replicas += 1
        logger.info("autoscale up: spawning replica %d", index)
        self._spawn(replica, recover=False)
        self._wait_ready(
            replica, time.monotonic() + self.worker_ready_timeout_s)
        # Prewarm BEFORE taking traffic: _wait_ready flipped the slot
        # UP; hold it back out of the candidate set while the
        # exemplars replay (each a disk-cache retrieval, not a cold
        # compile, thanks to the shared AOT cache dir).
        replica.status = STARTING
        self._prewarm(replica)
        replica.status = UP
        with self._lock:
            self.scale_ups += 1
            # The SLO window must not keep scaling on latencies
            # measured by the smaller fleet.
            self._lat.clear()
        self._up_gauge.set(self.up_count())
        logger.info("autoscale up: replica %d serving", index)

    def _prewarm(self, replica: Replica) -> None:
        with self._lock:
            exemplars = list(self._exemplars.items())
        for digest, (dcop_yaml, params) in exemplars[-4:]:
            body: Dict[str, Any] = {"dcop": dcop_yaml,
                                    "wait": True, "timeout": 60.0}
            if params:
                body["params"] = params
            try:
                self._forward(replica, "POST", "/solve",
                              json.dumps(body).encode(),
                              timeout=90.0, trace=None)
                # Unlike a crash respawn, this replica genuinely
                # executed the structure: its in-process jit cache is
                # warm for it.
                replica.warm.add(digest)
            except OSError as exc:
                logger.warning(
                    "replica %d prewarm forward failed (%s)",
                    replica.index, exc)
                return

    def _scale_down(self) -> None:
        with self._lock:
            live = [r for r in self.replicas
                    if r.managed and not r.retired
                    and r.status == UP]
            floor = self.min_replicas or 1
            if len(live) <= floor:
                return
            victim = next(
                (r for r in reversed(live)
                 if r.in_flight == 0 and r.queue_depth == 0), None)
            if victim is None:
                return
            victim.status = DRAINING
        logger.info("autoscale down: draining replica %d",
                    victim.index)
        with self._lock:
            sids = [sid for sid, idx in self._session_pins.items()
                    if idx == victim.index]
        from pydcop_tpu.serving import migration as migration_mod

        for sid in sids:
            try:
                migration_mod.migrate_session(self, sid)
            except Exception:  # noqa: BLE001 — a drain that can't
                # move every session aborts: the replica goes back to
                # serving rather than stranding a warm session.
                logger.exception(
                    "autoscale down aborted: session %s would not "
                    "migrate off replica %d", sid, victim.index)
                victim.status = UP
                return
        if victim.proc is not None and victim.proc.poll() is None:
            try:
                victim.proc.send_signal(signal.SIGTERM)
                victim.proc.wait(timeout=60.0)
            except (OSError, subprocess.TimeoutExpired):
                try:
                    victim.proc.kill()
                    victim.proc.wait(timeout=10.0)
                except (OSError, subprocess.TimeoutExpired):
                    pass
        with self._lock:
            victim.status = DOWN
            victim.retired = True
            self.scale_downs += 1
            self.n_replicas = max(self.n_replicas - 1, 1)
            self._lat.clear()
        self._up_gauge.set(self.up_count())
        logger.info("autoscale down: replica %d retired",
                    victim.index)

    # -- plumbing ------------------------------------------------------- #

    def _forward(self, replica: Replica, method: str, path: str,
                 body: Optional[bytes],
                 timeout: float = FORWARD_TIMEOUT_S,
                 trace: Optional[fleettrace.TraceContext] = None
                 ) -> Tuple[int, str, bytes]:
        # Every router->replica byte crosses the netfault seam: a
        # connect refusal (or an injected drop/partition) surfaces as
        # ForwardNotSent — zero bytes delivered, retry-safe — while
        # anything past the connect stays a plain, ambiguous OSError
        # (including an injected lost response).
        #
        # ``trace`` is mandatory at every call site (the static-check
        # trace-seam lint enforces the explicit kwarg): request-plane
        # forwards carry the admission context so the replica's spans
        # join the fleet trace; telemetry-plane probes pass
        # ``trace=None`` on purpose.
        headers = None
        trace_cm = contextlib.nullcontext()
        if trace is not None and fleettrace.enabled():
            headers = {fleettrace.HEADER: trace.encode()}
            if tracer.active:
                # Thread-bound context: anything recorded UNDER this
                # exchange (a netfault injection instant, most
                # usefully) lands inside the request's causal tree.
                trace_cm = tracer.context(trace_ids=[trace.trace_id])
        with trace_cm:
            return netfault.exchange(
                "router",
                (f"replica-{replica.index}", replica.host_id or ""),
                replica.host, replica.port, method, path,
                body=body, timeout=timeout, headers=headers)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            routed = self.routed
            hits = self.affinity_hits
            doc = {
                "replicas": self.n_replicas,
                "up": self.up_count(),
                "affinity": self.affinity,
                "routed": routed,
                "affinity_hits": hits,
                "affinity_hit_fraction": (round(hits / routed, 4)
                                          if routed else None),
                "spillovers": self.spillovers,
                "shed": self.shed,
                "reroutes": self.reroutes,
                "deaths": self.deaths,
                "retries": self.retries,
                "retry_budget_exceeded": self.retry_budget_exceeded,
                "fenced_sessions": self.fenced_sessions,
                "migrations": self.migrations,
                "adopted_sessions": self.adopted_sessions,
                "spill_slack": self.spill_slack,
                "heartbeat_s": self.heartbeat_s,
                "probe_timeout_s": self.probe_timeout_s,
                "hosts": self.hosts,
                "pinned_requests": len(self._pins),
                "pinned_sessions": len(self._session_pins),
                "workers": [r.summary() for r in self.replicas],
            }
        doc["fairness"] = self.fair.stats()
        if self.slo_p99_ms:
            doc["autoscale"] = {
                "slo_p99_ms": self.slo_p99_ms,
                "min_replicas": self.min_replicas,
                "max_replicas": self.max_replicas,
                "rolling_p99_ms": self.rolling_p99(),
                "scale_ups": self.scale_ups,
                "scale_downs": self.scale_downs,
            }
        from pydcop_tpu.engine import aotcache

        # The router never jits, so the process-local cache stats are
        # meaningless here; report the SHARED directory its workers
        # populate (how warm a scale-up prewarm will find the disk).
        doc["compile_cache"] = {"dir": self.compile_cache_dir}
        if self.compile_cache_dir:
            doc["compile_cache"].update(
                aotcache.disk_stats(self.compile_cache_dir))
        return doc

    def link_verdicts(self) -> List[Dict[str, Any]]:
        """Per-link router->replica health verdicts: ``ok``, ``gray``
        (slow-but-alive — answers arrive, late), ``starting``
        (mid-(re)start/drain) or ``dead``.  Retired slots (scaled
        away on purpose) don't count against the fleet."""
        out = []
        for r in self.replicas:
            if r.retired:
                continue
            if r.status == UP:
                verdict = "gray" if r.gray else "ok"
            elif r.status in (STARTING, RESTARTING, DRAINING):
                verdict = "starting"
            else:
                verdict = "dead"
            out.append({
                "replica": r.index, "host_id": r.host_id,
                "status": r.status, "verdict": verdict,
                "probe_ms": (round(r.probe_ewma_ms, 2)
                             if r.probe_ewma_ms is not None
                             else None),
            })
        return out

    def health_summary(self) -> Dict[str, Any]:
        """The fleet /healthz: failing (503) only when NOTHING can
        serve; degraded while any replica is down/restarting OR any
        link's verdict is not ok (gray failure must not hide behind a
        green fleet light)."""
        up = self.up_count()
        links = self.link_verdicts()
        degraded = (up < self.n_replicas
                    or any(l["verdict"] != "ok" for l in links))
        status = ("failing" if up == 0
                  else "degraded" if degraded else "ok")
        doc = {"status": status, "fleet": {
            "replicas": self.n_replicas, "up": up,
            "links": links,
            "workers": [r.summary() for r in self.replicas],
        }}
        injected = netfault.counters()
        if injected:
            doc["fleet"]["netfault_injected"] = injected
        return doc


class _RouterHandler(_Handler):
    """The fleet's client-facing wire protocol — same routes as the
    single-service front end (serving/http.py), implemented by
    admission-time routing + forwarding."""

    def _json(self, code: int, payload: Dict[str, Any],
              close: bool = False):
        self._reply(code, json.dumps(payload, default=str).encode(),
                    "application/json", close=close)

    def _read_body(self) -> Optional[bytes]:
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > MAX_BODY_BYTES:
            self._json(400, {"error": "body required (JSON, "
                                      f"<= {MAX_BODY_BYTES} bytes)"},
                       close=True)
            return None
        return self.rfile.read(length)

    @property
    def router(self) -> FleetRouter:
        return self.telemetry.router

    def _proxy(self, replica: Replica, method: str, path: str,
               body: Optional[bytes],
               timeout: float = FORWARD_TIMEOUT_S,
               trace=None) -> None:
        try:
            status, ctype, payload = self.router._forward(
                replica, method, path, body, timeout=timeout,
                trace=trace)
        except ForwardNotSent as exc:
            # Zero bytes reached the worker: the operation certainly
            # did not happen.
            self.router.mark_forward_error(replica)
            self._json(503, {
                "error": f"replica {replica.index} unreachable "
                         f"({exc}); recovering — retry",
                "status": "rejected", "retry": True})
            return
        except OSError as exc:
            # The request MAY have been received (and, for a PATCH,
            # acked into the journal) before the socket died: the
            # client must reconcile, not blind-resend.
            self.router.mark_forward_error(replica)
            self._json(503, {
                "error": f"replica {replica.index} failed mid-"
                         f"request ({exc}); outcome unknown — "
                         "reconcile before retrying",
                "status": "unknown", "retry": True})
            return
        self._reply(status, payload, ctype)

    # -- request plane -------------------------------------------------- #

    def do_POST(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if path == "/solve":
            self._route_solve()
        elif path == "/session":
            self._route_session_open()
        elif path == "/fleet/join":
            self._fleet_join()
        elif path == "/fleet/spans":
            self._fleet_spans()
        elif path == "/admin/migrate":
            self._admin_migrate()
        else:
            self._json(404, {"error": "unknown path"}, close=True)

    def _fleet_join(self):
        raw = self._read_body()
        if raw is None:
            return
        try:
            doc = json.loads(raw)
            url = doc.get("url")
            if not url or not isinstance(url, str):
                raise ValueError("body needs a 'url' string "
                                 "(the joining replica's address)")
        except ValueError as exc:
            self._json(400, {"error": f"bad join body: {exc}"})
            return
        try:
            out = self.router.register_remote(
                url, doc.get("host_id"),
                journal_dir=doc.get("journal_dir"))
        except ValueError as exc:
            self._json(400, {"error": str(exc)})
            return
        except RuntimeError as exc:
            self._json(503, {"error": str(exc), "retry": True})
            return
        self._json(200, out)

    def _admin_migrate(self):
        raw = self._read_body()
        if raw is None:
            return
        try:
            doc = json.loads(raw)
            sid = doc.get("session_id")
            if not sid or not isinstance(sid, str):
                raise ValueError("body needs a 'session_id'")
            target = doc.get("target")
            if target is not None and not isinstance(target, int):
                raise ValueError("'target' must be a replica index")
        except ValueError as exc:
            self._json(400, {"error": f"bad migrate body: {exc}"})
            return
        from pydcop_tpu.serving import migration as migration_mod

        try:
            out = migration_mod.migrate_session(
                self.router, sid, target_index=target)
        except KeyError:
            self._json(404, {"error": f"unknown session {sid!r}"})
            return
        except ValueError as exc:
            self._json(400, {"error": str(exc)})
            return
        except (OSError, RuntimeError) as exc:
            self._json(503, {"error": str(exc), "retry": True})
            return
        self._json(200, out)

    def _admission_key(self, raw: bytes
                       ) -> Tuple[Optional[dict], Optional[str]]:
        """Parse the body far enough to route: returns (body json,
        affinity digest).  Malformed bodies get their 4xx HERE — the
        router is the client's first contact and must speak the same
        validation language as a worker."""
        try:
            body = json.loads(raw)
            if not isinstance(body, dict):
                raise ValueError("body must be a JSON object")
        except ValueError as exc:
            self._json(400, {"error": f"bad request body: {exc}"})
            return None, None
        yaml_src = body.get("dcop")
        if not isinstance(yaml_src, str) or not yaml_src.strip():
            self._json(400, {"error": "bad request body: body needs "
                                      "a 'dcop' key holding the "
                                      "problem as a dcop yaml string"})
            return None, None
        digest = None
        try:
            from pydcop_tpu.dcop.yamldcop import load_dcop
            from pydcop_tpu.serving import binning

            merged = dict(self.router.default_params)
            merged.update(body.get("params") or {})
            digest = binning.affinity_key(load_dcop(yaml_src),
                                          merged)
        except Exception as exc:  # noqa: BLE001 — malformed problem
            self._json(400, {"error": f"bad problem: {exc}"})
            return None, None
        return body, digest

    def _route_solve(self):
        raw = self._read_body()
        if raw is None:
            return
        body, digest = self._admission_key(raw)
        if body is None:
            return
        router = self.router
        # Weighted-fair admission by tenant (an optional body key the
        # workers never see): one tenant's zipf storm queues behind
        # its own tag chain while other tenants' requests overtake
        # it.  Absent tenants share one lane, which is exactly the
        # pre-fairness behavior.
        tenant = str(body.pop("tenant", "") or "default")
        if not router.fair.acquire(tenant, router.up_count()):
            self._json(429, {
                "error": f"fair-queue admission timed out for "
                         f"tenant {tenant!r}; retry with backoff",
                "status": "rejected", "retry": True})
            return
        try:
            self._route_solve_admitted(body, digest)
        finally:
            router.fair.release()

    def _route_solve_admitted(self, body: dict,
                              digest: Optional[str]):
        router = self.router
        router.note_exemplar(digest, body.get("dcop"),
                             body.get("params"))
        # The router ALWAYS mints the id (a client-supplied one is
        # ignored): worker-local counters collide across replicas,
        # the pin table needs a fleet-unique handle before the worker
        # ever answers, and an externally chosen id could clobber
        # another request's pin — duplicate-id rejection is
        # per-worker, so two replicas would happily accept the same
        # spoofed id.
        rid = f"f{uuid.uuid4().hex[:16]}"
        body["request_id"] = rid
        payload = json.dumps(body).encode()
        # Admission is where the causal trace is born: the minted
        # context travels on every forward (and retry) of this
        # request, and the rid→trace map lets /fleet/forensics and
        # later /result reads rejoin the same trace.
        ctx = fleettrace.mint()
        router.note_request_trace(rid, ctx.trace_id)
        t0 = time.monotonic()
        # The ambiguous-failure retry budget is the client's own
        # remaining patience: a deadline_s in the body bounds it (a
        # retry that lands after the client gave up helps nobody),
        # else a modest default.
        try:
            deadline_s = float(body.get("deadline_s") or 0.0)
        except (TypeError, ValueError):
            deadline_s = 0.0
        budget = t0 + (deadline_s if deadline_s > 0
                       else DEFAULT_RETRY_BUDGET_S)
        tried: set = set()
        span_cm = (tracer.span("router_request", "fleet",
                               trace_id=ctx.trace_id, request=rid)
                   if tracer.active else contextlib.nullcontext())
        with span_cm:
            while True:
                detail: Dict[str, Any] = {}
                try:
                    replica, _hit = router.pick(digest, detail=detail)
                except FleetUnavailable as exc:
                    self._json(503, {"error": str(exc),
                                     "status": "rejected",
                                     "retry": True})
                    return
                if tracer.active:
                    tracer.instant("router_route_pick", "fleet",
                                   trace_id=ctx.trace_id, request=rid,
                                   **detail)
                if replica.index in tried:
                    # pick() charged this replica's in_flight; this
                    # exit path never forwards, so it must release
                    # here or the slot leaks and the spillover
                    # heuristic sees a permanently-busier replica.
                    router.release(replica)
                    self._json(503, {
                        "error": "every healthy replica failed the "
                                 "forward; retry",
                        "status": "rejected", "retry": True})
                    return
                tried.add(replica.index)
                router.pin(rid, replica)
                try:
                    result = self._forward_retrying(
                        replica, payload, rid, budget, ctx)
                except ForwardNotSent as exc:
                    # The connect was refused before ANY attempt
                    # reached the worker: zero bytes delivered,
                    # nothing acked — re-picking a healthy replica
                    # and resending the identical body (the id
                    # travels with it) is unconditionally safe.
                    if tracer.active:
                        tracer.instant("router_repick", "fleet",
                                       trace_id=ctx.trace_id,
                                       request=rid,
                                       replica=replica.index,
                                       error=str(exc))
                    router.mark_forward_error(replica)
                    with router._lock:
                        router.reroutes += 1
                    router.release(replica)
                    continue
                router.release(replica)
                if result is None:
                    return  # budget exhausted; 503 already sent
                status, ctype, out = result
                router.record_latency(
                    (time.monotonic() - t0) * 1000.0)
                self._reply(status, out, ctype)
                return

    def _forward_retrying(self, replica: Replica, payload: bytes,
                          rid: str, budget: float,
                          ctx: Optional[fleettrace.TraceContext] = None
                          ) -> Optional[Tuple[int, str, bytes]]:
        """Forward one /solve to ONE replica, absorbing ambiguous
        failures with jittered exponential backoff while the deadline
        budget lasts.

        Resending after bytes went out is safe ONLY here: the pinned
        replica dedupes on the router-minted id (same table, and —
        across a restart — the same journal segment), so N deliveries
        execute once.  Another replica has a different journal;
        re-picking after an ambiguous failure could double-execute,
        which is why a first-attempt connect refusal (ForwardNotSent)
        propagates to the caller's re-pick loop while everything
        later retries HERE.  Returns the response tuple, or None
        after answering the 503-outcome-unknown itself."""
        router = self.router
        attempt = 0
        while True:
            try:
                return router._forward(replica, "POST", "/solve",
                                       payload, trace=ctx)
            except OSError as exc:
                if attempt == 0 and isinstance(exc, ForwardNotSent):
                    raise
                attempt += 1
                if tracer.active and ctx is not None:
                    tracer.instant(
                        "router_retry", "fleet",
                        trace_id=ctx.trace_id, request=rid,
                        attempt=attempt, replica=replica.index,
                        not_sent=isinstance(exc, ForwardNotSent),
                        error=str(exc))
                backoff = min(0.05 * (2 ** attempt), 1.0)
                backoff *= 0.5 + random.random() * 0.5
                if time.monotonic() + backoff > budget:
                    with router._lock:
                        router.retry_budget_exceeded += 1
                    router.mark_forward_error(replica)
                    # The client gets the minted id — the pin
                    # survives the replica's restart, so
                    # /result/<id> either finds the journaled
                    # request's replayed result (it was acked) or
                    # 404s (it never landed; resubmitting is safe).
                    self._json(503, {
                        "error": f"replica {replica.index} failed "
                                 f"mid-forward ({exc}); outcome "
                                 "unknown — poll the result url, "
                                 "resubmit on 404",
                        "status": "unknown", "retry": True,
                        "request_id": rid,
                        "result_url": f"/result/{rid}"})
                    return None
                with router._lock:
                    router.retries += 1
                time.sleep(backoff)

    # -- result / stats / sessions -------------------------------------- #

    def do_GET(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if path.startswith("/result/"):
            self._route_result(path[len("/result/"):], path)
        elif path.startswith("/session/"):
            sid = path[len("/session/"):].split("/", 1)[0]
            replica = self.router.pinned(
                sid, self.router._session_pins)
            if replica is None:
                self._json(404, {"error": f"unknown session {sid!r}"})
                return
            tid = self.router.trace_for(sid)
            ctx = fleettrace.TraceContext(tid) if tid else None
            if path.endswith("/events"):
                self._proxy_sse(replica, path, ctx)
            else:
                self._proxy(replica, "GET", path, None, timeout=30.0,
                            trace=ctx)
        elif path == "/stats":
            self._fleet_stats()
        elif path == "/fleet/metrics":
            self._fleet_metrics()
        elif path == "/fleet/profile":
            self._fleet_profile()
        elif path == "/fleet/trace":
            self._fleet_trace()
        elif path.startswith("/fleet/forensics/"):
            self._fleet_forensics(path[len("/fleet/forensics/"):])
        else:
            super().do_GET()

    def _route_result(self, rid: str, path: str) -> None:
        """Hedged /result read: the pin may point at a replica that
        is mid-restart — its journal-recovered twin answers for every
        COMPLETED record within a couple of heartbeats, so wait
        briefly (re-reading the pin: adoption may repoint it
        meanwhile) instead of bouncing every poll straight to 503."""
        router = self.router
        tid = router.trace_for(rid)
        ctx = fleettrace.TraceContext(tid) if tid else None
        deadline = time.monotonic() + RESULT_HEDGE_S
        while True:
            replica = router.pinned(rid)
            if replica is None:
                self._json(404, {"error": f"unknown request {rid!r}"})
                return
            if replica.status == UP:
                try:
                    status, ctype, payload = router._forward(
                        replica, "GET", path, None, timeout=30.0,
                        trace=ctx)
                except OSError:
                    status = None
                if status is not None:
                    self._reply(status, payload, ctype)
                    return
            if time.monotonic() >= deadline:
                self._json(503, {
                    "error": f"replica {replica.index} recovering; "
                             "retry", "retry": True})
                return
            time.sleep(min(max(router.heartbeat_s, 0.05), 0.25))

    def _fleet_stats(self):
        """Router stats + a live per-worker /stats fetch: ONE surface
        that answers both "how is traffic spread" and "what is each
        replica doing"."""
        doc = self.router.stats()
        for worker in doc["workers"]:
            replica = self.router.replicas[worker["index"]]
            if replica.status != UP:
                continue
            try:
                status, _ctype, body = self.router._forward(
                    replica, "GET", "/stats", None, timeout=10.0,
                    trace=None)
                if status == 200:
                    worker["stats"] = json.loads(body)
            except (OSError, ValueError):
                pass
        self._json(200, doc)

    def _route_session_open(self):
        raw = self._read_body()
        if raw is None:
            return
        body, digest = self._admission_key(raw)
        if body is None:
            return
        payload = json.dumps(body).encode()
        # Session opens mint their own context: the worker adopts it
        # as the session trace_id, so every later event batch, SSE
        # attach, and migration hop for this session can be stitched
        # back to this admission.
        ctx = fleettrace.mint()
        tried: set = set()
        while True:
            try:
                replica, _hit = self.router.pick(digest)
            except FleetUnavailable as exc:
                self._json(503, {"error": str(exc),
                                 "status": "rejected", "retry": True})
                return
            if replica.index in tried:
                self.router.release(replica)
                self._json(503, {
                    "error": "every healthy replica refused the "
                             "session open; retry",
                    "status": "rejected", "retry": True})
                return
            tried.add(replica.index)
            try:
                status, ctype, out = self.router._forward(
                    replica, "POST", "/session", payload, trace=ctx)
            except ForwardNotSent:
                # Connect refused: no worker saw the open — re-pick.
                self.router.mark_forward_error(replica)
                with self.router._lock:
                    self.router.reroutes += 1
                self.router.release(replica)
                continue
            except OSError as exc:
                # The open may have been journaled before the socket
                # died; a blind re-open would mint a second session.
                self.router.mark_forward_error(replica)
                self.router.release(replica)
                self._json(503, {
                    "error": f"replica failed mid-open ({exc}); "
                             "outcome unknown — retry with an "
                             "explicit session_id to stay idempotent",
                    "status": "unknown", "retry": True})
                return
            self.router.release(replica)
            break
        if status == 201:
            try:
                sid = json.loads(out).get("session_id")
                if sid:
                    # Sessions are stateful: every later PATCH/GET/
                    # DELETE must land on the replica holding the
                    # warm engine.
                    self.router.pin(sid, replica,
                                    self.router._session_pins)
                    self.router.note_session(sid)
                    self.router.note_session_trace(sid, ctx.trace_id)
                    if tracer.active:
                        tracer.instant("router_session_open", "fleet",
                                       trace_id=ctx.trace_id,
                                       session=sid,
                                       replica=replica.index)
            except ValueError:
                pass
        self._reply(status, out, ctype)

    def _session_replica(self, path: str) -> Optional[Replica]:
        sid = path[len("/session/"):].split("/", 1)[0]
        replica = self.router.pinned(sid, self.router._session_pins)
        if replica is None:
            self._json(404, {"error": f"unknown session {sid!r}"},
                       close=True)
            return None
        return replica

    def do_PATCH(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if not (path.startswith("/session/")
                and path.endswith("/events")):
            self._json(404, {"error": "unknown path"}, close=True)
            return
        raw = self._read_body()
        if raw is None:
            return
        replica = self._session_replica(path)
        if replica is None:
            return
        if replica.status != UP:
            # Affinity-stranded: the warm state lives (or lived) on
            # that replica; shed honestly instead of silently
            # re-homing — adoption repoints the pin when it can.
            self._json(503, {
                "error": f"session owner (replica {replica.index}) "
                         "is recovering; retry",
                "status": "rejected", "retry": True})
            return
        sid = path[len("/session/"):].split("/", 1)[0]
        try:
            doc = json.loads(raw)
            if isinstance(doc, dict):
                # The ownership fence travels with every forwarded
                # event batch: a replica holding a pre-repoint copy
                # of the session rejects this epoch with a 409
                # instead of double-applying.
                doc["epoch"] = self.router.session_epoch(sid)
                raw = json.dumps(doc).encode()
        except ValueError:
            pass  # the worker's validation answers malformed bodies
        tid = self.router.trace_for(sid)
        ctx = fleettrace.TraceContext(tid) if tid else fleettrace.mint()
        if tracer.active:
            tracer.instant("router_session_events", "fleet",
                           trace_id=ctx.trace_id, session=sid,
                           replica=replica.index)
        self._proxy(replica, "PATCH", path, raw, trace=ctx)

    def do_DELETE(self):  # noqa: N802 — stdlib name
        path = self.path.split("?", 1)[0]
        if not path.startswith("/session/"):
            self._json(404, {"error": "unknown path"}, close=True)
            return
        replica = self._session_replica(path)
        if replica is not None:
            sid = path[len("/session/"):].split("/", 1)[0]
            tid = self.router.trace_for(sid)
            self._proxy(replica, "DELETE", path, None,
                        trace=fleettrace.TraceContext(tid)
                        if tid else None)

    # -- fleet trace / telemetry surfaces (ISSUE 20) --------------------- #

    def _fleet_spans(self):
        """Collector ingest: replicas POST batches of completed spans
        here.  Shipping is lossy-by-design on the worker side; this
        endpoint only validates and files what arrives."""
        raw = self._read_body()
        if raw is None:
            return
        collector = self.router.collector
        if collector is None:
            self._json(503, {"error": "fleet trace collector is not "
                                      "attached", "retry": True})
            return
        try:
            doc = json.loads(raw)
            if not isinstance(doc, dict):
                raise ValueError("body must be a JSON object")
            out = collector.ingest(doc)
        except ValueError as exc:
            self._json(400, {"error": f"bad span batch: {exc}"})
            return
        self._json(200, out)

    def _fleet_trace(self):
        """The merged fleet trace, live: one lane per source (router
        + each replica), rebased onto the router's clock — the same
        document `pydcop fleet forensics --trace` consumes offline."""
        collector = self.router.collector
        if collector is None:
            self._json(503, {"error": "fleet tracing is disabled "
                                      "(PYDCOP_FLEET_TRACE=0)"})
            return
        self._json(200, collector.merged_doc())

    def _fleet_metrics(self):
        """Every replica's metric registry plus the router's own,
        merged under a `replica` label.  Per-source samples survive
        the merge, so conservation checks (summed replica counters ==
        router admission ledger) read straight off this surface."""
        from pydcop_tpu.observability import metrics as metrics_mod

        router = self.router
        snaps: Dict[str, Dict] = {
            "router": self.telemetry.registry.snapshot()}
        for replica in router.replicas:
            if replica.status != UP:
                continue
            try:
                status, _ctype, body = router._forward(
                    replica, "GET", "/metrics.json", None,
                    timeout=10.0, trace=None)
                if status == 200:
                    snaps[f"replica-{replica.index}"] = \
                        json.loads(body)
            except (OSError, ValueError):
                continue  # a recovering replica just skips one scrape
        merged = metrics_mod.merge_snapshots(snaps)
        query = (self.path.split("?", 1)[1]
                 if "?" in self.path else "")
        if "format=json" in query:
            self._json(200, {"sources": sorted(snaps),
                             "metrics": merged})
            return
        text = metrics_mod.render_snapshot_prometheus(merged)
        self._reply(200, text.encode(),
                    "text/plain; version=0.0.4; charset=utf-8")

    def _fleet_profile(self):
        """Pooled efficiency rollup: each UP replica's /profile doc,
        device-time-weighted into one fleet attainment + summed
        ledgers."""
        from pydcop_tpu.observability import efficiency

        router = self.router
        docs: Dict[str, Dict] = {}
        for replica in router.replicas:
            if replica.status != UP:
                continue
            try:
                status, _ctype, body = router._forward(
                    replica, "GET", "/profile", None,
                    timeout=10.0, trace=None)
                if status == 200:
                    docs[f"replica-{replica.index}"] = \
                        json.loads(body)
            except (OSError, ValueError):
                continue
        self._json(200, efficiency.pooled_rollup(docs))

    def _fleet_forensics(self, rid: str):
        """One request's full causal story, reconstructed from the
        merged fleet trace: the admission span, every route pick and
        retry, and the winning replica's serve ledger — as the same
        query document `pydcop fleet forensics` renders."""
        from pydcop_tpu.observability.trace import query_request

        collector = self.router.collector
        if collector is None:
            self._json(503, {"error": "fleet tracing is disabled "
                                      "(PYDCOP_FLEET_TRACE=0)"})
            return
        rid = rid.split("?", 1)[0].strip("/")
        if not rid:
            self._json(400, {"error": "need /fleet/forensics/<id>"})
            return
        events = collector.merged_events()
        trace_id = self.router.trace_for(rid)
        if trace_id is None:
            # Fall back to scanning: a request (or session) id that
            # aged out of the bounded map may still live in the
            # retained spans themselves.
            for ev in events:
                args = ev.get("args") or {}
                if rid in (args.get("request"), args.get("session")):
                    trace_id = args.get("trace_id")
                    if trace_id:
                        break
        if not trace_id:
            self._json(404, {"error": f"unknown request {rid!r}: no "
                                      "trace recorded (tracing off, "
                                      "spans dropped, or id aged "
                                      "out)"})
            return
        doc = query_request(events, trace_id)
        doc["request_id"] = rid
        doc["dropped_spans"] = collector.dropped_spans()
        self._json(200, doc)

    def _proxy_sse(self, replica: Replica, path: str, trace=None):
        """Stream a worker's per-session SSE through: chunks are
        relayed as they arrive until either side closes.

        The upstream read runs on a SHORT timeout (a few worker
        keepalive periods) instead of the forward timeout: when the
        owning replica is SIGKILLed the TCP peer may simply go
        silent, and a client must observe a clean reconnectable EOF
        within seconds — not a five-minute hang.  A timeout while the
        replica is still UP just keeps reading (the worker's 1 s
        keepalives make that rare)."""
        read_timeout = max(self.router.heartbeat_s * 8, 3.0)
        headers = ({fleettrace.HEADER: trace.encode()}
                   if trace is not None and fleettrace.enabled()
                   else None)
        try:
            conn, resp = netfault.open_stream(
                "router",
                (f"replica-{replica.index}", replica.host_id or ""),
                replica.host, replica.port, "GET", path, None,
                FORWARD_TIMEOUT_S, headers=headers)
        except OSError as exc:
            self._json(503, {"error": f"replica unreachable ({exc})"})
            return
        if resp.status != 200:
            self._reply(resp.status, resp.read(),
                        resp.getheader("Content-Type",
                                       "application/json"))
            conn.close()
            return
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        self.send_header("Connection", "close")
        self.end_headers()
        if conn.sock is not None:
            conn.sock.settimeout(read_timeout)
        try:
            while not self.telemetry._stopping.is_set():
                try:
                    chunk = resp.read1(65536)
                except TimeoutError:  # socket.timeout is its alias
                    if replica.status != UP:
                        # The owner died under the stream: end it
                        # cleanly; the client reconnects through the
                        # router and lands on whoever owns the
                        # session now (the restarted replica, or a
                        # survivor that adopted it).
                        break
                    continue
                if not chunk:
                    break
                self.wfile.write(chunk)
                self.wfile.flush()
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass  # either side went away — normal SSE termination
        finally:
            conn.close()


class RouterFrontEnd(TelemetryServer):
    """The fleet's single client-facing HTTP server.  Mounts the
    router wire protocol over the telemetry routes; while running,
    the fleet health summary feeds the process-wide /healthz
    provider (zero live replicas → 503, like a single service's open
    breaker)."""

    handler_class = _RouterHandler

    def __init__(self, router: FleetRouter, port: int = 0,
                 host: str = "127.0.0.1", registry=None):
        super().__init__(port=port, host=host, registry=registry)
        self.router = router
        self._prior_provider = None

    def start(self) -> "RouterFrontEnd":
        super().start()
        self._prior_provider = get_health_provider()
        set_health_provider(self.router.health_summary)
        if fleettrace.enabled():
            # The front end's own URL is the collector address every
            # replica ships spans to; attaching also pushes that
            # config to workers already UP.
            self.router.attach_collector(self.url)
        return self

    def stop(self):
        self.router.detach_collector()
        set_health_provider(self._prior_provider)
        self._prior_provider = None
        super().stop()

"""Sharded-vs-unsharded parity for the WHOLE device algorithm family.

Round-4 verdict: sharded parity was asserted for 2 of 14 algorithms;
"the mesh is just bigger" was a claim, not a test, for the other 12.
This battery runs every algorithm with a device path through
``api.solve`` twice — single device and sharded over the 8-virtual-
device mesh (``n_devices=8``) — and asserts the results agree.

Reference analogue: the distribution layer works for every algorithm
(pydcop/distribution/objects.py:36 Distribution is algorithm-
agnostic); the sharding replacement must be too.

Parity tiers, by numeric class (docs/performance.md "Sharded
all-reduce" + __graft_entry__.dryrun_multichip rationale):

- **integer-cost local search** (dsa, dsatuto, adsa, mgm, mgm2, dba,
  gdba, mixeddsa): f32 sums of integer costs are exact, so the
  sharded trajectory is BIT-identical — identical assignment, cost,
  and cycle count at any cycle budget, even on loopy graphs;
- **maxsum family** (maxsum, amaxsum, maxsum_dynamic): float messages
  — the mesh all-reduce reassociates sums, so exact cross-topology
  parity is asserted on a QUIESCENT (tree) instance where
  send-suppression freezes the fixpoint;
- **exact solvers** (dpop, syncbb, ncbb): the mesh changes row padding
  (dpop) or is accepted-and-unused (host-driven B&B) — optimal cost
  must be identical either way.
"""

import numpy as np
import pytest

import jax

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)

N_DEVICES = 8


def _loopy_int_dcop(n_vars=24, n_edges=36, d=3, seed=0):
    """Random loopy binary DCOP with integer tables (exact f32 sums)."""
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("loopy", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    seen = set()
    k = 0
    while k < n_edges:
        i, j = rng.choice(n_vars, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        table = rng.integers(0, 10, size=(d, d)).astype(np.float64)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], table, f"c{k}"))
        k += 1
    return dcop


def _tree_dcop(n_vars=24, d=3, seed=1):
    """Random tree: MaxSum quiesces (every edge send-suppressed), so
    sharded and single-device runs reach the identical fixpoint."""
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("tree", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(1, n_vars):
        parent = int(rng.integers(0, i))
        table = rng.integers(0, 10, size=(d, d)).astype(np.float64)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[parent], variables[i]], table, f"c{i}"))
    return dcop


def _small_dcop(n_vars=8, n_cons=12, d=3, seed=2):
    return _loopy_int_dcop(n_vars=n_vars, n_edges=n_cons, d=d,
                           seed=seed)


def _pair(dcop, algo, max_cycles=30, algo_params=None):
    single = solve(dcop, algo, backend="device", max_cycles=max_cycles,
                   algo_params=algo_params)
    sharded = solve(dcop, algo, backend="device",
                    max_cycles=max_cycles, n_devices=N_DEVICES,
                    algo_params=algo_params)
    return single, sharded


LOCAL_SEARCH = [
    ("dsa", {"seed": 3}),
    ("dsatuto", {"seed": 3}),
    ("adsa", {"seed": 3, "stop_cycle": 30}),
    ("mgm", {"seed": 3}),
    ("mgm2", {"seed": 3}),
    ("dba", {"seed": 3}),
    ("gdba", {"seed": 3}),
    ("mixeddsa", {"seed": 3}),
]


@pytest.mark.parametrize(
    "algo,params", LOCAL_SEARCH, ids=[a for a, _ in LOCAL_SEARCH])
def test_local_search_bit_parity(algo, params):
    dcop = _loopy_int_dcop()
    single, sharded = _pair(dcop, algo, algo_params=params)
    assert sharded.assignment == single.assignment, (
        f"{algo}: sharded assignment diverged")
    assert sharded.cost == single.cost


@pytest.mark.parametrize("algo", ["maxsum", "amaxsum", "maxsum_dynamic"])
def test_maxsum_family_fixpoint_parity(algo):
    dcop = _tree_dcop()
    single, sharded = _pair(dcop, algo, max_cycles=200)
    assert sharded.assignment == single.assignment, (
        f"{algo}: sharded fixpoint diverged on a quiescent problem")
    assert sharded.cost == single.cost


@pytest.mark.parametrize("algo", ["dpop", "syncbb", "ncbb"])
def test_exact_solvers_cost_parity(algo):
    dcop = _small_dcop()
    single, sharded = _pair(dcop, algo)
    assert sharded.cost == pytest.approx(single.cost)
    assert sharded.assignment == single.assignment


def test_all_fourteen_covered():
    """The battery must cover every algorithm exposing a device path
    (pkgutil discovery — a 15th algorithm without a parity row fails
    here, keeping this file honest as the family grows)."""
    from pydcop_tpu.algorithms import list_available_algorithms

    covered = {a for a, _ in LOCAL_SEARCH} | {
        "maxsum", "amaxsum", "maxsum_dynamic", "dpop", "syncbb", "ncbb",
    }
    available = set(list_available_algorithms())
    missing = available - covered
    assert not missing, (
        f"algorithms without a sharded-parity row: {sorted(missing)}")

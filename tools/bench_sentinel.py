"""Bench regression sentinel: run-over-run guard on the BENCH_r*.json
trajectory.

Every PR's driver appends a ``BENCH_r<N>.json`` (the supervised
``bench.py`` line, wrapped with attempt metadata) and TPU runs persist
``BENCH_TPU_LAST.json`` — but until now nothing ever COMPARED them, so
a perf regression only surfaced when a human eyeballed the numbers.
This tool parses the whole history, builds a noise-aware baseline per
backend (CPU-fallback and TPU rates differ by orders of magnitude and
must never share a baseline — and CPU baselines are further keyed on
the host's core count once a round records ``host_cpus``, because a
1-core bench box measures the same code ~3x slower than an 8-core
one), and fails when the newest run regresses beyond threshold.

Noise model: the baseline is the MEDIAN of the trailing window with a
MAD (median absolute deviation) spread — both robust to the single
wild outlier a wedged-tunnel run produces.  The newest value regresses
when it falls below ``median - max(rel_tol * median, mad_mult * MAD)``:
the relative term guards stable series (MAD ~ 0 would otherwise flag
every wiggle), the MAD term widens tolerance on genuinely noisy
series (shared-CPU benchmark hosts jitter ±15% run to run).

Host-shift guard (ISSUE 19): the closed-loop serving legs are bound
by the host's thread scheduler, not device compute — the same code
measures 2x slower when a shared box degrades, even at the same core
count (so the ``cpu@<n>`` class key cannot see it).  The guard
detects that from the data: every HOST-BOUND family's newest/median
speed ratio is pooled (including the envelope-off control arm
``serve_mixed_baseline``, the same workload every round), and when
the MEDIAN ratio itself falls beyond the relative tolerance the drop
is common-mode — a host-class change, not a code regression (one
code change does not slow serve, fleet, sessions, cold-start AND the
feature-off control arm in unison).  Host-bound regressions in such
a round are reported loudly but do not gate; compute-bound families
(headline, sharded, dpop, time-to-cost) always gate, and an isolated
single-family drop still fails because it cannot move the median.
The blind spot (a stack-wide code slowdown coinciding with the
round) self-heals: the trailing window re-medians over the following
same-class rounds and a persistent regression resurfaces.

Usage::

    python tools/bench_sentinel.py             # report + exit 1 on
                                               # regression (make
                                               # bench-check)
    python tools/bench_sentinel.py --json      # machine-readable
    python tools/bench_sentinel.py --root DIR  # history elsewhere

``make test`` runs it ADVISORY (report printed, failures don't gate:
a slow shared host must not block an unrelated PR); ``make
bench-check`` is the hard gate for perf-focused work.  Each series
also prints a one-line sparkline trajectory suitable for pasting into
CHANGES.md.
"""

import argparse
import glob
import json
import os
import re
import statistics
import sys
from typing import Any, Dict, List, Optional

DEFAULT_REL_TOL = 0.15
DEFAULT_MAD_MULT = 3.0
DEFAULT_WINDOW = 5
MIN_POINTS = 3  # newest + at least 2 history points to call anything
# Host-shift guard: the common-mode estimator needs at least this many
# host-bound series with judgeable history before it may conclude
# anything — two ratios have no meaningful median.
HOST_SHIFT_MIN_SERIES = 3

_SPARKS = "▁▂▃▄▅▆▇█"


def _opt_float(value) -> Optional[float]:
    return float(value) if value is not None else None


def load_history(root: str) -> List[Dict[str, Any]]:
    """All bench runs in chronological order: ``BENCH_r*.json`` (by
    round number), plus ``BENCH_TPU_LAST.json`` ONLY when no round
    ever ran on TPU — the artifact has no position in the round
    chronology, so once real TPU rounds exist it must not masquerade
    as "the newest run" (a stale artifact would be judged instead of
    the actual latest round); with zero TPU rounds it is the only
    TPU evidence and seeds the series instead.

    Unreadable or value-less files are skipped with a note in the
    returned rows (``"skipped"`` entries), never a crash — the history
    predates this tool and its earliest rows are ragged.
    """
    runs: List[Dict[str, Any]] = []
    # Round files strictly: the glob also matches names like
    # BENCH_rerun.json, which have no round number to sort by.
    numbered = []
    for path in glob.glob(os.path.join(root, "BENCH_r*.json")):
        match = re.fullmatch(r"BENCH_r(\d+)\.json",
                             os.path.basename(path))
        if match:
            numbered.append((int(match.group(1)), path))
    paths = [p for _, p in sorted(numbered)]
    for path in paths:
        name = os.path.basename(path)
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError) as exc:
            runs.append({"source": name, "skipped": str(exc)})
            continue
        # A brand-new (or hand-edited) history may hold JSON that is
        # valid but not a run document — a bare list, a string.  Skip
        # it like an unreadable file, never crash the sentinel.
        if not isinstance(doc, dict):
            runs.append({"source": name,
                         "skipped": "not a JSON object"})
            continue
        parsed = doc.get("parsed")
        if not isinstance(parsed, dict):
            parsed = {}
        value = parsed.get("value")
        if value is None:
            runs.append({"source": name,
                         "skipped": "no parsed.value"})
            continue
        serve_value = parsed.get("serve_problems_per_sec")
        sharded_value = parsed.get("maxsum_cycles_per_sec_sharded")
        runs.append({
            "source": name,
            "n": doc.get("n"),
            "value": float(value),
            # Rounds 1-5 all fell back to CPU; the earliest line
            # predates the backend key, so absent means cpu.
            "backend": parsed.get("backend") or "cpu",
            # Host hardware class (ISSUE 17): CPU-fallback rates scale
            # with the bench box's core count, so CPU baselines are
            # keyed on it (``cpu@<n>``) once a round records it —
            # rounds that predate the key stay plain ``cpu``.
            "host_cpus": parsed.get("host_cpus"),
            # Serving-throughput leg (PR-6 bench_serving); absent in
            # earlier rounds, None when the leg failed that round.
            "serve_value": (float(serve_value)
                            if serve_value is not None else None),
            # Sharded-superstep leg (PR-7 bench_sharded: partitioned
            # engine, halo-only exchange).  Judged on its own backend
            # key — the CPU leg runs on a forced-host-device mesh
            # whose rates say nothing about a real TPU mesh.
            "sharded_value": (float(sharded_value)
                              if sharded_value is not None else None),
            "sharded_backend": parsed.get("sharded_backend")
            or parsed.get("backend") or "cpu",
            # Time-to-target-cost leg (ISSUE 10 bench_time_to_cost):
            # milliseconds the pruned engine takes to reach the
            # reference cost on the large-domain loopy graph — LOWER
            # is better; absent before PR 10.
            "ttc_value": _opt_float(
                parsed.get("maxsum_time_to_cost_ms")),
            # Recovery-latency legs (ISSUE 8 bench_recovery_replay /
            # bench_sharded): seconds, LOWER is better — absent
            # before PR 8, None when the leg failed that round.
            "serve_recovery_value": _opt_float(
                parsed.get("serve_recovery_replay_s")),
            "shard_recovery_value": _opt_float(
                parsed.get("shard_recovery_s")),
            # Mixed-structure serving leg (ISSUE 11
            # bench_serving_mixed): zipf-diverse topologies through
            # the envelope batching tier — absent before PR 11, None
            # when the leg failed that round.
            "serve_mixed_value": _opt_float(
                parsed.get("serve_mixed_problems_per_sec")),
            # Envelope-OFF control arm of the same mixed leg: the one
            # series whose workload and code path barely change round
            # to round, so its drift measures the HOST, not the PR.
            # Never gates on its own — it anchors the host-shift
            # guard's common-mode estimator (ISSUE 19).
            "serve_mixed_baseline_value": _opt_float(
                parsed.get("serve_mixed_baseline_problems_per_sec")),
            # Pipelined-flush overlap (ISSUE 18 bench_serving_mixed):
            # measured-window fraction of device execute wall the
            # scheduler hid decode work under — HIGHER is better, a
            # drop means the closed-loop hot path stopped
            # overlapping.  Absent before PR 18.
            "serve_overlap_value": _opt_float(
                parsed.get("serve_overlap_fraction")),
            # Stateful-session legs (ISSUE 13 bench_sessions):
            # warm time-to-recovered-cost after a scenario event
            # (ms, LOWER is better) and sustained applied events per
            # second per session — absent before PR 13, None when
            # the leg failed that round.
            "session_ttr_value": _opt_float(
                parsed.get("session_time_to_recovered_cost_ms")),
            "session_eps_value": _opt_float(
                parsed.get("session_events_per_sec")),
            # Fleet-serving legs (ISSUE 15 bench_serving_fleet /
            # bench_serve_cold_start): aggregate problems/sec through
            # 2 router-fronted worker replicas, and the fresh-worker
            # warm-disk-cache time-to-first-result (s, LOWER is
            # better) — absent before PR 15, None when the leg failed
            # that round.
            "fleet_value": _opt_float(
                parsed.get("fleet_problems_per_sec_r2")),
            "cold_start_value": _opt_float(
                parsed.get("serve_cold_start_warm_s")),
            # Exact-inference leg (ISSUE 17 bench_dpop_exact):
            # warmed best-of-N full DPOP sweep (UTIL up + VALUE
            # down, CEC on) on the width-bounded seeded instance
            # (ms, LOWER is better) — absent before PR 17, None
            # when the leg failed that round.
            "dpop_value": _opt_float(parsed.get("dpop_exact_ms")),
            # Elastic-fleet leg (ISSUE 16 bench_fleet_elastic):
            # baseline closed-loop problems/sec through the two-host
            # fleet that also survives the leg's migration, 4x-step
            # autoscale, and host-kill phases — absent before PR 16,
            # None when the leg failed that round.
            "fleet_elastic_value": _opt_float(
                parsed.get("fleet_elastic_problems_per_sec")),
            # Partition-tolerant fleet leg (ISSUE 19
            # bench_serving_fleet_faulted): closed-loop problems/sec
            # through a 2-replica fleet under a seeded 1%-drop /
            # 20ms-delay plan on the solve links.  Its OWN family —
            # a faulted round must never be judged against (or
            # pollute the baseline of) the clean fleet numbers.
            # Absent before PR 19, None when the leg failed.
            "fleet_faulted_value": _opt_float(
                parsed.get("fleet_faulted_problems_per_sec")),
            # The p99 latency exemplar from the serving leg (ISSUE
            # 9): when the newest run regresses, the report points at
            # a concrete request trace instead of a bare number.
            "exemplar": parsed.get("exemplar_trace_id"),
            # Per-leg RESOLVED backends (ISSUE 11 crumb, consumed
            # since ISSUE 14): a run whose headline ran on TPU can
            # still have individual legs fall back to CPU — each
            # leg's value must be judged against ITS backend's
            # baseline, never the headline's.  Absent before PR 11.
            "leg_backends": {
                leg: info.get("backend")
                for leg, info in (
                    parsed.get("leg_backends") or {}).items()
                if isinstance(info, dict)
            },
        })
    last_path = os.path.join(root, "BENCH_TPU_LAST.json")
    have_tpu_round = any(r.get("backend") == "tpu" for r in runs)
    if os.path.exists(last_path) and not have_tpu_round:
        try:
            with open(last_path, encoding="utf-8") as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError("not a JSON object")
            value = doc.get("value")
            if value is not None:
                runs.append({
                    "source": "BENCH_TPU_LAST.json",
                    "n": None,
                    "value": float(value),
                    "backend": doc.get("backend") or "tpu",
                })
        except (OSError, ValueError) as exc:
            runs.append({"source": "BENCH_TPU_LAST.json",
                         "skipped": str(exc)})
    return runs


def check_series(values: List[float],
                 rel_tol: float = DEFAULT_REL_TOL,
                 mad_mult: float = DEFAULT_MAD_MULT,
                 window: int = DEFAULT_WINDOW,
                 higher_is_better: bool = True) -> Dict[str, Any]:
    """Verdict for one backend's chronological metric series.

    The newest value is judged against the median ± MAD of the
    ``window`` runs before it.  ``higher_is_better=True`` (rates:
    cycles/s, problems/s) regresses when the newest value falls below
    the floor; ``False`` (latencies: recovery seconds) regresses when
    it rises above the ceiling.  Returns a dict with the verdict
    (``ok`` / ``regressed`` / ``insufficient``), the baseline stats,
    the tolerance actually applied, and ``bound`` (the floor or
    ceiling crossed)."""
    if len(values) < MIN_POINTS:
        return {
            "verdict": "insufficient",
            "points": len(values),
            "detail": f"need >= {MIN_POINTS} runs to judge",
        }
    newest = values[-1]
    trail = values[-(window + 1):-1]
    med = statistics.median(trail)
    mad = statistics.median([abs(v - med) for v in trail])
    tolerance = max(rel_tol * abs(med), mad_mult * mad)
    if higher_is_better:
        bound = med - tolerance
        regressed = newest < bound
    else:
        bound = med + tolerance
        regressed = newest > bound
    return {
        "verdict": "regressed" if regressed else "ok",
        "points": len(values),
        "newest": newest,
        "median": med,
        "mad": mad,
        "tolerance": tolerance,
        "bound": bound,
        # Kept for history consumers that predate lower-is-better
        # series: "floor" has always named the regression boundary.
        "floor": bound,
        "higher_is_better": higher_is_better,
        "delta_rel": (newest - med) / med if med else 0.0,
    }


def sparkline(values: List[float]) -> str:
    """One block-character per run, scaled to the series range — the
    pasteable trajectory line."""
    if not values:
        return ""
    lo, hi = min(values), max(values)
    span = hi - lo
    if span <= 0:
        return _SPARKS[3] * len(values)
    return "".join(
        _SPARKS[min(int((v - lo) / span * (len(_SPARKS) - 1)),
                    len(_SPARKS) - 1)]
        for v in values
    )


def run_check(root: str, rel_tol: float = DEFAULT_REL_TOL,
              mad_mult: float = DEFAULT_MAD_MULT,
              window: int = DEFAULT_WINDOW) -> Dict[str, Any]:
    """Full sentinel pass over a history directory: per-backend
    verdicts + summary lines.  ``failed`` is True iff any backend
    with enough history regressed."""
    runs = load_history(root)
    skipped = [r for r in runs if "skipped" in r]
    # Five metric families judged with the same noise model: the
    # headline engine rate ("value", cycles/s), the serving
    # throughput ("serve_value", problems/s — absent before PR 6),
    # the sharded-superstep rate ("sharded_value", cycles/s — absent
    # before PR 7; judged on its own backend key because the CPU leg
    # runs on a forced-host-device mesh), and the two ISSUE-8
    # recovery LATENCIES (journal crash replay, shard-loss
    # repartition — seconds, LOWER is better, regression = newest
    # above the ceiling).  Backends never share a baseline in any
    # family.
    metrics = (
        # (family, value field, unit, fallback backend key, higher is
        # better, bench.py leg name in ``leg_backends``, host-bound).
        # ``host_bound=True`` marks closed-loop serving legs whose
        # rate is dominated by the host's thread scheduler rather
        # than device compute — the population the host-shift guard
        # pools its common-mode estimator over (ISSUE 19).  Compute
        # families stay False and always gate.
        ("bench", "value", "cycles/s", "backend", True, "headline",
         False),
        ("serve", "serve_value", "problems/s", "backend", True,
         "serve", True),
        # ISSUE 11: throughput on zipf-diverse structures through the
        # envelope batching tier — the traffic shape on which pure
        # structure binning degenerates to batch-size-1.
        ("serve_mixed", "serve_mixed_value", "problems/s",
         "backend", True, "serve_mixed", True),
        # ISSUE 19: the envelope-OFF control arm of the same leg.
        # Same workload every round, so its drift measures the host;
        # it feeds the host-shift estimator and NEVER gates (see
        # CONTROL_FAMILIES below).
        ("serve_mixed_baseline", "serve_mixed_baseline_value",
         "problems/s", "backend", True, "serve_mixed", True),
        # ISSUE 18: decode/dispatch overlap fraction of the pipelined
        # scheduler on the same mixed leg — a brand-new family: until
        # 3 rounds exist its verdict is "insufficient", never a crash
        # or gate.  A fraction, so host-speed cancels: not host-bound.
        ("serve_overlap", "serve_overlap_value", "fraction",
         "backend", True, "serve_mixed", False),
        ("sharded", "sharded_value", "cycles/s",
         "sharded_backend", True, "sharded", False),
        # ISSUE 10: wall-clock to the reference cost on the
        # large-domain loopy graph (bench_time_to_cost) — the
        # work-reduction stack's headline, LOWER is better.
        # Host-bound: wall-clock ms of cpu-resolved compute tracks
        # host speed; the work-reduction logic itself is gated
        # load-immune by perf-smoke's same-box decimation-vs-baseline
        # wall ratio (DECIM_MAX_FRACTION).
        ("time_to_cost", "ttc_value", "ms", "backend", False,
         "time_to_cost", True),
        ("serve_recovery", "serve_recovery_value", "s",
         "backend", False, "serve_recovery", True),
        # ISSUE 15: the fleet-scale serving families — aggregate
        # replicas=2 throughput through the structure-affinity
        # router (higher is better) and a fresh worker's warm-cache
        # time-to-first-result (the persistent AOT compile cache's
        # reason to exist; lower is better).
        ("serving_fleet", "fleet_value", "problems/s",
         "backend", True, "serving_fleet", True),
        ("serve_cold_start", "cold_start_value", "s",
         "backend", False, "serve_cold_start", True),
        # ISSUE 16: steady-state throughput through the elastic
        # two-host fleet — the rate the migration/autoscale/host-kill
        # machinery must not tax.  A brand-new family: until 3 rounds
        # exist its verdict is "insufficient", never a crash or gate.
        ("fleet_elastic", "fleet_elastic_value", "problems/s",
         "backend", True, "fleet_elastic", True),
        # ISSUE 19: throughput through the same fleet under the
        # seeded drop+delay plan — the injected-fault leg is judged
        # as its own family so the retry tax is tracked against
        # faulted rounds only, never against the clean fleet
        # baseline.  A brand-new family: until 3 rounds exist its
        # verdict is "insufficient", never a crash or gate.
        ("fleet_faulted", "fleet_faulted_value", "problems/s",
         "backend", True, "fleet_faulted", True),
        # Host-bound like serve_recovery/session_recovery: on a
        # cpu-resolved round this wall-clock is host compute, so it
        # tracks a host-class change 1:1 (r09: identical trees
        # measured +26% on the shifted box).  Real recovery-path
        # regressions still gate on quiet rounds, and kernel-level
        # slowdowns are caught machine-independently by the golden
        # ratio races in tests/unit/test_perf_regression.py.
        ("shard_recovery", "shard_recovery_value", "s",
         "sharded_backend", False, "sharded", True),
        # ISSUE 17: warm wall-clock of one exact DPOP sweep on the
        # width-bounded seeded instance (ms, LOWER is better) — a
        # brand-new family: until 3 rounds exist its verdict is
        # "insufficient", never a crash or gate.  Host-bound for the
        # same reason as shard_recovery: cpu-resolved wall-ms of a
        # jitted sweep IS host speed; the load-immune dpop kernel
        # gate lives in test_perf_regression.py.
        ("dpop_exact", "dpop_value", "ms", "backend", False,
         "dpop_exact", True),
        # ISSUE 13: the stateful-session families — sustained
        # scenario-event throughput per session (higher is better)
        # and warm time-to-recovered-cost after an event (the
        # session plane's reason to exist: it must stay far below a
        # cold re-solve; lower is better).
        ("session_events", "session_eps_value", "events/s",
         "backend", True, "sessions", True),
        ("session_recovery", "session_ttr_value", "ms",
         "backend", False, "sessions", True),
    )
    # Families that only anchor the host-shift estimator: their
    # regressions never set ``failed`` even when the guard does not
    # fire — the control arm exists to measure the host, not the PR.
    control_families = {"serve_mixed_baseline"}
    series = {}
    lines = []
    failed = False
    # Host-shift guard state: every host-bound GATING series with a
    # judgeable baseline contributes its speed ratio (newest/median
    # for rates, median/newest for latencies — >1 means the host got
    # faster either way); regressions in that population are held
    # here until the common-mode estimator decides whether they gate.
    host_ratios: Dict[str, float] = {}
    host_pending: List[Dict[str, Any]] = []
    for (family, field, unit, backend_key, higher_better,
         leg, host_bound) in metrics:
        # Rates print whole, latencies and fractions keep precision.
        fmt = (".3f" if (not higher_better or unit == "fraction")
               else ".0f")

        def leg_backend(r):
            # The leg's RESOLVED backend when the round recorded one
            # (``leg_backends``, PR 11+); older rounds fall back to
            # their per-run backend field — identical to the pre-leg
            # behavior, so legacy histories judge unchanged.
            base = ((r.get("leg_backends") or {}).get(leg)
                    or r.get(backend_key) or r.get("backend")
                    or "cpu")
            # CPU rates are host-bound: the same code measures ~3x
            # slower on a 1-core box than the 8-core boxes earlier
            # rounds ran on.  Once a round records its core count,
            # its CPU series is keyed ``cpu@<n>`` so it is judged only
            # against same-class hosts — the exact refusal the
            # backend split (ISSUE 14) applies between cpu and tpu.
            # Accelerator backends keep their plain key: their rates
            # are device-bound, not host-core-bound.
            cpus = r.get("host_cpus")
            if base == "cpu" and cpus:
                return f"cpu@{int(cpus)}"
            return base

        rows_f = [r for r in runs
                  if "skipped" not in r and r.get(field) is not None]
        by_backend: Dict[str, List[Dict[str, Any]]] = {}
        for r in rows_f:
            by_backend.setdefault(leg_backend(r), []).append(r)
        # Cross-backend refusal (ISSUE 14): the newest run's leg is
        # judged ONLY against history rows whose recorded leg backend
        # matches its own resolved backend — a CPU-fallback round
        # must neither regress nor pad a TPU baseline.  Rows with an
        # explicit mismatching leg record are named as SKIPPED so the
        # exclusion is visible, not silent.  "Newest" means the
        # newest NUMBERED round: load_history appends the stale
        # BENCH_TPU_LAST reference row last, and a reference artifact
        # with no position in the chronology must not define which
        # backend the latest round "resolved".
        numbered_rows = [
            r for r in rows_f
            if re.fullmatch(r"BENCH_r\d+\.json", r.get("source", ""))
        ]
        newest_row = (numbered_rows[-1] if numbered_rows
                      else rows_f[-1] if rows_f else None)
        newest_backend = (leg_backend(newest_row)
                          if newest_row is not None else None)
        skipped_rows = [
            (r["source"], leg_backend(r)) for r in rows_f
            if (r.get("leg_backends") or {}).get(leg)
            and leg_backend(r) != newest_backend
        ]
        for source, row_backend in skipped_rows:
            lines.append(
                f"{family}[{newest_backend}] SKIPPED {source} "
                f"(leg ran on {row_backend}, newest resolved "
                f"{newest_backend})")
        for backend in sorted(by_backend):
            rows = by_backend[backend]
            values = [r[field] for r in rows]
            result = check_series(values, rel_tol=rel_tol,
                                  mad_mult=mad_mult, window=window,
                                  higher_is_better=higher_better)
            result["values"] = values
            result["sources"] = [r["source"] for r in rows]
            label = (backend if family == "bench"
                     else f"{family}:{backend}")
            series[label] = result
            spark = sparkline(values)
            if result["verdict"] == "insufficient":
                lines.append(
                    f"{family}[{backend}] {spark} "
                    f"{values[0]:{fmt}}→{values[-1]:{fmt}} {unit} — "
                    f"{result['detail']} ({result['points']} run(s))"
                )
                continue
            direction = f"{result['delta_rel']:+.1%}"
            verdict = ("REGRESSED" if result["verdict"] == "regressed"
                       else "OK")
            bound_name = "floor" if higher_better else "ceiling"
            # Only the backend the newest round actually resolved
            # GATES: a stale series (e.g. an old TPU baseline while
            # the newest round fell back to CPU) still reports, but
            # its newest member is an old round that was judged in
            # its own day — failing CI on it would block a round the
            # report itself says was not compared against it.
            stale = (newest_backend is not None
                     and backend != newest_backend)
            result["gating"] = not stale
            line_idx = len(lines)
            lines.append(
                f"{family}[{backend}] {spark} "
                f"{values[0]:{fmt}}→{values[-1]:{fmt}} {unit}, newest "
                f"{direction} vs median {result['median']:{fmt}} "
                f"({bound_name} {result['bound']:{fmt}}) {verdict}"
                + (" (stale backend — not gating)" if stale else "")
            )
            if host_bound and not stale and result["median"]:
                newest_v = result["newest"]
                if higher_better:
                    host_ratios[label] = newest_v / result["median"]
                elif newest_v:
                    host_ratios[label] = result["median"] / newest_v
            if result["verdict"] == "regressed" and not stale:
                if family in control_families:
                    # The control arm's own drop IS the host signal —
                    # it feeds the estimator above, never ``failed``.
                    result["gating"] = False
                elif host_bound:
                    host_pending.append({"label": label,
                                         "result": result,
                                         "line": line_idx})
                else:
                    failed = True
                # The exemplar is the SERVING leg's p99 latency
                # trace_id — only the serve-latency family may point
                # at it (a compile or shard regression has nothing to
                # do with that request).
                exemplar = (rows[-1].get("exemplar")
                            if family == "serve" else None)
                if exemplar:
                    result["exemplar"] = exemplar
                    lines.append(
                        f"  ↳ exemplar trace {exemplar} — open it: "
                        f"pydcop trace query --request {exemplar} "
                        f"<trace file>")
    # Host-shift guard: with enough host-bound series to pool, a
    # common-mode drop (the MEDIAN ratio itself beyond the relative
    # tolerance) means the bench host changed class — the same
    # refusal ``cpu@<n>`` keying applies to core-count changes,
    # detected from the data instead of nproc.  Held host-bound
    # regressions then report as ``host-shift`` without gating; with
    # no shift (an isolated drop cannot move the median) they gate
    # exactly as before.
    estimator = (statistics.median(host_ratios.values())
                 if len(host_ratios) >= HOST_SHIFT_MIN_SERIES
                 else None)
    shift = estimator is not None and estimator < 1.0 - rel_tol
    host_shift = {"fired": shift, "estimator": estimator,
                  "threshold": 1.0 - rel_tol, "ratios": host_ratios}
    if host_pending and shift:
        for pend in host_pending:
            pend["result"]["verdict"] = "host-shift"
            pend["result"]["gating"] = False
            lines[pend["line"]] = (
                lines[pend["line"]].replace(
                    " REGRESSED",
                    " REGRESSED (host-shift — not gating)"))
        held = ", ".join(p["label"] for p in host_pending)
        lines.append(
            f"host-shift guard: median speed ratio "
            f"{estimator:.2f} across {len(host_ratios)} host-bound "
            f"series (incl. the envelope-off control arm) is below "
            f"{1.0 - rel_tol:.2f} — the bench host changed class, "
            f"not the code; held from gating: {held}")
    elif host_pending:
        failed = True
    return {
        "root": root,
        "runs": len(runs),
        "skipped": [r["source"] for r in skipped],
        "series": series,
        "lines": lines,
        "host_shift": host_shift,
        "failed": failed,
    }


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="bench regression sentinel over BENCH_r*.json")
    parser.add_argument(
        "--root",
        default=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))),
        help="directory holding BENCH_r*.json (default: repo root)")
    parser.add_argument("--threshold", type=float,
                        default=DEFAULT_REL_TOL,
                        help="relative regression tolerance "
                             f"(default {DEFAULT_REL_TOL})")
    parser.add_argument("--mad-mult", type=float,
                        default=DEFAULT_MAD_MULT,
                        help="MAD multiples added to the tolerance "
                             f"(default {DEFAULT_MAD_MULT})")
    parser.add_argument("--window", type=int, default=DEFAULT_WINDOW,
                        help="trailing runs in the baseline "
                             f"(default {DEFAULT_WINDOW})")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the full verdict as JSON")
    args = parser.parse_args(argv)

    report = run_check(args.root, rel_tol=args.threshold,
                       mad_mult=args.mad_mult, window=args.window)
    if args.as_json:
        print(json.dumps(report))
        return 1 if report["failed"] else 0
    if not report["series"]:
        print(f"bench_sentinel: no usable bench history under "
              f"{args.root}")
        return 0
    for line in report["lines"]:
        print(line)
    if report["skipped"]:
        print(f"bench_sentinel: skipped unreadable: "
              f"{', '.join(report['skipped'])}")
    if report["failed"]:
        print("bench_sentinel: FAIL — newest run regressed beyond "
              "the noise-aware floor (median - max(rel_tol*median, "
              "mad_mult*MAD) of the trailing window)")
        return 1
    print("bench_sentinel: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Self-healing battery: failure detection, guarded recovery,
checkpoint integrity, partition healing (ISSUE 4 / docs/resilience.md
"Failure detection & recovery").

- Heartbeat health subsystem: phi-accrual estimator + HealthMonitor
  verdict transitions under a FAKE clock (deterministic bounds: dead
  exactly within ``dead_misses`` intervals, suspicion before that,
  recovery on the next beat), plus end-to-end thread runs (a silent
  kill detected by heartbeats and repaired; pure delay never escalates
  past suspicion);
- guarded engine segments: no-trip runs byte-identical to unguarded
  (checkpoint checksums compared), injected trips rolled back
  bit-identically with the escalation ladder (noise -> damping bump ->
  RecoveryExhausted carrying the partial trajectory), all of it
  visible in the exported trace;
- checkpoint integrity: content checksums catch silent corruption,
  truncation falls back to the newest VALID snapshot, retention keeps
  exactly N;
- AsyncCheckpointWriter atexit regression: a failed flush at
  interpreter shutdown is logged, not raised (explicit flush still
  raises);
- partition healing: cross-group traffic resumes at the heal index, a
  pure function of (seed, edge, index);
- multihost coordinator loss: a failed global-mesh participant
  surfaces a clean error, latches nothing, and global_mesh refuses to
  build a wrong single-host mesh.
"""

import os
import threading

import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    CommunicationLayer,
    ComputationMessage,
)
from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.resilience.checkpoint import (
    AsyncCheckpointWriter,
    CheckpointCorruptError,
    CheckpointManager,
    load_state,
    read_meta,
    resume_from_checkpoint,
    verify_checkpoint,
)
from pydcop_tpu.resilience.faults import FaultPlan, FaultyCommunicationLayer
from pydcop_tpu.resilience.health import (
    ALIVE,
    DEAD,
    SUSPECT,
    HealthConfig,
    HealthMonitor,
    PhiAccrualEstimator,
)
from pydcop_tpu.resilience.recovery import (
    GuardViolation,
    RecoveryExhausted,
    RecoveryPolicy,
    RecoveryRun,
    perturb_state,
)

CHAOS_SEED = int(os.environ.get("PYDCOP_CHAOS_SEED", "42"))


# ------------------------------------------------------------------ #
# fixtures


def _ring_dcop(n_vars=6):
    d = Domain("c", "", list(range(3)))
    dcop = DCOP("selfheal", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)] + [(0, 3)]
    for i, j in edges:
        dcop.add_constraint(constraint_from_str(
            f"c{i}_{j}", f"10 if v{i} == v{j} else 0",
            [variables[i], variables[j]],
        ))
    return dcop


def _coloring_dcop(n_agents=5, n_vars=4):
    d = Domain("colors", "", ["R", "G", "B"])
    dcop = DCOP("chaos", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(n_vars - 1):
        dcop.add_constraint(constraint_from_str(
            f"diff_{i}_{i + 1}",
            f"10 if v{i} == v{i + 1} else 0",
            [variables[i], variables[i + 1]],
        ))
    dcop.add_agents([
        AgentDef(f"a{i}", capacity=100, default_hosting_cost=i)
        for i in range(n_agents)
    ])
    return dcop


def _engine():
    from pydcop_tpu.algorithms.maxsum import build_engine

    return build_engine(_ring_dcop(), {})


def _msg(prio=MSG_ALGO, content="x"):
    return ComputationMessage(
        "c_src", "c_dst", Message("test", content), prio)


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------------ #
# phi-accrual estimator


class TestPhiAccrual:
    def test_regular_beats_keep_phi_low(self):
        est = PhiAccrualEstimator(expected=0.1)
        t = 0.0
        for _ in range(10):
            est.beat(t)
            t += 0.1
        # Right on schedule: low suspicion.
        assert est.phi(t, anchor=0.0) < 1.0
        assert est.missed(t, anchor=0.0) == pytest.approx(1.0)

    def test_phi_grows_with_silence(self):
        est = PhiAccrualEstimator(expected=0.1)
        t = 0.0
        for _ in range(10):
            est.beat(t)
            t += 0.1
        last = t - 0.1
        phis = [est.phi(last + dt, anchor=0.0)
                for dt in (0.1, 0.3, 0.6, 1.0)]
        assert phis == sorted(phis)
        assert phis[-1] > 5.0

    def test_no_samples_uses_expected_interval(self):
        est = PhiAccrualEstimator(expected=0.5)
        # Never beat: missed counts from the anchor.
        assert est.missed(101.0, anchor=100.0) == pytest.approx(2.0)

    def test_mean_never_shrinks_below_expected(self):
        est = PhiAccrualEstimator(expected=0.1)
        # A burst of queued beats (delay fault released) lands at
        # near-zero intervals — the estimator must not hair-trigger.
        for t in (0.0, 0.001, 0.002, 0.003):
            est.beat(t)
        assert est.mean_interval() >= 0.1

    def test_missed_uses_configured_interval_not_adaptive_mean(self):
        """The death bound is HARD: a faulty link stretching the
        observed arrival mean must not stretch the miss count with it
        (only phi, the advisory score, adapts)."""
        est = PhiAccrualEstimator(expected=0.1)
        t = 0.0
        for _ in range(10):  # arrivals at 5x the cadence
            est.beat(t)
            t += 0.5
        assert est.mean_interval() == pytest.approx(0.5)
        last = t - 0.5
        # 0.8 s of silence = 8 configured intervals, NOT 1.6 observed.
        assert est.missed(last + 0.8, anchor=0.0) == pytest.approx(8.0)


# ------------------------------------------------------------------ #
# health monitor verdicts (deterministic fake clock)


class TestHealthMonitor:
    def _monitor(self, **kwargs):
        clock = FakeClock()
        config = HealthConfig(interval=0.1, suspect_misses=3,
                              dead_misses=8, **kwargs)
        deaths, suspects = [], []
        monitor = HealthMonitor(
            config, on_dead=deaths.append, on_suspect=suspects.append,
            clock=clock,
        )
        return monitor, clock, deaths, suspects

    def _beat_regularly(self, monitor, clock, agent, n=10, dt=0.1):
        for _ in range(n):
            clock.advance(dt)
            monitor.record(agent, 0)

    def test_alive_while_beating(self):
        monitor, clock, deaths, _ = self._monitor()
        monitor.watch("a1")
        self._beat_regularly(monitor, clock, "a1")
        assert monitor.scan()["a1"] == ALIVE
        assert deaths == []

    def test_silence_escalates_suspect_then_dead_within_bound(self):
        """THE detection bound: suspect after suspect_misses expected
        intervals, dead after dead_misses — never before, always by
        then."""
        monitor, clock, deaths, suspects = self._monitor()
        monitor.watch("a1")
        self._beat_regularly(monitor, clock, "a1")
        clock.advance(0.15)  # 1.5 intervals: still alive
        assert monitor.scan()["a1"] == ALIVE
        clock.advance(0.2)   # 3.5 intervals: suspect, not dead
        assert monitor.scan()["a1"] == SUSPECT
        assert suspects == ["a1"] and deaths == []
        clock.advance(0.4)   # 7.5 intervals: still only suspect
        assert monitor.scan()["a1"] == SUSPECT
        clock.advance(0.1)   # 8.5 intervals: past the dead bound
        assert monitor.scan()["a1"] == DEAD
        assert deaths == ["a1"]
        # Death fires once, even across further scans.
        monitor.scan()
        assert deaths == ["a1"]

    def test_heartbeat_recovers_suspect(self):
        monitor, clock, deaths, _ = self._monitor()
        monitor.watch("a1")
        self._beat_regularly(monitor, clock, "a1")
        clock.advance(0.35)
        assert monitor.scan()["a1"] == SUSPECT
        monitor.record("a1", 99)  # the link was lossy, not dead
        assert monitor.statuses()["a1"] == ALIVE
        statuses = [s for _, a, s in monitor.verdicts if a == "a1"]
        assert statuses == [SUSPECT, ALIVE]
        assert deaths == []

    def test_dead_is_final_despite_zombie_beat(self):
        monitor, clock, deaths, _ = self._monitor()
        monitor.watch("a1")
        clock.advance(10.0)
        assert monitor.scan()["a1"] == DEAD
        monitor.record("a1", 1)  # a delayed beat from the corpse
        assert monitor.statuses()["a1"] == DEAD
        assert deaths == ["a1"]

    def test_never_beaten_agent_dies_from_watch_anchor(self):
        monitor, clock, deaths, _ = self._monitor()
        monitor.watch("a1")
        clock.advance(0.79)  # 7.9 intervals from the watch anchor
        assert monitor.scan()["a1"] == SUSPECT
        clock.advance(0.02)
        assert monitor.scan()["a1"] == DEAD
        assert deaths == ["a1"]

    def test_forget_removed_keeps_dead_record_drops_live(self):
        monitor, clock, _, _ = self._monitor()
        monitor.watch("a1")
        monitor.watch("a2")
        clock.advance(10.0)
        monitor.scan()  # both dead
        monitor.forget_removed("a1")  # dead: record kept
        assert monitor.statuses()["a1"] == DEAD
        monitor.watch("a3")
        monitor.forget_removed("a3")  # live: dropped, no verdict
        assert "a3" not in monitor.statuses()

    def test_straggler_beat_cannot_resurrect_forgotten_agent(self):
        """A delay-faulted heartbeat arriving AFTER the agent was
        removed through the failure path must not auto-watch it back
        into scoring — the ensuing silence would read as a spurious
        death verdict, breaking the verdicts==kills soak invariant."""
        monitor, clock, deaths, _ = self._monitor()
        monitor.watch("a1")
        monitor.forget_removed("a1")  # transport marked it dead first
        monitor.record("a1", 7)       # straggler from the corpse
        clock.advance(10.0)
        assert "a1" not in monitor.scan()
        assert deaths == []
        # An explicit re-watch (scenario re-adds the name) clears the
        # removal and scoring resumes.
        monitor.watch("a1")
        clock.advance(10.0)
        assert monitor.scan()["a1"] == DEAD

    def test_config_validation(self):
        with pytest.raises(ValueError):
            HealthConfig(interval=0)
        with pytest.raises(ValueError):
            HealthConfig(suspect_misses=8, dead_misses=3)

    def test_summary_shape(self):
        monitor, clock, _, _ = self._monitor()
        monitor.watch("a1")
        clock.advance(10.0)
        monitor.scan()
        summary = monitor.summary()
        assert summary["dead"] == ["a1"]
        assert summary["statuses"]["a1"] == DEAD
        assert summary["verdicts"][0]["agent"] == "a1"


# ------------------------------------------------------------------ #
# health end-to-end (thread runtime)


class TestHealthEndToEnd:
    DIST = Distribution({
        "a0": ["v0"], "a1": ["v1"], "a2": ["v2"], "a3": ["v3"],
        "a4": [],
    })

    def test_silent_kill_detected_and_repaired(self):
        """A silently-murdered agent (no failure report from the
        injector) is detected by heartbeats alone; its computation
        migrates and the solve completes at the fault-free cost."""
        from pydcop_tpu.infrastructure.run import solve_with_agents
        from pydcop_tpu.resilience.faults import CrashEvent

        algo = AlgorithmDef.build_with_default_param(
            "adsa", {"stop_cycle": 40, "period": 0.05}, mode="min")
        plan = FaultPlan(seed=CHAOS_SEED,
                         crashes=(CrashEvent("a1", 5),), replicas=2)
        res = solve_with_agents(
            _coloring_dcop(), algo, distribution=self.DIST,
            timeout=45, fault_plan=plan,
            health_config=HealthConfig(),
        )
        assert res["killed_agents"] == ["a1"]
        assert res["health"]["dead"] == ["a1"]
        assert res["status"] == "FINISHED"
        assert res["cost"] == 0
        assert set(res["assignment"]) == {"v0", "v1", "v2", "v3"}

    def test_lossy_link_never_escalates_past_suspicion(self):
        """Drop + delay with NO kill: zero agent_dead verdicts —
        suspicion is allowed (that is the phi detector working)."""
        from pydcop_tpu.infrastructure.run import solve_with_agents

        algo = AlgorithmDef.build_with_default_param(
            "adsa", {"stop_cycle": 20, "period": 0.05}, mode="min")
        plan = FaultPlan(seed=CHAOS_SEED, drop=0.10, delay=0.10,
                         delay_time=0.03)
        res = solve_with_agents(
            _coloring_dcop(), algo, distribution=self.DIST,
            timeout=20, fault_plan=plan,
            health_config=HealthConfig(),
        )
        assert res["health"]["dead"] == []
        assert res["cost"] == 0

    def test_health_rejects_process_mode(self):
        from pydcop_tpu.infrastructure.run import solve_with_agents

        with pytest.raises(ValueError, match="thread"):
            solve_with_agents(
                _coloring_dcop(), "dsa", distribution=self.DIST,
                mode="process", health_config=HealthConfig(),
            )


# ------------------------------------------------------------------ #
# guarded engine segments


class TestGuardedSegments:
    def test_no_trip_bit_identical_to_unguarded(self, tmp_path):
        """Guards are pure reads: with nothing injected, the guarded
        run's final snapshot is BYTE-identical to the unguarded one
        (content checksums compared), and assignment/cycles match."""
        ref_mgr = CheckpointManager(str(tmp_path / "ref"), every=7)
        ref = _engine().run_checkpointed(
            max_cycles=100, manager=ref_mgr, checkpoint_async=False)
        guard_mgr = CheckpointManager(str(tmp_path / "g"), every=7)
        res = _engine().run_checkpointed(
            max_cycles=100, manager=guard_mgr, checkpoint_async=False,
            recovery=RecoveryPolicy())
        assert res.metrics["guard_trips"] == 0
        assert res.assignment == ref.assignment
        assert res.cycles == ref.cycles
        assert res.converged == ref.converged
        ref_meta = read_meta(ref_mgr.latest())
        g_meta = read_meta(guard_mgr.latest())
        assert ref_meta["cycle"] == g_meta["cycle"]
        assert ref_meta["checksum"] == g_meta["checksum"]

    def test_injected_trip_recovers_and_traces(self, tmp_path):
        """Guard-trip injection at cycle c: rollback restores the last
        snapshot bit-identically (verify_restore asserts in-line), the
        attempt counter lands in result metrics, and guard_trip +
        recovery_rollback events appear in the exported trace."""
        from pydcop_tpu.observability.trace import (
            load_trace_file,
            tracer,
        )

        trace_path = str(tmp_path / "trip.trace.json")
        tracer.enable()
        try:
            res = _engine().run_checkpointed(
                max_cycles=120, segment_cycles=7,
                recovery=RecoveryPolicy(trip_cycles=(14,),
                                        verify_restore=True),
            )
        finally:
            tracer.disable()
            tracer.export(trace_path, "chrome")
        assert res.metrics["guard_trips"] == 1
        assert res.metrics["recovery_attempts"] == 1
        assert res.metrics["recovery_actions"] == ["reseed_noise"]
        assert res.metrics["guard_violations"][0]["kind"] == "injected"
        assert res.converged
        names = [e["name"] for e in load_trace_file(trace_path)]
        assert "guard_trip" in names
        assert "recovery_rollback" in names

    def test_escalation_ladder_order_and_damping_bump(self):
        """Attempt 1 reseeds noise, attempt 2 bumps damping (and the
        bumped segment program is a fresh compile, not a stale
        cache hit — the run would diverge from the damping change
        otherwise)."""
        engine = _engine()
        base_damping = engine.damping
        res = engine.run_checkpointed(
            max_cycles=200, segment_cycles=7,
            recovery=RecoveryPolicy(trip_cycles=(7, 7),
                                    max_restarts=3),
        )
        assert res.metrics["recovery_actions"] == [
            "reseed_noise", "damping_bump"]
        assert engine.damping == pytest.approx(base_damping + 0.2)
        assert res.converged

    def test_budget_exhaustion_carries_partial(self):
        engine = _engine()
        with pytest.raises(RecoveryExhausted) as exc:
            # stop_on_convergence=False pins segment ends to 7, 14,
            # 21... so the repeated cycle-14 injection re-fires on
            # every re-run until the budget is spent (a converging
            # segment could otherwise stop short of the trip cycle).
            engine.run_checkpointed(
                max_cycles=200, segment_cycles=7,
                stop_on_convergence=False,
                recovery=RecoveryPolicy(trip_cycles=(14,) * 6,
                                        max_restarts=2),
            )
        err = exc.value
        assert err.attempts == 3
        assert len(err.violations) == 3
        # Trips hit at cycle 14, after segment 7 validated: the
        # partial trajectory carries the last VALID state.
        assert err.partial["cycle"] == 7
        assert err.partial["assignment"] is not None
        assert set(err.partial["assignment"]) == {
            f"v{i}" for i in range(6)}

    def test_nan_guard_detects_poisoned_state(self):
        """The device-side guard flags a NaN in any float leaf."""
        import jax
        import jax.numpy as jnp

        engine = _engine()
        state = engine.init_state()
        values = jnp.zeros(
            (len(engine.meta.var_names),), dtype=jnp.int32)
        finite, _ = jax.device_get(
            engine._guard_fn()(engine.graph, state, values))
        assert bool(finite)
        poisoned = state._replace(
            v2f=tuple(m.at[0].set(jnp.nan) for m in state.v2f))
        finite, _ = jax.device_get(
            engine._guard_fn()(engine.graph, poisoned, values))
        assert not bool(finite)

    def test_nan_trip_rolls_back_to_valid_state(self):
        """End to end: a NaN planted in the state AFTER a validated
        segment trips the nonfinite guard and recovery restarts from
        the clean snapshot — the solve still converges."""
        engine = _engine()
        rec_holder = {}
        original_retain = RecoveryRun.retain

        def poisoning_retain(self, state, values):
            original_retain(self, state, values)
            rec_holder.setdefault("rec", self)

        # Inject the NaN through the guard's own check path: plant it
        # by flipping the first validated snapshot's successor. The
        # simplest honest injection: monkeypatch check() to report
        # nonfinite exactly once.
        original_check = RecoveryRun.check
        fired = []

        def nan_once_check(self, end_cycle, finite, cost):
            if not fired and end_cycle >= 14:
                fired.append(end_cycle)
                return GuardViolation(
                    "nonfinite", end_cycle, "injected NaN")
            return original_check(self, end_cycle, finite, cost)

        RecoveryRun.retain = poisoning_retain
        RecoveryRun.check = nan_once_check
        try:
            res = engine.run_checkpointed(
                max_cycles=120, segment_cycles=7,
                recovery=RecoveryPolicy())
        finally:
            RecoveryRun.retain = original_retain
            RecoveryRun.check = original_check
        assert res.metrics["guard_trips"] == 1
        assert res.metrics["guard_violations"][0]["kind"] == \
            "nonfinite"
        assert res.converged

    def test_divergence_window_trips(self):
        """RecoveryRun.check verdicts: a window of costs all above
        factor * best trips the divergence guard; recovering costs do
        not."""
        policy = RecoveryPolicy(divergence_window=3,
                                divergence_factor=2.0)
        rec = RecoveryRun(policy, _engine())
        assert rec.check(10, True, 10.0) is None   # establishes best
        assert rec.check(20, True, 12.0) is None
        assert rec.check(30, True, 15.0) is None   # window below 20
        violation = rec.check(40, True, 50.0)
        assert violation is None  # window = [12, 15, 50]: min 12 < 20
        for cycle, cost in ((50, 30.0), (60, 40.0)):
            violation = rec.check(cycle, True, cost)
        assert violation is not None
        assert violation.kind == "divergence"

    def test_perturb_state_is_seeded_and_clears_stable(self):
        import jax
        import jax.numpy as jnp

        engine = _engine()
        state = engine.init_state()
        state = state._replace(stable=jnp.asarray(True))
        p1 = perturb_state(state, 1e-3, seed=7)
        p2 = perturb_state(state, 1e-3, seed=7)
        p3 = perturb_state(state, 1e-3, seed=8)
        assert not bool(p1.stable)
        l1 = jax.device_get(jax.tree_util.tree_leaves(p1))
        l2 = jax.device_get(jax.tree_util.tree_leaves(p2))
        l3 = jax.device_get(jax.tree_util.tree_leaves(p3))
        for a, b in zip(l1, l2):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert any(
            not np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(l1, l3)
        )

    def test_api_solve_with_recovery(self, tmp_path):
        from pydcop_tpu.api import solve

        dcop = _ring_dcop()
        ref = solve(dcop, "maxsum", backend="device", max_cycles=100)
        res = solve(
            dcop, "maxsum", backend="device", max_cycles=100,
            recovery=RecoveryPolicy(),
        )
        assert res["assignment"] == ref["assignment"]
        assert res["metrics"]["guard_trips"] == 0
        with pytest.raises(ValueError, match="device"):
            solve(dcop, "maxsum", backend="thread",
                  recovery=RecoveryPolicy())


# ------------------------------------------------------------------ #
# checkpoint integrity


class TestCheckpointIntegrity:
    def test_checksum_written_and_verified(self, tmp_path):
        engine = _engine()
        manager = CheckpointManager(str(tmp_path), every=5)
        manager.save(engine.init_state(), 5)
        meta = verify_checkpoint(manager.path_for(5))
        assert len(meta["checksum"]) == 64

    def test_flipped_byte_detected(self, tmp_path):
        import json

        engine = _engine()
        manager = CheckpointManager(str(tmp_path), every=5)
        path = manager.save(engine.init_state(), 5)
        with np.load(path, allow_pickle=False) as data:
            meta = json.loads(str(data["__meta__"]))
            arrays = {k: data[k].copy() for k in data.files
                      if k != "__meta__"}
        flat = arrays["leaf_0"].reshape(-1).view(np.uint8)
        flat[0] ^= 0xFF
        with open(path, "wb") as f:
            np.savez(f, __meta__=json.dumps(meta), **arrays)
        with pytest.raises(CheckpointCorruptError):
            verify_checkpoint(path)
        with pytest.raises(CheckpointCorruptError):
            load_state(path, engine.init_state())
        # latest() must skip it entirely.
        assert manager.latest() is None

    def test_truncated_newest_falls_back_on_resume(self, tmp_path,
                                                   caplog):
        """THE corruption-safety criterion: truncate the newest
        snapshot mid-file (a torn async write); resume comes from the
        previous valid snapshot, with a warning, and reproduces the
        uninterrupted run."""
        import logging

        dcop = _ring_dcop()
        from pydcop_tpu.algorithms.maxsum import build_engine

        ref = build_engine(dcop, {}).run(max_cycles=100)
        manager = CheckpointManager(str(tmp_path), every=5, keep=3)
        build_engine(dcop, {}).run_checkpointed(
            max_cycles=100, manager=manager, max_segments=2)
        cycles = [c for c, _ in manager.checkpoints()]
        assert cycles == [5, 10]
        newest = manager.path_for(10)
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 2)
        with caplog.at_level(logging.WARNING,
                             logger="pydcop.resilience.checkpoint"):
            res = resume_from_checkpoint(
                build_engine(dcop, {}), manager, max_cycles=100)
        assert res.metrics["resumed_from_cycle"] == 5
        assert res.assignment == ref.assignment
        assert res.cycles == ref.cycles
        assert any("falling back" in r.message for r in caplog.records)

    def test_retention_keeps_exactly_n(self, tmp_path):
        engine = _engine()
        manager = CheckpointManager(str(tmp_path), every=5, keep=3)
        state = engine.init_state()
        for cycle in (5, 10, 15, 20, 25):
            manager.save(state, cycle)
        assert [c for c, _ in manager.checkpoints()] == [15, 20, 25]

    def test_structural_mismatch_still_aborts_resume(self, tmp_path):
        """Only CORRUPTION falls back; resuming the wrong problem is a
        caller error and must abort loudly, never silently restart
        from cycle 0 (which would also let retention GC the other
        problem's snapshots)."""
        from pydcop_tpu.algorithms.maxsum import build_engine

        manager = CheckpointManager(str(tmp_path), every=5)
        build_engine(_ring_dcop(6), {}).run_checkpointed(
            max_cycles=100, manager=manager, max_segments=1)
        other_engine = build_engine(_ring_dcop(4), {})
        with pytest.raises(ValueError, match="wrong problem"):
            resume_from_checkpoint(other_engine, manager,
                                   max_cycles=100)

    def test_first_segment_trip_with_max_segments_returns(self):
        """A guard trip on the very first segment + a max_segments
        interrupt: no validated values exist yet — the result must
        still come back (value selection computed without stepping),
        not crash on a None fetch."""
        res = _engine().run_checkpointed(
            max_cycles=100, segment_cycles=7, max_segments=1,
            recovery=RecoveryPolicy(trip_cycles=(1,)),
        )
        assert res.metrics["interrupted"]
        assert res.metrics["guard_trips"] == 1
        assert res.cycles == 0  # rolled back to the initial snapshot
        assert set(res.assignment) == {f"v{i}" for i in range(6)}

    def test_api_checkpoint_keep_knob(self, tmp_path):
        from pydcop_tpu.api import solve

        solve(_ring_dcop(), "maxsum", backend="device",
              max_cycles=100, checkpoint_dir=str(tmp_path),
              checkpoint_every=5, checkpoint_keep=1)
        snapshots = [f for f in os.listdir(tmp_path)
                     if f.startswith("ckpt_")]
        assert len(snapshots) == 1


# ------------------------------------------------------------------ #
# AsyncCheckpointWriter atexit regression


class TestAsyncWriterAtexit:
    def _failing_writer(self, tmp_path, monkeypatch):
        from pydcop_tpu.resilience import checkpoint as ckpt_mod

        manager = CheckpointManager(str(tmp_path), every=5)

        def boom(*args, **kwargs):
            raise OSError("disk full")

        monkeypatch.setattr(ckpt_mod, "save_state", boom)
        return AsyncCheckpointWriter(manager)

    def test_atexit_drain_swallows_and_logs(self, tmp_path,
                                            monkeypatch, caplog):
        """An exception during the atexit flush must be logged, never
        re-raised into interpreter shutdown."""
        import logging

        writer = self._failing_writer(tmp_path, monkeypatch)
        writer.submit({"x": np.zeros(3)}, 5)
        with caplog.at_level(logging.ERROR,
                             logger="pydcop.resilience.checkpoint"):
            writer._close_at_exit()  # must NOT raise
        assert any("interpreter shutdown" in r.message
                   for r in caplog.records)

    def test_explicit_flush_still_raises(self, tmp_path, monkeypatch):
        writer = self._failing_writer(tmp_path, monkeypatch)
        writer.submit({"x": np.zeros(3)}, 5)
        with pytest.raises(RuntimeError, match="checkpoint write"):
            writer.flush()

    def test_explicit_close_still_raises(self, tmp_path, monkeypatch):
        writer = self._failing_writer(tmp_path, monkeypatch)
        writer.submit({"x": np.zeros(3)}, 5)
        with pytest.raises(RuntimeError, match="checkpoint write"):
            writer.close()


# ------------------------------------------------------------------ #
# partition healing


class RecordingLayer(CommunicationLayer):
    def __init__(self):
        super().__init__()
        self.sent = []

    @property
    def address(self):
        return self

    def send_msg(self, src_agent, dest_agent, msg, on_error=None):
        self.sent.append((src_agent, dest_agent, msg))


class TestPartitionHealing:
    def test_cross_traffic_resumes_at_heal_index(self):
        plan = FaultPlan(
            partitions=(frozenset({"a"}), frozenset({"b"})),
            partition_heal_index=5,
        )
        inner = RecordingLayer()
        layer = FaultyCommunicationLayer(inner, plan)
        for i in range(10):
            layer.send_msg("a", "b", _msg(content=i))
        # Messages 0-4 blocked, 5-9 delivered.
        assert [m.msg.content for _, _, m in inner.sent] == \
            [5, 6, 7, 8, 9]
        assert layer.stats.partitioned == 5

    def test_heal_is_per_edge(self):
        plan = FaultPlan(
            partitions=(frozenset({"a"}), frozenset({"b", "c"})),
            partition_heal_index=2,
        )
        inner = RecordingLayer()
        layer = FaultyCommunicationLayer(inner, plan)
        layer.send_msg("a", "b", _msg(content="b0"))  # blocked
        layer.send_msg("a", "c", _msg(content="c0"))  # blocked
        layer.send_msg("a", "b", _msg(content="b1"))  # blocked
        layer.send_msg("a", "b", _msg(content="b2"))  # healed (idx 2)
        layer.send_msg("a", "c", _msg(content="c1"))  # still blocked
        assert [m.msg.content for _, _, m in inner.sent] == ["b2"]

    def test_unhealed_partition_blocks_forever(self):
        plan = FaultPlan(partitions=(frozenset({"a"}),
                                     frozenset({"b"})))
        assert plan.is_partitioned("a", "b", index=10 ** 6)

    def test_decision_is_pure_function_of_index(self):
        plan = FaultPlan(
            partitions=(frozenset({"a"}), frozenset({"b"})),
            partition_heal_index=3,
        )
        assert plan.is_partitioned("a", "b", 2)
        assert not plan.is_partitioned("a", "b", 3)
        # Same answers on re-query: no hidden state.
        assert plan.is_partitioned("a", "b", 2)


# ------------------------------------------------------------------ #
# multihost coordinator loss


class TestMultihostCoordinatorLoss:
    @pytest.fixture()
    def multihost(self):
        from pydcop_tpu.engine import multihost as mh

        was_initialized = mh._initialized
        mh._reset_initialized()
        yield mh
        mh._initialized = was_initialized

    def test_coordinator_loss_surfaces_clean_error_no_latch(
            self, multihost, monkeypatch):
        """A participant losing the coordinator mid-join gets a
        bounded, clean RetryExhaustedError (no hang: attempts are
        capped), the partial client is torn down, and the module never
        latches — a later successful join works."""
        import jax

        from pydcop_tpu.resilience.retry import (
            RetryExhaustedError,
            RetryPolicy,
        )

        shutdowns = []

        def lost_coordinator(**kwargs):
            raise RuntimeError(
                "DEADLINE_EXCEEDED: coordinator heartbeat lost")

        monkeypatch.setattr(
            jax.distributed, "initialize", lost_coordinator)
        monkeypatch.setattr(
            jax.distributed, "shutdown",
            lambda: shutdowns.append(1))
        with pytest.raises(RetryExhaustedError):
            multihost.initialize_multihost(
                coordinator_address="127.0.0.1:65501",
                num_processes=2, process_id=1,
                retry_policy=RetryPolicy(max_attempts=2,
                                         base_delay=0.01, jitter=0.0),
            )
        assert not multihost.multihost_initialized()
        assert shutdowns, "partial distributed client not torn down"
        # The loss did not latch: a later join succeeds.
        monkeypatch.setattr(
            jax.distributed, "initialize", lambda **kw: None)
        multihost.initialize_multihost(
            coordinator_address="127.0.0.1:65501",
            num_processes=1, process_id=0,
            retry_policy=RetryPolicy(max_attempts=1),
        )
        assert multihost.multihost_initialized()

    def test_global_mesh_refuses_unjoined_configured_env(
            self, multihost, monkeypatch):
        """With the environment configured for multihost but the join
        failed, global_mesh must raise a clean error — NOT silently
        build a single-host mesh that computes a wrong answer."""
        monkeypatch.setenv("PYDCOP_NUM_PROCESSES", "2")
        assert multihost.multihost_configured()
        with pytest.raises(RuntimeError, match="not.*initialized"):
            multihost.global_mesh()

    def test_global_mesh_works_single_host(self, multihost,
                                           monkeypatch):
        for var in ("PYDCOP_COORDINATOR", "PYDCOP_NUM_PROCESSES",
                    "PYDCOP_MULTIHOST"):
            monkeypatch.delenv(var, raising=False)
        assert not multihost.multihost_configured()
        mesh = multihost.global_mesh()
        assert mesh is not None

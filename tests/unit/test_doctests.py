"""Run every pydcop_tpu module's doctests as part of the suite.

Reference parity: the reference Makefile runs
``pytest --doctest-modules ./pydcop`` (Makefile:8-24); this keeps the
same guarantee inside the normal `pytest tests/` invocation.
"""

import doctest
import importlib
import pkgutil

import pydcop_tpu


def _walk_modules():
    for info in pkgutil.walk_packages(
        pydcop_tpu.__path__, prefix="pydcop_tpu."
    ):
        yield info.name


def test_all_module_doctests():
    total_failures = []
    for name in _walk_modules():
        module = importlib.import_module(name)
        result = doctest.testmod(module, verbose=False)
        if result.failed:
            total_failures.append((name, result.failed))
    assert not total_failures, f"doctest failures: {total_failures}"

"""Pin the `start_messages` fixed-point claim (VERDICT r4 weak #7).

The device engine fires every factor and variable each cycle —
``start_messages=all`` semantics — and algorithms/maxsum.py documents
that the reference's other start schedules (`leafs`, `leafs_vars`,
reference maxsum.py start modes) change only the transient, not the
fixed point.  That claim was documentation until now; this battery
executes all three schedules with the agent-mode message math
(factor_costs_for_var / costs_for_factor — the exact functions agent
computations send with) under an explicit host scheduler, on tree
factor graphs where min-sum converges exactly, and asserts:

- every schedule reaches a message fixed point,
- the fixed-point messages are IDENTICAL across schedules (same dicts,
  same floats — converged inputs flow through the same summation
  order),
- the selected assignment and its DCOP cost are identical across
  schedules,
- the device engine (start=all by construction) selects an assignment
  with the same cost.

Loopy graphs are excluded on purpose: min-sum has no schedule-
independent fixed-point guarantee there (the docstring's claim is
about convergent problems, and the bench's quality legs cover loopy
behavior separately).
"""

import numpy as np
import pytest

from pydcop_tpu.computations_graph.factor_graph import (
    build_computation_graph,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, VariableWithCostDict
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.infrastructure.agent_algorithms import (
    costs_for_factor,
    factor_costs_for_var,
    select_value,
)

D = 3


def tree_dcop(n_vars: int, seed: int):
    """Random tree 3-coloring with random binary tables and random
    unary costs (unique optimum with overwhelming probability, so
    assignment equality is meaningful)."""
    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", list(range(D)))
    dcop = DCOP(f"start_{n_vars}_{seed}", objective="min")
    variables = []
    for i in range(n_vars):
        costs = {d: round(float(rng.random()), 3) for d in dom.values}
        v = VariableWithCostDict(f"v{i}", dom, costs)
        variables.append(v)
        dcop.add_variable(v)
    for i in range(1, n_vars):
        p = int(rng.integers(0, i))
        dcop.add_constraint(NAryMatrixRelation(
            [variables[p], variables[i]],
            rng.random((D, D)).round(3), f"c{i}"))
    return dcop


def run_host_schedule(dcop: DCOP, start: str, max_cycles: int = 200):
    """Reference-style dict message passing under an explicit start
    schedule.  A node sends from cycle 0 if the schedule includes it,
    and from the cycle after it first receives a message otherwise.
    Returns (messages_fixed_point, assignment, cost, cycles_used,
    first_cycle_senders) — the latter is the set of nodes that spoke
    in cycle 0, i.e. the schedule's observable difference.
    """
    cg = build_computation_graph(dcop)
    factors = {n.factor.name: n.factor for n in cg.nodes
               if hasattr(n, "factor")}
    variables = {v.name: v for v in dcop.variables.values()}
    # Adjacency from the graph itself.
    var_factors = {name: [] for name in variables}
    for fname, factor in factors.items():
        for v in factor.dimensions:
            var_factors[v.name].append(fname)
    degree = {**{f: len(factors[f].dimensions) for f in factors},
              **{v: len(var_factors[v]) for v in variables}}

    if start == "all":
        active = set(degree)
    elif start == "leafs":
        active = {n for n, deg in degree.items() if deg == 1}
    elif start == "leafs_vars":
        active = {v for v in variables if degree[v] == 1}
    else:
        raise ValueError(start)
    if not active:
        raise AssertionError("degenerate tree: no start nodes")

    recv = {n: {} for n in degree}      # node -> {sender: costs}
    prev_msgs = None
    first_cycle_senders = frozenset(active)
    for cycle in range(max_cycles):
        sends = []                      # (src, dst, costs)
        for fname in factors:
            if fname not in active:
                continue
            factor = factors[fname]
            for v in factor.dimensions:
                sends.append((fname, v.name, factor_costs_for_var(
                    factor, v, recv[fname], "min")))
        for vname in variables:
            if vname not in active:
                continue
            for fname in var_factors[vname]:
                sends.append((vname, fname, costs_for_factor(
                    variables[vname], fname, var_factors[vname],
                    recv[vname])))
        for src, dst, costs in sends:
            recv[dst][src] = costs
            active.add(dst)             # receiving activates a node
        msgs = {(s, d): tuple(sorted(c.items()))
                for s, d, c in sends}
        if prev_msgs is not None and msgs == prev_msgs \
                and len(active) == len(degree):
            break
        prev_msgs = msgs
    else:
        raise AssertionError(f"no fixed point within {max_cycles}")

    assignment = {}
    for vname, v in variables.items():
        value, _ = select_value(v, recv[vname], "min")
        assignment[vname] = value
    cost, _ = dcop.solution_cost(assignment)
    return msgs, assignment, cost, cycle, first_cycle_senders


@pytest.mark.parametrize("seed", [2, 9, 31])
def test_all_three_schedules_share_one_fixed_point(seed):
    dcop = tree_dcop(16, seed)
    results = {
        start: run_host_schedule(dcop, start)
        for start in ("all", "leafs", "leafs_vars")
    }
    msgs_all, asg_all, cost_all, _, _ = results["all"]
    for start in ("leafs", "leafs_vars"):
        msgs, asg, cost, _, _ = results[start]
        assert msgs.keys() == msgs_all.keys()
        for edge in msgs_all:
            got = dict(msgs[edge])
            want = dict(msgs_all[edge])
            assert got.keys() == want.keys()
            for d in want:
                assert got[d] == pytest.approx(want[d], abs=1e-9), (
                    f"start={start} message {edge} value {d} diverged")
        assert asg == asg_all, f"start={start} assignment diverged"
        assert cost == pytest.approx(cost_all)


@pytest.mark.parametrize("seed", [2, 9])
def test_schedules_differ_in_the_transient_only(seed):
    """The schedules are genuinely different processes — their first
    cycle sends different message sets (leafs-start: only leaves
    speak; all-start: everyone does) — so the shared fixed point above
    is a non-trivial result, not three runs of the same code path.
    (Direction of convergence speed is NOT asserted: measured here,
    leafs-start can converge FASTER than all-start — it is the exact
    leaf-to-root-and-back sweep, while all-start emits interior junk
    waves that take extra cycles to wash out.)"""
    dcop = tree_dcop(16, seed)
    senders = {
        start: run_host_schedule(dcop, start)[4]
        for start in ("all", "leafs", "leafs_vars")
    }
    # Cycle-0 sender sets are nested: leaf variables ⊆ leaf nodes ⊂
    # all nodes (binary factors have degree 2, so the two leaf sets
    # coincide here) — the schedules are observably different
    # processes on the same problem.
    assert senders["leafs_vars"] <= senders["leafs"] < senders["all"]


@pytest.mark.parametrize("seed", [2, 9, 31])
def test_device_engine_matches_the_shared_fixed_point(seed):
    from pydcop_tpu.api import solve

    dcop = tree_dcop(16, seed)
    _, _, host_cost, _, _ = run_host_schedule(dcop, "all")
    res = solve(dcop, "maxsum", max_cycles=120,
                algo_params={"noise": 0.0})
    assert res["cost"] == pytest.approx(host_cost, abs=1e-4), (
        "device (start=all by construction) must land on the same "
        "fixed-point cost the schedule family shares")

"""The BSP engine runner: jit-compiles and executes algorithm loops.

This replaces the reference's orchestrator + thread-per-agent runtime
(pydcop/infrastructure/run.py:145 run_local_thread_dcop) for on-device
execution: the whole solve — message updates, damping, convergence test,
value selection — is one XLA program; the host only launches it and reads
back the result.
"""

import sys
import time
from dataclasses import dataclass, field
from functools import partial
from typing import Any, Dict, NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from pydcop_tpu.engine import aotcache
from pydcop_tpu.engine.compile import (
    BIG,
    CompiledFactorGraph,
    FactorGraphMeta,
)
from pydcop_tpu.engine.sharding import make_mesh, shard_graph
from pydcop_tpu.engine.timing import sync
from pydcop_tpu.observability.efficiency import (
    tracker as efficiency_tracker,
)
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.profiler import key_str, profiler
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.ops import maxsum as maxsum_ops
from pydcop_tpu.ops import maxsum_lane as lane_ops


@dataclass(frozen=True)
class DecimationPlan:
    """Segmented decimation policy (Improving Max-Sum through
    Decimation, arXiv:1706.02209): at every segment boundary — where
    the host already syncs for guards/probes, so the jitted loop gains
    ZERO new syncs — variables whose belief margin (gap between best
    and second-best value) clears ``margin`` are CLAMPED to their
    selected value (unary costs overwritten with BIG everywhere else,
    the one-hot-constant-message form the kernels already respect),
    shrinking the set of edges still doing useful work round by round.

    ``margin``: threshold a variable's margin must exceed to clamp
    (0 = pure top-fraction selection, the classic decimation schedule).
    ``frac_per_round``: cap on the fraction of ALL variables newly
    clamped per boundary.  ``force_progress``: clamp the top-margin
    free variable even when none clears the threshold — guarantees the
    classic schedule terminates with everything fixed; threshold mode
    (margin > 0) leaves it False so only genuinely confident variables
    ever clamp.  ``cycles_per_round``: segment length used when the
    caller does not impose one (checkpoint cadence wins when present).
    """

    margin: float = 0.0
    frac_per_round: float = 0.1
    force_progress: bool = True
    cycles_per_round: int = 60


class DecimationState(NamedTuple):
    """Checkpoint payload of a decimated run: solver state + the clamp
    bookkeeping that must travel with it.  A snapshot missing the
    clamp set would resume message passing against un-clamped unary
    costs — a silently different problem; bundling them makes
    resume-mid-decimation reproduce the uninterrupted run (asserted
    in tests/unit/test_workreduction_battery.py)."""

    solver: Any           # MaxSumState
    fixed: Any            # [V] bool — clamped variables
    var_costs: Any        # [V+1, D] f32 — current (clamped) table


@dataclass
class DeviceRunResult:
    """Result of an on-device solve.

    Timing convention (uniform across all engines): ``time_s`` is the
    total wall time of the engine call, INCLUDING any jit compile that
    happened inside it; ``compile_time_s`` is the compile portion when
    it was separately measurable, else it EQUALS ``time_s`` (the two
    fields overlap — never sum them) and ``metrics['cold_start']`` is
    True.  Callers that need steady-state execution time (benchmarks)
    warm the engine up with an identical call first; the warm call has
    ``compile_time_s == 0``."""

    assignment: Dict[str, Any]
    cycles: int
    converged: bool
    time_s: float
    compile_time_s: float
    metrics: Dict[str, Any] = field(default_factory=dict)


def timed_jit_call(warm: set, key, fn, *args):
    """Execute a cached-jit function, splitting compile from run time.

    Plain jit dispatch, NOT ``fn.lower(...).compile()``: the AOT
    execute path measured ~1500x slower per call through the axon TPU
    tunnel (it re-ships argument buffers per call), it recompiles on
    every call (lower/compile bypasses the jit cache), and it freezes
    input placements, which breaks feeding device-resident state back
    in on mesh runs.  The first call per ``key`` includes trace+compile
    and reports the whole elapsed interval as BOTH compile and run time
    (the DeviceRunResult overlapping-fields convention; compile
    dominates); warm calls report (0, elapsed).

    Completion is forced with engine.timing.sync, not
    ``jax.block_until_ready`` — the axon tunnel implements the latter
    as a partial/no-op sync, which silently turns run times into
    enqueue times (see timing module docstring).

    Returns (out, compile_s, run_s).
    """
    first = key not in warm
    # Cost attribution happens BEFORE the timer: the profiler's
    # throwaway AOT compile must never pollute the measured interval,
    # and it must run before the dispatch below donates ``args``'
    # buffers (the profiler only reads avals, but they come from the
    # live arrays).
    entry = None
    if first and profiler.enabled:
        entry = profiler.capture(key, fn, args)
    # Persistent-cache attribution (engine/aotcache.py): snapshot the
    # disk-cache counters around a cold dispatch so a first call whose
    # executables all deserialized from disk reports the retrieval
    # wall — not the whole interval — as its compile component.
    aot_before = aotcache.counters() if first and aotcache.enabled() \
        else None
    t0 = time.perf_counter()
    span = None
    # Cold dispatches record on ``tracer.active`` (a recompile storm
    # is exactly the signal a flight-recorder postmortem needs); warm
    # dispatches only under a file session — in flight-only mode the
    # enclosing engine_segment span already marks every segment, and
    # the redundant per-segment event would eat the ring AND the ≤5%
    # overhead budget gated in make perf-smoke.
    if tracer.enabled or (first and tracer.active):
        span = tracer.span("jit_compile" if first else "engine_call",
                           "engine", key=str(key))
        with span:
            out = sync(fn(*args))
    else:
        out = sync(fn(*args))
    elapsed = time.perf_counter() - t0
    if entry is not None and span is not None:
        # The recorded event holds this args dict BY REFERENCE until
        # export, so measured cost lands in the jit_compile span
        # without widening the timed window.
        span.args["xla_cost"] = {
            k: v for k, v in entry.items() if k != "capture_s"
        }
    if metrics_registry.active:
        _account_jit_call(str(key), first, elapsed)
    if first:
        warm.add(key)
        disk_compile = None
        if aot_before is not None:
            disk_compile = aotcache.split_cold_call(
                elapsed, aot_before, aotcache.counters())
        # Efficiency plane (observability/efficiency.py): global
        # cold/warm dispatch accounting — the compile column of
        # waste-by-cause, covering every engine that routes through
        # this one chokepoint.  The disk-attributed compile (when
        # available) goes to the tracker too, or /profile's compile
        # waste would keep charging whole cold intervals the
        # persistent cache actually saved.
        efficiency_tracker.record_jit(str(key), first, elapsed,
                                      compile_s=disk_compile)
        if disk_compile is not None:
            # Every executable came off the disk cache: the cold
            # interval holds trace + retrieval + first run, with
            # zero XLA compile — charge only the retrieval wall
            # to ``compile`` so the cold-start ledger says what
            # actually happened.
            return out, disk_compile, elapsed
        return out, elapsed, elapsed
    efficiency_tracker.record_jit(str(key), first, elapsed)
    return out, 0.0, elapsed


def launch_jit_call(warm: set, key, fn, *args):
    """Async-launch a WARM cached-jit dispatch without forcing
    completion (JAX async dispatch: the call returns device futures
    almost immediately while the backend executes).  The pipelined
    serving path uses this to issue dispatch k+1 while dispatch k's
    results are still in flight; :func:`finish_jit_call` later forces
    completion and performs exactly the accounting a warm
    :func:`timed_jit_call` would have.

    Only valid for warm keys: a cold launch would hide trace+compile
    inside an unattributed wait (and the profiler/aotcache cold-call
    bookkeeping lives on the synchronous path).  Callers gate on
    warmth and fall back to ``timed_jit_call`` when cold.
    """
    if key not in warm:
        raise RuntimeError(
            f"launch_jit_call on cold key {key!r}: cold dispatches "
            "must go through timed_jit_call")
    return fn(*args)


def finish_jit_call(key, out, t_launch: float):
    """Force completion of a launched warm dispatch and account it.

    ``t_launch`` is the perf_counter the caller took just before
    :func:`launch_jit_call`; the elapsed interval is the honest device
    wall of the dispatch — launch, execution (possibly overlapped with
    host work on other dispatches) and the residual completion wait.
    Returns ``(out, run_s)``; the warm-call compile time is 0 by
    definition."""
    out = sync(out)
    elapsed = time.perf_counter() - t_launch
    if metrics_registry.active:
        _account_jit_call(str(key), False, elapsed)
    efficiency_tracker.record_jit(str(key), False, elapsed)
    return out, elapsed


def _account_jit_call(skey: str, first: bool, elapsed: float):
    """Per-cache-key compile/dispatch accounting (registry.active
    only — the key label is unbounded across engines, so this is
    opt-in detail): warm-vs-cold call counts plus cold wall seconds,
    the queryable form of "did this run recompile, and what did it
    cost"."""
    metrics_registry.counter(
        "pydcop_jit_calls_total",
        "Engine jit dispatches by cache key and warmth",
    ).inc(key=skey, warmth="cold" if first else "warm")
    if first:
        metrics_registry.counter(
            "pydcop_jit_compile_seconds_total",
            "Wall seconds of cold engine dispatches (trace+compile+"
            "first run) by cache key",
        ).inc(elapsed, key=skey)


def _fn_label(fn) -> str:
    """Stable, low-cardinality name for a solve fn: partials (every
    one-shot algorithm wraps its runner in one) resolve to the
    wrapped function's name — never repr(), whose embedded addresses
    and array dumps would mint a fresh metric label per solve."""
    name = getattr(fn, "__name__", None)
    if name:
        return name
    inner = getattr(fn, "func", None)  # functools.partial
    return getattr(inner, "__name__", None) or type(fn).__name__


class _DecimationRun:
    """Host-side clamp bookkeeping for ONE decimated
    ``run_checkpointed`` call: the fixed-variable mask, the clamped
    unary table, and their rollback snapshot.  All mutation happens at
    segment boundaries on the host; the jitted loop only ever sees a
    fresh (replaced) graph, so decimation adds zero syncs inside it.
    """

    def __init__(self, engine, plan: DecimationPlan,
                 initial: Optional[DecimationState] = None):
        self.engine = engine
        self.plan = plan
        self.n_vars = len(engine.meta.var_names)
        if initial is not None:
            self.fixed = np.asarray(
                jax.device_get(initial.fixed)).astype(bool).copy()
            self.var_costs = np.asarray(
                jax.device_get(initial.var_costs)).copy()
        else:
            self.fixed = np.zeros(self.n_vars, dtype=bool)
            self.var_costs = np.asarray(
                jax.device_get(engine.graph.var_costs)).copy()
        self.rounds = 0
        self.rollbacks = 0
        self._snap = None

    def put(self, arr: np.ndarray):
        """Place a replacement var_costs table like the original: a
        replicated-mesh engine needs the replicated sharding spec, a
        single-device engine a plain device_put."""
        from jax.sharding import NamedSharding, PartitionSpec

        mesh = self.engine.mesh
        if mesh is not None and mesh.size > 1:
            return jax.device_put(
                arr, NamedSharding(mesh, PartitionSpec()))
        return jax.device_put(arr)

    def clamp(self, graph, state, values, margin):
        """Select-and-clamp at one segment boundary.  Returns
        ``(newly_clamped, graph, state)`` — a nonzero clamp count
        replaces the graph's unary table and clears the convergence
        flag (the clamped problem is a new problem; the warm-started
        messages adapt)."""
        margin = np.asarray(jax.device_get(margin))
        vals = np.asarray(jax.device_get(values))
        free = np.nonzero(~self.fixed)[0]
        if free.size == 0:
            return 0, graph, state
        cap = max(1, int(self.plan.frac_per_round * self.n_vars))
        if self.plan.margin > 0:
            eligible = free[margin[free] > self.plan.margin]
        else:
            eligible = free
        order = eligible[np.argsort(-margin[eligible], kind="stable")]
        chosen = order[:cap]
        if chosen.size == 0 and self.plan.force_progress:
            chosen = free[
                np.argsort(-margin[free], kind="stable")[:1]]
        if chosen.size == 0:
            return 0, graph, state
        d = self.var_costs.shape[1]
        for i in chosen:
            keep = int(vals[i])
            row = np.full((d,), BIG, self.var_costs.dtype)
            row[keep] = self.var_costs[i, keep]
            self.var_costs[i] = row
            self.fixed[i] = True
        self.rounds += 1
        graph = graph._replace(
            var_costs=self.put(self.var_costs.copy()))
        state = state._replace(stable=jnp.asarray(False))
        return int(chosen.size), graph, state

    def retain(self, graph):
        """Snapshot the clamp set alongside the recovery run's state
        snapshot: a later rollback must restore BOTH, or the replayed
        segment would run against a clamp set from its future."""
        self._snap = (self.fixed.copy(), self.var_costs.copy(), graph)

    def rollback(self):
        """Restore the clamp set retained with the last validated
        snapshot; returns the graph to continue with."""
        fixed, var_costs, graph = self._snap
        self.fixed = fixed.copy()
        self.var_costs = var_costs.copy()
        self.rollbacks += 1
        return graph

    def snapshot_payload(self, solver_state) -> DecimationState:
        """Checkpoint payload: solver state + the CURRENT clamp set
        (called after the boundary's clamping, so a resume replays
        exactly the uninterrupted sequence)."""
        return DecimationState(
            solver=solver_state,
            fixed=self.fixed.copy(),
            var_costs=self.var_costs.copy(),
        )

    def active_edges(self, graph) -> int:
        """Edge slots whose variable is still free — the per-round
        shrinking work set the metrics report."""
        n = 0
        for b in graph.buckets:
            ids = np.asarray(b.var_ids).reshape(-1)
            real = ids < self.n_vars
            n += int(np.sum(
                real & ~self.fixed[np.minimum(ids, self.n_vars - 1)]))
        return n

    def metrics(self, graph) -> Dict[str, Any]:
        return {
            "decimated_vars": int(self.fixed.sum()),
            "decimated_fraction": (
                float(self.fixed.sum()) / self.n_vars
                if self.n_vars else 0.0),
            "active_edges": self.active_edges(graph),
            "decimation_rounds": self.rounds,
            "decimation_rollbacks": self.rollbacks,
        }


def decimation_template(engine, solver_template) -> DecimationState:
    """Checkpoint restore template of a decimated run (resilience/
    checkpoint.load_state restores into this structure/placement)."""
    n_vars = len(engine.meta.var_names)
    return DecimationState(
        solver=solver_template,
        fixed=np.zeros(n_vars, dtype=bool),
        var_costs=np.asarray(
            jax.device_get(engine.graph.var_costs)).copy(),
    )


def _place_graph(graph: CompiledFactorGraph, mesh,
                 n_devices: Optional[int]):
    """Put the graph on device(s): sharded over a mesh when requested,
    else whole on the default device.  Returns (graph, mesh)."""
    if mesh is None and n_devices is not None and n_devices > 1:
        available = len(jax.devices())
        if n_devices > available:
            raise ValueError(
                f"Requested {n_devices} devices but only {available} "
                "available"
            )
        mesh = make_mesh(n_devices)
    if mesh is not None and mesh.size > 1:
        return shard_graph(graph, mesh), mesh
    return jax.device_put(graph), mesh


def run_device_fn(graph: CompiledFactorGraph, meta: FactorGraphMeta,
                  fn, mesh=None, n_devices: Optional[int] = None,
                  finished: bool = False,
                  warmup: bool = False) -> DeviceRunResult:
    """Jit + run a whole-solve function ``fn(graph) -> (values, cost,
    cycles)`` and package the result (shared by the local-search and
    sweep algorithms).

    One-shot cached-jit dispatch (not ``lower().compile()``: the AOT
    execute path is orders of magnitude slower through the axon TPU
    tunnel — see MaxSumEngine._call).  By default a cold call (fresh
    jit), so per the DeviceRunResult convention time_s and
    compile_time_s both carry the whole wall time and cycles_per_s is a
    lower bound.  With ``warmup=True`` the jitted fn is executed once
    untimed first, so the timed call is steady-state: compile_time_s
    is 0 per the warm-call convention (the warmup wall time, compile +
    one discarded execution, lands in metrics['warmup_time_s']) and
    cycles_per_s is the true run-only rate (use for benchmarking
    one-shot algorithms)."""
    graph, mesh = _place_graph(graph, mesh, n_devices)
    jitted = jax.jit(fn)
    xla_entry = None
    xla_key = None
    if profiler.enabled:
        xla_key = ("device_fn", _fn_label(fn))
        xla_entry = profiler.capture(xla_key, jitted, (graph,))
    compile_s = 0.0
    if warmup:
        t0 = time.perf_counter()
        sync(jitted(graph))
        compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    if tracer.active:
        with tracer.span("device_solve", "engine",
                         warmed=warmup):
            out = sync(jitted(graph))
    else:
        out = sync(jitted(graph))
    t1 = time.perf_counter()
    values, cost, cycles = jax.device_get(out)
    values = np.asarray(values)
    assignment = meta.assignment_from_indices(values)
    sign = 1.0 if meta.mode == "min" else -1.0
    metrics = {
        "device_cost": sign * float(cost) + meta.constant_cost,
        "cycles_per_s": (
            int(cycles) / (t1 - t0) if t1 > t0 else 0.0
        ),
        "cold_start": not warmup,
    }
    if warmup:
        metrics["warmup_time_s"] = compile_s
    if xla_entry is not None:
        metrics["xla_cost"] = {key_str(xla_key): xla_entry}
    return DeviceRunResult(
        assignment=assignment,
        cycles=int(cycles),
        converged=finished,
        time_s=t1 - t0,
        compile_time_s=0.0 if warmup else t1 - t0,
        metrics=metrics,
    )


class MaxSumEngine:
    """Runs MaxSum supersteps on a compiled factor graph.

    Parameters mirror the reference algo_params (maxsum.py:212-220):
    damping (0.5), damping_nodes (vars/factors/both/none), stability
    (0.1).  `noise` is applied at compile time (engine.compile).
    """

    def __init__(self, graph: CompiledFactorGraph, meta: FactorGraphMeta,
                 damping: float = 0.5, damping_nodes: str = "both",
                 stability: float = 0.1,
                 mesh=None, n_devices: Optional[int] = None,
                 layout: str = "edge", donate: bool = True,
                 prune: bool = False):
        if layout not in ("edge", "lane"):
            raise ValueError(
                f"layout must be 'edge' or 'lane', got {layout!r}")
        if prune and layout == "lane":
            raise ValueError(
                "prune=True gathers rows of the edge-major cost "
                "hypercubes; run with layout='edge'")
        self.meta = meta
        self.layout = layout
        if layout == "lane":
            # Lane-major ([D, arity, F], factors on the TPU lane axis
            # — see ops/maxsum_lane.py).  Single-device: shard_graph's
            # row sharding and the sort-based aggregations are
            # edge-major concepts.
            if (mesh is not None and mesh.size > 1) or (
                    n_devices is not None and n_devices > 1):
                raise ValueError(
                    "layout='lane' is single-device; use the default "
                    "edge layout for mesh runs")
            if graph.agg_perm is not None or graph.agg_ell is not None:
                raise ValueError(
                    "layout='lane' uses its own scatter aggregation; "
                    "compile with aggregation='scatter'")
            self.graph = jax.device_put(lane_ops.to_lane_graph(graph))
            self.mesh = None
        else:
            self.graph, self.mesh = _place_graph(graph, mesh, n_devices)
        self._ops = lane_ops if layout == "lane" else maxsum_ops
        self._init_solver_state(damping, damping_nodes, stability,
                                donate, prune)

    def _init_solver_state(self, damping: float, damping_nodes: str,
                           stability: float, donate: bool,
                           prune: bool = False):
        """Solver-parameter and runtime-bookkeeping tail shared by
        every engine initializer (ShardedMaxSumEngine builds its own
        graph/ops head, then calls this — one place to grow when the
        runner gains per-engine attributes)."""
        self.damping = damping
        self.damp_vars = damping_nodes in ("vars", "both")
        self.damp_factors = damping_nodes in ("factors", "both")
        self.stability = stability
        # Branch-and-bound message pruning (ops/maxsum.prune_tables):
        # a per-engine constant, so the per-engine jit caches need no
        # extra key term.  Pruning changes wall-clock, never values.
        self.prune = prune
        # Donate the state argument of the segment program: XLA then
        # writes each segment's output state into the input buffers
        # instead of allocating fresh ones — zero steady-state
        # allocations across a checkpointed/dynamic run.  Donation
        # only changes WHERE outputs land, never their values (the
        # tier-1 battery pins the bit-identical trajectory);
        # ``donate=False`` keeps input states alive for callers that
        # re-run from one (the A/B tests do).
        self.donate = donate
        # Per-engine annotations (e.g. the aggregation autotuner's
        # decision) merged into every DeviceRunResult.metrics.
        self.extra_metrics: Dict[str, Any] = {}
        # Extra args stamped onto every engine_segment span (the
        # partitioned engine tags its shard count here so trace
        # tooling can tell sharded segments apart).
        self._segment_span_args: Dict[str, Any] = {}
        self._jitted: Dict[Any, Any] = {}
        self._warm: set = set()

    def _call(self, key, fn, *args):
        """See timed_jit_call (module level, shared with the dynamic
        engine).  While the profiler is enabled, every compiled
        program's measured cost/memory analysis (or its explicit
        unavailable marker) is folded into ``extra_metrics`` so each
        DeviceRunResult carries ``metrics['xla_cost']`` keyed by cache
        key.  The fold happens only on the COLD dispatch (the one the
        capture rode in on) — warm dispatches skip the profiler
        lock entirely."""
        out = timed_jit_call(self._warm, key, fn, *args)
        if profiler.enabled and out[1] > 0:
            entry = profiler.get(key)
            if entry is not None:
                self.extra_metrics.setdefault(
                    "xla_cost", {})[key_str(key)] = entry
        return out

    def init_state(self):
        """Fresh solver state for this engine's placed graph — also the
        checkpoint *template*: resilience/checkpoint.py restores
        snapshots into this exact pytree structure (shapes, dtypes,
        device placement)."""
        return self._ops.init_state(self.graph)

    def _segment_key(self, extra_cycles: int,
                     stop_on_convergence: bool):
        """Cache key of one segment program.  Damping parameters are
        part of the key: a recovery damping bump
        (resilience/recovery.py) mid-run must compile a fresh program,
        not silently reuse the one that baked in the old damping."""
        return ("segment", extra_cycles, stop_on_convergence,
                self.damping, self.damp_vars, self.damp_factors)

    def _segment_fn(self, extra_cycles: int, stop_on_convergence: bool):
        """Cached-jit ``run_maxsum_from`` for one K-cycle segment (the
        checkpointed loop re-enters the solve with device state, the
        warm-start primitive dynamic DCOPs already use).  With
        ``donate=True`` (default) the state argument is donated, so
        every segment reuses the previous segment's buffers in place
        — the donated input is dead after the call; the loop only
        ever touches the returned state."""
        key = self._segment_key(extra_cycles, stop_on_convergence)
        if key not in self._jitted:
            self._jitted[key] = jax.jit(
                partial(
                    self._ops.run_maxsum_from,
                    extra_cycles=extra_cycles,
                    damping=self.damping,
                    damp_vars=self.damp_vars,
                    damp_factors=self.damp_factors,
                    stability=self.stability,
                    stop_on_convergence=stop_on_convergence,
                    prune=self.prune,
                ),
                donate_argnums=(1,) if self.donate else (),
            )
        return self._jitted[key]

    def _guard_fn(self, with_cost: bool = True):
        """Cached-jit segment-boundary guard: NaN/Inf scan over every
        floating-point state leaf, plus (``with_cost``) the constraint
        cost of the selected assignment — computed ON DEVICE so the
        verdict rides the segment boundary's existing host fetch (no
        syncs enter the jitted loop).  ``with_cost=False`` (the
        default-policy case: divergence guard disabled) skips the cost
        evaluation entirely instead of computing a value nobody reads.
        Pure reads either way: running the guard can never change the
        trajectory (the no-trip bit-identity the battery pins)."""
        key = ("guard", with_cost)
        if key not in self._jitted:
            ops = self._ops

            def guard(graph, state, values):
                finite = jnp.asarray(True)
                for leaf in jax.tree_util.tree_leaves(state):
                    if jnp.issubdtype(leaf.dtype, jnp.inexact):
                        finite = finite & jnp.all(jnp.isfinite(leaf))
                cost = (
                    ops.assignment_constraint_cost(graph, values)
                    if with_cost else jnp.asarray(0.0)
                )
                return finite, cost

            self._jitted[key] = jax.jit(guard)
        return self._jitted[key]

    def run_checkpointed(self, max_cycles: int = 1000, *,
                         manager=None,
                         checkpoint_dir: Optional[str] = None,
                         segment_cycles: Optional[int] = None,
                         stop_on_convergence: bool = True,
                         initial_state=None,
                         max_segments: Optional[int] = None,
                         probe=None,
                         checkpoint_async: bool = True,
                         recovery=None,
                         decimation: Optional[DecimationPlan] = None,
                         ) -> "DeviceRunResult":
        """The solve loop chunked into K-cycle segments with a state
        snapshot between segments — the preemption-survival entry point
        (resilience/checkpoint.py owns the format and the resume side).

        Because each segment re-enters ``run_maxsum_from`` with the
        exact device state the previous one produced, the segmented
        trajectory is the same superstep sequence as :meth:`run`'s
        single XLA program: same assignment, cost and cycle count
        (asserted in the tier-1 resilience battery).

        Steady-state host cost per segment is one scalar fetch (the
        data-dependent cycle counter): with ``checkpoint_async=True``
        (default) the snapshot's device→host copy and atomic NPZ
        write run on a background writer thread
        (resilience.checkpoint.AsyncCheckpointWriter) and overlap the
        NEXT segment's device compute, and with the engine's
        ``donate=True`` each segment reuses the previous state's
        buffers in place (the writer gets a device-side copy so
        donation can never invalidate an in-flight snapshot).
        ``checkpoint_async=False`` restores the synchronous
        fetch-then-write between segments.

        ``manager`` (a resilience.checkpoint.CheckpointManager) or
        ``checkpoint_dir`` enables snapshots; with neither this is just
        a segmented run (still useful to bound time-to-interrupt).
        ``initial_state`` resumes from a restored snapshot (with
        ``donate=True`` the passed state is consumed by the first
        segment — reload it for any later reuse);
        ``max_segments`` stops early after that many segments — the
        test harness's deterministic stand-in for a preemption.
        All snapshots are flushed to disk before this returns,
        whichever mode wrote them.

        ``probe`` (an observability.engine_probe.EngineProbe) receives
        ``on_segment(state, values, run_s, compile_s)`` after every
        segment — the chunk boundary is the only place a host already
        waits, so the probe's cost/convergence points cost no extra
        syncs inside the jitted loop.

        ``recovery`` (a resilience.recovery.RecoveryPolicy) arms the
        segment-boundary GUARD: each segment's end state is validated
        on device (NaN/Inf scan + optional cost-divergence window) and
        a tripped guard rolls back to the last valid in-memory
        snapshot and re-runs under the policy's escalation ladder
        (reseeded tie-break noise -> damping bump -> RecoveryExhausted
        carrying the partial trajectory), bounded by its restart
        budget.  Only VALIDATED states are checkpointed or fed to the
        probe; with no trips the guarded trajectory is bit-identical
        to the unguarded one (guards are pure reads — tier-1
        asserted).

        ``decimation`` (a :class:`DecimationPlan`) turns the segmented
        loop into the decimated solve: at every boundary — the host is
        already synced there — variables whose belief margin clears
        the plan's threshold are clamped to their selected value and
        the graph's unary table replaced (the jitted loop gains zero
        syncs; the clamped problem warm-starts from the surviving
        messages).  The clamp set rides every snapshot
        (:class:`DecimationState`) and every recovery retain, so a
        resume or a guard-trip rollback restores messages AND clamp
        set together — never a stale active-edge mask.  Metrics gain
        ``decimated_vars`` / ``decimated_fraction`` / ``active_edges``
        / ``decimation_rounds`` / ``decimation_rollbacks``.
        """
        from pydcop_tpu.resilience.checkpoint import (
            AsyncCheckpointWriter,
            CheckpointManager,
        )

        if decimation is not None and self._ops is not maxsum_ops:
            raise ValueError(
                "decimation clamps the edge-major var_costs table; "
                "run the unsharded edge-layout engine (no shards=, "
                "layout='edge')")
        if manager is None and checkpoint_dir is not None:
            manager = CheckpointManager(
                checkpoint_dir, every=segment_cycles or 100
            )
        every = segment_cycles or (
            manager.every if manager is not None else 100
        )
        graph = self.graph
        decim = None
        if decimation is not None:
            initial_decim = (
                initial_state
                if isinstance(initial_state, DecimationState) else None
            )
            decim = _DecimationRun(self, decimation, initial_decim)
            if initial_decim is not None:
                graph = graph._replace(
                    var_costs=decim.put(decim.var_costs.copy()))
                initial_state = initial_decim.solver
        elif isinstance(initial_state, DecimationState):
            raise ValueError(
                "initial_state carries a decimation clamp set but no "
                "decimation plan was passed — resuming it without one "
                "would silently solve a different problem")
        state = (
            initial_state if initial_state is not None
            else self.init_state()
        )
        rec = None
        if recovery is not None:
            from pydcop_tpu.resilience.recovery import RecoveryRun

            rec = RecoveryRun(recovery, self)
            # The starting state is the first rollback target: a trip
            # on the very first segment restarts from here — the
            # decimation clamp set must be retained alongside it, or
            # that first-segment rollback would unpack an empty
            # snapshot.
            rec.retain(state, None)
            if decim is not None:
                decim.retain(graph)
        writer = None
        if manager is not None and checkpoint_async:
            writer = AsyncCheckpointWriter(manager)
        t0 = time.perf_counter()
        compile_s = 0.0
        segments = 0
        checkpoints = 0
        interrupted = False
        values = None
        try:
            while True:
                cycle = int(state.cycle)
                if values is not None and (
                    cycle >= max_cycles
                    or (stop_on_convergence and bool(state.stable))
                    # Every variable clamped: the decimated solve is
                    # complete by definition — the clamped unary rows
                    # (BIG off the kept value) push message magnitudes
                    # to the BIG scale where the relative stability
                    # test may never settle, so waiting for it would
                    # burn the whole cycle budget for nothing.
                    or (decim is not None and bool(decim.fixed.all()))
                ):
                    break
                # A resume at/past the cycle budget still needs the
                # value selection: a zero-extra segment computes it
                # without stepping.
                extra = min(every, max(max_cycles - cycle, 0))
                fn = self._segment_fn(extra, stop_on_convergence)
                seg_key = self._segment_key(extra, stop_on_convergence)
                if tracer.active:
                    with tracer.span("engine_segment", "engine",
                                     segment=segments,
                                     from_cycle=cycle,
                                     extra_cycles=extra,
                                     **self._segment_span_args):
                        (state, values), c_s, run_s = self._call(
                            seg_key, fn, graph, state,
                        )
                else:
                    (state, values), c_s, run_s = self._call(
                        seg_key, fn, graph, state,
                    )
                compile_s += c_s
                segments += 1
                if rec is not None:
                    finite, g_cost = jax.device_get(
                        self._guard_fn(
                            recovery.divergence_window > 0
                        )(graph, state, values))
                    violation = rec.check(
                        int(state.cycle), bool(finite), float(g_cost))
                    if violation is not None:
                        # Tripped: the segment's output never reaches
                        # the probe or a checkpoint.  rollback raises
                        # RecoveryExhausted past the restart budget.
                        state, values = rec.rollback(violation)
                        if decim is not None:
                            # The clamp set travels with the snapshot:
                            # resuming the rolled-back messages under
                            # a newer (stale-in-time) active-edge mask
                            # would solve a different problem than the
                            # one the snapshot was validated for.
                            graph = decim.rollback()
                        else:
                            # A shard-loss rollback rebuilt the
                            # engine's graph on the surviving mesh
                            # (repartition_after_loss): re-read it.
                            graph = self.graph
                        if max_segments is not None \
                                and segments >= max_segments:
                            interrupted = True
                            break
                        continue
                    rec.retain(state, values)
                    if decim is not None:
                        decim.retain(graph)
                if probe is not None:
                    probe.on_segment(state, values, run_s, c_s)
                if decim is not None:
                    # Clamp BEFORE the checkpoint: the snapshot then
                    # carries the post-clamp set, and a resume replays
                    # exactly the uninterrupted boundary sequence
                    # (next segment first, next clamp after it).
                    margin = self._margin_fn()(graph, state)
                    newly, graph, state = decim.clamp(
                        graph, state, values, margin)
                    if newly and tracer.active:
                        tracer.instant(
                            "decimation_clamp", "engine",
                            newly_clamped=newly,
                            decimated_vars=int(decim.fixed.sum()),
                            cycle=int(state.cycle))
                if manager is not None:
                    if writer is not None:
                        snap = state
                        if self.donate:
                            # The next segment donates ``state``'s
                            # buffers; the writer must fetch from a
                            # copy that outlives the donation.  The
                            # copy is a device-side program — it
                            # overlaps, no host sync.  The recovery
                            # run already retained exactly that copy
                            # (both sides only read it), so reuse it
                            # rather than paying a second one.  A
                            # decimated run copies fresh instead: the
                            # retained copy predates this boundary's
                            # clamp (stable flag reset).
                            snap = (
                                rec.snapshot_state
                                if rec is not None and decim is None
                                else jax.tree_util.tree_map(
                                    jnp.copy, state)
                            )
                        # snap.cycle, not state.cycle: the original
                        # scalar is donated along with the rest of
                        # the state on the next dispatch.
                        if decim is not None:
                            writer.submit(
                                decim.snapshot_payload(snap),
                                snap.cycle)
                        else:
                            writer.submit(snap, snap.cycle)
                    else:
                        payload = (
                            decim.snapshot_payload(state)
                            if decim is not None else state
                        )
                        manager.save(payload, int(state.cycle))
                    checkpoints += 1
                if max_segments is not None \
                        and segments >= max_segments:
                    interrupted = True
                    break
        finally:
            if writer is not None:
                try:
                    writer.close()
                except Exception:
                    # Don't mask an in-flight engine error with a
                    # checkpoint-write error; with a clean loop exit
                    # the write failure IS the error.
                    if sys.exc_info()[0] is None:
                        raise
        if values is None:
            # Reachable when a guard trip on the very first segment
            # meets a max_segments break: the rollback restored the
            # initial snapshot, which carries no selected values yet.
            # A zero-extra segment computes the selection without
            # stepping (the same trick the resume-at-budget path
            # uses).
            fn = self._segment_fn(0, stop_on_convergence)
            (state, values), c_s, _ = self._call(
                self._segment_key(0, stop_on_convergence), fn,
                graph, state,
            )
            compile_s += c_s
        total = time.perf_counter() - t0
        values_host, cycle, stable = jax.device_get(
            (values, state.cycle, state.stable)
        )
        values_host = np.asarray(values_host)
        cycle, stable = int(cycle), bool(stable)
        if decim is not None and decim.fixed.all() and not interrupted:
            # Fully decimated = solved: every variable carries its
            # clamped value (legacy run_decimated convention).
            stable = True
        steady = max(total - compile_s, 0.0)
        return DeviceRunResult(
            assignment=self.meta.assignment_from_indices(values_host),
            cycles=cycle,
            converged=stable,
            time_s=total,
            compile_time_s=compile_s,
            metrics={
                **self.extra_metrics,
                "segments": segments,
                "segment_cycles": every,
                "checkpoints_written": checkpoints,
                "checkpoint_async": writer is not None,
                "interrupted": interrupted,
                "cycles_per_s": cycle / steady if steady > 0 else 0.0,
                "cold_start": compile_s > 0,
                **(rec.metrics() if rec is not None else {}),
                **(decim.metrics(graph) if decim is not None else {}),
            },
        )

    def _margin_fn(self):
        """Cached-jit belief-margin evaluation ([V] gap between best
        and second-best value) — the decimation confidence signal,
        computed on device and fetched at the segment boundary the
        host is already syncing on."""
        key = ("decim_margin",)
        if key not in self._jitted:
            def margin_of(graph, state):
                beliefs, _ = maxsum_ops.aggregate_beliefs(
                    graph, state.f2v)
                masked = jnp.where(
                    graph.var_valid, beliefs, jnp.inf)[:-1]
                best2 = jnp.sort(masked, axis=1)[:, :2]
                return best2[:, 1] - best2[:, 0]

            self._jitted[key] = jax.jit(margin_of)
        return self._jitted[key]

    def _fn(self, max_cycles: int, stop_on_convergence: bool):
        key = (max_cycles, stop_on_convergence)
        if key not in self._jitted:
            self._jitted[key] = jax.jit(
                partial(
                    self._ops.run_maxsum,
                    max_cycles=max_cycles,
                    damping=self.damping,
                    damp_vars=self.damp_vars,
                    damp_factors=self.damp_factors,
                    stability=self.stability,
                    stop_on_convergence=stop_on_convergence,
                    prune=self.prune,
                )
            )
        return self._jitted[key]

    def run_trace(self, max_cycles: int,
                  stop_on_convergence: bool = True
                  ) -> "DeviceRunResult":
        """Run recording the constraint cost of the selected
        assignment after every cycle (metrics['cost_trace'], numpy
        [max_cycles]) — the curve behind time-to-equal-cost claims
        (bench.py).  Default ``stop_on_convergence`` matches
        :meth:`run`: the loop exits at the fixpoint, the cycle count
        agrees with an untraced solve, and the curve's tail holds the
        final cost (still a valid anytime record at full length)."""
        key = ("trace", max_cycles, stop_on_convergence)
        if key not in self._jitted:
            base = self.meta.var_base_costs
            self._jitted[key] = jax.jit(
                partial(
                    self._ops.run_maxsum_trace,
                    max_cycles=max_cycles,
                    damping=self.damping,
                    damp_vars=self.damp_vars,
                    damp_factors=self.damp_factors,
                    stability=self.stability,
                    var_base_costs=(
                        None if base is None else jnp.asarray(base)
                    ),
                    stop_on_convergence=stop_on_convergence,
                    prune=self.prune,
                )
            )
        fn = self._jitted[key]
        (state, values, costs), compile_s, run_s = self._call(
            key, fn, self.graph)
        values, cycle, stable, costs = jax.device_get(
            (values, state.cycle, state.stable, costs)
        )
        values = np.asarray(values)
        sign = 1.0 if self.meta.mode == "min" else -1.0
        return DeviceRunResult(
            assignment=self.meta.assignment_from_indices(values),
            cycles=int(cycle),
            converged=bool(stable),
            time_s=run_s,
            compile_time_s=compile_s,
            metrics={
                **self.extra_metrics,
                "cost_trace": sign * np.asarray(costs)
                + self.meta.constant_cost,
                "cold_start": compile_s > 0,
            },
        )

    def run_decimated(self, max_cycles: int = 1000,
                      frac: float = 0.1,
                      cycles_per_round: int = 60) -> DeviceRunResult:
        """MaxSum with decimation (Improving Max-Sum through Decimation,
        arXiv:1706.02209): alternate message passing with fixing the
        most *confident* variables — those with the largest belief
        margin between their best and second-best value — by clamping
        their unary costs, then warm-restarting the messages.  On loopy
        graphs this breaks the oscillations that keep plain MaxSum away
        from good assignments, at the price of a handful of
        host-driven rounds (each round is still one XLA program).

        ``frac`` of all variables (at least 1, capped to the remaining
        free set) is fixed per round; runs until every variable is
        fixed or ``max_cycles`` total cycles are spent.
        """
        from jax.sharding import NamedSharding, PartitionSpec

        if self.layout != "edge":
            raise ValueError(
                "decimation clamps rows of the edge-major var_costs "
                "table; run with layout='edge'")
        n_vars = len(self.meta.var_names)
        dmax = self.graph.var_costs.shape[1]
        var_costs = np.asarray(self.graph.var_costs).copy()
        fixed = np.zeros(n_vars, dtype=bool)
        graph = self.graph
        state = maxsum_ops.init_state(graph)

        compile_s = 0.0

        def _call_round(extra, g, s):
            """Run one compiled round via cached-jit dispatch (see
            timed_jit_call for why never AOT lower/compile).  The
            first call per round length is timed as compile — it
            includes one execution, but compile dominates, the same
            approximation every engine entry point uses."""
            nonlocal compile_s
            key = ("decim", extra)
            first_call = key not in self._jitted
            if first_call:
                def _round(g, s, _extra=extra):
                    s, values = maxsum_ops.run_maxsum_from(
                        g, s, _extra,
                        damping=self.damping,
                        damp_vars=self.damp_vars,
                        damp_factors=self.damp_factors,
                        stability=self.stability,
                        stop_on_convergence=True,
                    )
                    beliefs, _ = maxsum_ops.aggregate_beliefs(g, s.f2v)
                    masked = jnp.where(
                        g.var_valid, beliefs, jnp.inf)[:-1]
                    best2 = jnp.sort(masked, axis=1)[:, :2]
                    margin = best2[:, 1] - best2[:, 0]
                    return s, values, margin

                self._jitted[key] = jax.jit(_round)
            tc = time.perf_counter()
            out = self._jitted[key](g, s)
            if first_call:
                sync(out)
                compile_s += time.perf_counter() - tc
            return out

        def _put(arr):
            if self.mesh is not None and self.mesh.size > 1:
                return jax.device_put(
                    arr, NamedSharding(self.mesh, PartitionSpec()))
            return jax.device_put(arr)

        t0 = time.perf_counter()
        values = None
        while True:
            # Never overshoot the caller's cycle budget: the final
            # round runs only the remainder (at most one extra compile
            # for the non-standard round length).
            remaining = max_cycles - int(state.cycle)
            if remaining <= 0 and values is not None:
                break
            extra = min(cycles_per_round, max(remaining, 1))
            state, values, margin = _call_round(extra, graph, state)
            if bool(np.all(fixed)) or \
                    int(state.cycle) >= max_cycles:
                break
            margin = np.asarray(margin)
            vals_host = np.asarray(values)
            free = np.nonzero(~fixed)[0]
            if free.size == 0:
                break
            k = max(1, int(frac * n_vars))
            chosen = free[np.argsort(-margin[free])[:k]]
            for i in chosen:
                keep = int(vals_host[i])
                clamp = np.full(dmax, BIG, np.float32)
                clamp[keep] = var_costs[i, keep]
                var_costs[i] = clamp
                fixed[i] = True
            graph = graph._replace(var_costs=_put(var_costs.copy()))
            # Clamped costs changed the problem: clear convergence so
            # the warm-started messages adapt.
            state = state._replace(stable=jnp.asarray(False))
        sync(values)
        total = time.perf_counter() - t0
        # DeviceRunResult convention: time_s = total wall including
        # compiles; steady-state rate uses the compile-free remainder.
        steady = max(total - compile_s, 0.0)
        values = np.asarray(jax.device_get(values))
        cycle = int(state.cycle)
        return DeviceRunResult(
            assignment=self.meta.assignment_from_indices(values),
            cycles=cycle,
            converged=bool(np.all(fixed)),
            time_s=total,
            compile_time_s=compile_s,
            metrics={
                **self.extra_metrics,
                "decimated_vars": int(fixed.sum()),
                "cycles_per_s": cycle / steady if steady > 0 else 0.0,
                "cold_start": compile_s > 0,
            },
        )

    def run(self, max_cycles: int = 1000,
            stop_on_convergence: bool = True) -> DeviceRunResult:
        """Steady-state ``time_s`` requires a prior warmup call with
        the same (max_cycles, stop_on_convergence); a first call
        reports the trace+compile+run total in BOTH time_s and
        compile_time_s (bench.py warms up before timing)."""
        key = (max_cycles, stop_on_convergence)
        fn = self._fn(max_cycles, stop_on_convergence)
        (state, values), compile_s, run_s = self._call(
            key, fn, self.graph)
        # One host transfer (the tunnel round-trip dominates small gets).
        values, cycle, stable = jax.device_get(
            (values, state.cycle, state.stable)
        )
        values = np.asarray(values)
        cycle, stable = int(cycle), bool(stable)
        assignment = self.meta.assignment_from_indices(values)
        n_msgs = sum(
            int(np.prod(b.var_ids.shape)) for b in self.graph.buckets
        )
        return DeviceRunResult(
            assignment=assignment,
            cycles=cycle,
            converged=stable,
            time_s=run_s,
            compile_time_s=compile_s,
            metrics={
                **self.extra_metrics,
                "msg_count": 2 * n_msgs * cycle,
                "cycles_per_s": cycle / run_s if run_s > 0 else 0.0,
                "cold_start": compile_s > 0,
            },
        )


class ShardedMaxSumEngine(MaxSumEngine):
    """MaxSum on a PARTITIONED factor graph: each shard owns a local
    slice of the variable tables and the messages of its own factors;
    the per-superstep cross-shard traffic is the compacted ``[B, D]``
    halo buffer (B = cut-edge endpoint count) instead of the
    replicated path's dense ``[V+1, D]`` all-reduce — O(cut·D), not
    O(V·D) (engine/sharding.py: build_partitioned_graph + ShardOps;
    engine/partition.py: the min-edge-cut partitioner).

    Everything above the kernel — segmented runs, checkpointing,
    recovery guards, probes — is inherited from MaxSumEngine through
    the ``_ops`` seam: ShardOps exposes the ops.maxsum call surface
    (init_state / run_maxsum / run_maxsum_from / run_maxsum_trace /
    assignment_constraint_cost) over the sharded state, and the
    returned ``values`` are already reassembled to global order.

    ``metrics`` on every result carry the partition statistics
    (``edge_cut_fraction``, ``halo_vars_per_shard``, ``balance``) and
    the communication accounting
    (``halo_exchange_elems_per_superstep`` vs
    ``replicated_allreduce_elems_per_superstep``)."""

    def __init__(self, graph: CompiledFactorGraph,
                 meta: FactorGraphMeta, *,
                 n_shards: Optional[int] = None, mesh=None,
                 partition=None,
                 damping: float = 0.5, damping_nodes: str = "both",
                 stability: float = 0.1, donate: bool = True,
                 prune: bool = False):
        from pydcop_tpu.engine.partition import partition_compiled
        from pydcop_tpu.engine.sharding import (
            ShardOps,
            build_partitioned_graph,
        )

        if mesh is None:
            mesh = make_mesh(n_shards)
        if mesh.size < 2:
            raise ValueError(
                "partitioned sharding needs a mesh of >= 2 devices; "
                "run unsharded (or force host devices with XLA_FLAGS="
                "--xla_force_host_platform_device_count=N for CPU "
                "testing)")
        if partition is None:
            partition = partition_compiled(graph, mesh.size)
        self.meta = meta
        # Edge-major messages (the probe's layout contract); the
        # partitioning is orthogonal to the layout.
        self.layout = "edge"
        self.mesh = mesh
        self.partition = partition
        # Kept for shard-loss recovery: re-partitioning onto a
        # surviving mesh rebuilds the per-shard layout from the
        # ORIGINAL compiled graph (repartition_after_loss).
        self._source_graph = graph
        self.graph, part_metrics = build_partitioned_graph(
            graph, partition, mesh)
        self._ops = ShardOps(mesh, len(meta.var_names))
        self._init_solver_state(damping, damping_nodes, stability,
                                donate, prune)
        self.extra_metrics.update(part_metrics)
        self._segment_span_args["shards"] = mesh.size

    def _call(self, key, fn, *args):
        out = super()._call(key, fn, *args)
        if tracer.active:
            # One instant per shard with its static partition stats:
            # the honest per-shard facts a single-program dispatch
            # can report (per-shard wall time does not exist — the
            # mesh runs one XLA program).  Trace merge routes
            # shard-tagged events onto distinct lanes.
            owned = self.extra_metrics.get(
                "owned_vars_per_shard", [])
            halo = self.extra_metrics.get(
                "halo_vars_per_shard", [])
            for s in range(self.mesh.size):
                tracer.instant(
                    "shard_segment", "engine", shard=s,
                    owned_vars=owned[s] if s < len(owned) else None,
                    halo_vars=halo[s] if s < len(halo) else None,
                    key=str(key),
                )
        return out

    def repartition_after_loss(self, lost_shard: int,
                               snapshot_state):
        """Shard-loss recovery: rebuild this engine on the surviving
        mesh and remap a validated snapshot onto the new layout.

        Called by the recovery run (resilience/recovery.py) when a
        ``shard_loss`` guard trips.  The sequence: (1) a fresh 1-D
        mesh over the surviving devices, (2) a re-partition of the
        ORIGINAL compiled graph onto it — memoized by structure key +
        shard count (engine/partition.partition_cache), so a repeated
        loss pattern re-partitions from cache, (3) the per-shard
        layout rebuilt, (4) the snapshot's messages remapped onto the
        new factor→shard packing with the halo buffer recomputed
        on-device (engine/sharding.remap_partitioned_state), and
        (5) every cached jit/warm entry dropped — the old programs
        baked in the dead mesh.  Returns the remapped state to resume
        from; raises :class:`~pydcop_tpu.resilience.recovery.
        NoSurvivingDevices` when the mesh would be empty.

        The repartition + remap wall time lands in
        ``extra_metrics['shard_recovery_s']`` (the bench's
        per-backend recovery-time series).
        """
        from jax.sharding import Mesh

        from pydcop_tpu.engine.partition import partition_compiled
        from pydcop_tpu.engine.sharding import (
            SHARD_AXIS,
            ShardOps,
            build_partitioned_graph,
            remap_partitioned_state,
        )
        from pydcop_tpu.resilience.recovery import NoSurvivingDevices

        t0 = time.perf_counter()
        devices = list(self.mesh.devices.flat)
        if not 0 <= lost_shard < len(devices):
            raise ValueError(
                f"lost shard {lost_shard} out of range for a mesh "
                f"of {len(devices)}")
        survivors = [d for i, d in enumerate(devices)
                     if i != lost_shard]
        if not survivors:
            raise NoSurvivingDevices(
                f"shard {lost_shard} was the last device")
        new_mesh = Mesh(np.array(survivors), (SHARD_AXIS,))
        new_part = partition_compiled(self._source_graph,
                                      new_mesh.size)
        new_graph, part_metrics = build_partitioned_graph(
            self._source_graph, new_part, new_mesh)
        new_ops = ShardOps(new_mesh, len(self.meta.var_names))
        state = remap_partitioned_state(
            self._source_graph, self.partition, new_part,
            snapshot_state, new_graph, new_ops)
        self.mesh = new_mesh
        self.partition = new_part
        self.graph = new_graph
        self._ops = new_ops
        # Stale compiled programs reference the dead mesh; the next
        # segment call recompiles against the survivors.
        self._jitted.clear()
        self._warm.clear()
        self.extra_metrics.update(part_metrics)
        self.extra_metrics["repartitions"] = (
            self.extra_metrics.get("repartitions", 0) + 1)
        self.extra_metrics.setdefault(
            "lost_shards", []).append(int(lost_shard))
        self.extra_metrics["shard_recovery_s"] = round(
            time.perf_counter() - t0, 4)
        self._segment_span_args["shards"] = new_mesh.size
        return state

    def run_decimated(self, *args, **kwargs):
        raise ValueError(
            "decimation clamps rows of the single-device var_costs "
            "table; run without shards= (or use the replicated "
            "n_devices= path)")

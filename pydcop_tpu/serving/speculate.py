"""Speculative envelope compilation (ISSUE 18 tentpole piece b).

The serving plane's compile stalls are concentrated on *predictable*
programs: a structure that arrived once will arrive again (the
affinity router already banks on it), and when it does it will batch
— the flush will pad the group to the next bin rung and dispatch a
program whose shape is fully determined by (envelope, bin size,
solver statics).  Nothing about that program needs a live request to
exist: the stacked input's avals can be derived abstractly with
``jax.eval_shape`` (zero device work), and the executable can be
built with compile-only AOT lowering
(``_batched_solve.lower(...).compile()``) which populates the PR-15
persistent compile cache on disk WITHOUT touching jit's dispatch
cache — so when the real traffic arrives, the "cold" jit call
resolves as a fast disk hit instead of a multi-hundred-ms XLA build
on the request path.

Discipline (battery-asserted):

* all compilation runs on ONE low-priority daemon thread, never the
  device-owning scheduler thread — every compile record carries its
  ``thread_ident`` so the battery can assert the separation;
* compile-only lowering only: the worker never calls the jitted
  entry point, never executes a program, and never touches
  ``engine.batch._warm`` (marking a speculated key warm would route
  the first REAL dispatch through the warm launch path with no
  compile attribution — the ledger would lie);
* the job queue is bounded (drops are counted, not blocked on) so a
  diverse stream cannot grow an unbounded compile backlog.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import jax

from ..engine import batch as engine_batch
from ..engine.compile import CompiledFactorGraph, FactorBucket
from ..observability.trace import tracer
from . import binning

log = logging.getLogger("pydcop_tpu.serving.speculate")

# Bin rungs speculated ahead of the observed group size: when a
# structure shows up at size n, the next flushes will most likely pad
# it to the next rung(s) up.  Two rungs ahead covers a doubling burst
# without flooding the queue on every observation.
_RUNGS_AHEAD = 2


def _padded_avals(graph, env: binning.Envelope) -> CompiledFactorGraph:
    """ShapeDtypeStruct skeleton of ``graph`` padded to ``env`` —
    every padded shape is fully determined by the envelope
    (``engine.batch.pad_graph_to_envelope`` docstring), so the
    skeleton can be built WITHOUT the numpy padding work and without
    a single device buffer.  Shape parity with the real padding path
    is battery-asserted (the speculated program key must equal the
    live ``_prepare_stacked`` key or every speculation misses)."""
    import numpy as np

    cost_dtype = graph.var_costs.dtype
    by_arity = {b.arity: b.costs.dtype for b in graph.buckets}
    buckets = tuple(
        FactorBucket(
            costs=jax.ShapeDtypeStruct(
                (rows,) + (env.d_env,) * arity,
                by_arity.get(arity, cost_dtype)),
            var_ids=jax.ShapeDtypeStruct((rows, arity), np.int32),
        )
        for arity, rows in env.rows
    )
    return CompiledFactorGraph(
        var_costs=jax.ShapeDtypeStruct(
            (env.v_env + 1, env.d_env), cost_dtype),
        var_valid=jax.ShapeDtypeStruct(
            (env.v_env + 1, env.d_env), np.bool_),
        buckets=buckets,
    )


def _statics_from_params(params: Dict[str, Any]) -> Dict[str, Any]:
    """The jit static-arg dict, derived EXACTLY like
    ``engine.batch._prepare_stacked`` does — key equality with the
    live dispatch path is the whole point."""
    damping_nodes = params.get("damping_nodes", "vars")
    return dict(
        max_cycles=params["max_cycles"],
        damping=params["damping"],
        damp_vars=damping_nodes in ("vars", "both"),
        damp_factors=damping_nodes in ("factors", "both"),
        stability=params["stability"],
        prune=bool(params.get("prune", 0)),
    )


class _Job:
    __slots__ = ("graph_avals", "env", "bs", "statics")

    def __init__(self, graph_avals, env, bs, statics):
        self.graph_avals = graph_avals
        self.env = env
        self.bs = bs
        self.statics = statics


class SpeculativeCompiler:
    """Arrival-histogram-driven background compiler for envelope
    programs.  ``observe()`` is called by the flush planner (cheap:
    histogram update + bounded enqueue); one daemon worker drains the
    queue with compile-only AOT lowering."""

    def __init__(self, bin_sizes: Tuple[int, ...] = (1, 2, 4, 8, 16),
                 max_queue: int = 16):
        self.bin_sizes = tuple(sorted(set(int(b) for b in bin_sizes)))
        self._queue: "queue.Queue[Optional[_Job]]" = queue.Queue(
            maxsize=max_queue)
        self._lock = threading.Lock()
        # Per-(envelope, statics) arrival counts — the structure
        # histogram the predictions rank on.
        self.histogram: Dict[tuple, int] = {}
        # str(program_key) of every executable this speculator built
        # (or queued — dedupe is at enqueue time so a slow compile
        # does not get queued twice).
        self._seen_keys: set = set()
        self.compiled_keys: set = set()
        self.records: List[Dict[str, Any]] = []
        self.compiled_total = 0
        self.dropped_total = 0
        self.hit_total = 0
        self.failed_total = 0
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ----------------------------------------------------------- #
    # lifecycle

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._worker, name="pydcop-spec-compile",
            daemon=True)
        self._thread.start()

    def stop(self, timeout: float = 2.0) -> None:
        if self._thread is None:
            return
        self._stop.set()
        try:
            self._queue.put_nowait(None)
        except queue.Full:
            pass
        self._thread.join(timeout=timeout)
        self._thread = None

    # ----------------------------------------------------------- #
    # planner-side API (scheduler thread — must stay cheap)

    def observe(self, graph, env: binning.Envelope,
                params: Dict[str, Any], count: int) -> None:
        """Record one envelope group's arrival and enqueue the
        programs its structure will plausibly need next: the observed
        envelope at the next ``_RUNGS_AHEAD`` bin rungs above
        ``count``, plus the current rung itself (a recurring solo
        structure's next arrival is the most likely program of all).
        Two skeletons per prediction: the graph's RAW shapes (what an
        exact same-structure bin dispatches — ``run_stacked`` with
        ``envelope=None`` stacks the compiled graphs as-is) and the
        envelope-padded shapes (what a heterogeneous packed group
        dispatches); an exact-fit graph collapses both to one key.
        Derives avals from ``graph`` (shape skeletons only) so the
        jobs hold no device buffers."""
        statics = _statics_from_params(params)
        hkey = (env, tuple(sorted(statics.items())))
        with self._lock:
            self.histogram[hkey] = self.histogram.get(hkey, 0) + 1
        try:
            skeletons = [
                jax.tree_util.tree_map(
                    lambda x: (jax.ShapeDtypeStruct(x.shape, x.dtype)
                               if hasattr(x, "shape")
                               and hasattr(x, "dtype") else x),
                    graph),
                _padded_avals(graph, env),
            ]
        except Exception:
            return  # never raise into the flush planner
        sizes: List[int] = []
        ahead = 0
        for b in self.bin_sizes:
            if b >= max(int(count), 1):
                sizes.append(b)
                ahead += 1
                if ahead > _RUNGS_AHEAD:
                    break
        for bs in sizes:
            for avals in skeletons:
                self._enqueue(_Job(avals, env, bs, statics))

    def _enqueue(self, job: _Job) -> None:
        try:
            key = self._program_key(job)
        except Exception:  # aval derivation failed — never raise into
            return         # the flush planner
        skey = str(key)
        with self._lock:
            if skey in self._seen_keys:
                return
            if key in engine_batch._warm:
                # Already live-compiled: nothing to speculate.
                self._seen_keys.add(skey)
                return
            self._seen_keys.add(skey)
        try:
            self._queue.put_nowait(job)
        except queue.Full:
            with self._lock:
                self.dropped_total += 1
                self._seen_keys.discard(skey)

    # ----------------------------------------------------------- #
    # worker side

    @staticmethod
    def _stacked_avals(job: _Job):
        """Abstract shapes of the stacked dispatch input — pure
        ``eval_shape`` over the already-padded skeleton, zero device
        work (asserted by the battery via the compile records' thread
        idents + compile_only flag)."""
        return jax.eval_shape(
            lambda g: engine_batch.stack_graphs([g] * job.bs),
            job.graph_avals,
        )

    def _program_key(self, job: _Job) -> tuple:
        stacked = self._stacked_avals(job)
        return (
            "maxsum_batch", job.bs,
            engine_batch._shape_signature(stacked),
            tuple(sorted(job.statics.items())),
        )

    def _worker(self) -> None:
        while not self._stop.is_set():
            try:
                job = self._queue.get(timeout=0.2)
            except queue.Empty:
                continue
            if job is None:
                break
            try:
                self._compile_one(job)
            except Exception as exc:
                with self._lock:
                    self.failed_total += 1
                log.debug("speculative compile failed: %s", exc)

    def _compile_one(self, job: _Job) -> None:
        stacked = self._stacked_avals(job)
        key = (
            "maxsum_batch", job.bs,
            engine_batch._shape_signature(stacked),
            tuple(sorted(job.statics.items())),
        )
        if key in engine_batch._warm:
            return
        t0 = time.perf_counter()
        with tracer.span("speculative_compile", cat="serve",
                         key=str(key)[:120], compile_only=True,
                         thread=threading.get_ident()):
            # Compile-only AOT path: builds the executable (and
            # populates the persistent disk cache when enabled) but
            # NEVER dispatches — the device stays with the scheduler
            # thread.
            engine_batch._batched_solve.lower(
                stacked, **job.statics).compile()
        wall = time.perf_counter() - t0
        with self._lock:
            self.compiled_total += 1
            self.compiled_keys.add(str(key))
            self.records.append({
                "key": str(key),
                "thread_ident": threading.get_ident(),
                "wall_s": round(wall, 6),
                "compile_only": True,
            })

    # ----------------------------------------------------------- #
    # completion-side API (hit accounting + stats)

    def record_hit(self, program_key: str) -> bool:
        """Called by the service when a cold dispatch's program key
        matches a speculated executable — the compile the request
        path just skipped (disk hit instead of XLA build)."""
        with self._lock:
            if program_key in self.compiled_keys:
                self.hit_total += 1
                return True
        return False

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "speculative_compiles_total": self.compiled_total,
                "speculative_hits_total": self.hit_total,
                "speculative_dropped_total": self.dropped_total,
                "speculative_failed_total": self.failed_total,
                "queued": self._queue.qsize(),
                "structures_observed": len(self.histogram),
            }

"""North-star benchmark: MaxSum on 10k-variable graph coloring
(BASELINE.json config #4/#1 scale), device engine vs this repo's OWN
threaded agent runtime on the same problem — the comparison the
reference architecture implies (pydcop/infrastructure/run.py:145
run_local_thread_dcop hosts every computation on an agent thread; the
hot loop is factor_costs_for_var maxsum.py:382 + costs_for_factor :623).

Prints ONE json line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

extra keys: backend ("tpu"/"cpu"), baseline_cycles_per_s, cost-parity
evidence (device vs thread cost on a converged mid-size run), and a
modeled roofline (flops/bytes per superstep, achieved GFLOP/s, MFU vs
v5e bf16 peak, HBM utilization — see pydcop_tpu/engine/roofline.py for
the counting rules and why HBM util is the meaningful number).

Both paths share one problem builder and the same seeded tie-breaking
noise (_stable_noise), so costs are directly comparable.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np

N_VARS = 10_000
N_COLORS = 3
DEVICE_CYCLES = 200
SCALE_N_VARS = 1_000_000     # HBM-bound leg (TPU only)
SCALE_CYCLES = 50
THREAD_TIMEOUT_S = 30.0
THREAD_AGENTS = 8
PARITY_VARS = 60
PARITY_SEED = 3
PARITY_TIMEOUT_S = 8.0
# Matched-cycle quality tolerance at 10k vars, as a fraction of the
# constraint count: thread mode stops on wall clock with computations at
# slightly skewed cycles, so mid-descent costs can differ by a few
# cycles' worth of improvement.
QUALITY_TOL_FRAC = 0.025

# Per-leg backend resolution (ROADMAP open item 5 crumb): five
# straight rounds silently fell back to CPU and only the post-hoc
# probe log said why.  Every leg now records the backend it ACTUALLY
# resolved plus the accelerator-probe outcome at that moment
# (mirroring the /healthz ``accelerator_probe`` body), emitted as
# ``leg_backends`` in the JSON line — the next CPU-fallback round is
# self-explaining per leg, not per process.
_LEG_BACKENDS = {}


def record_leg_backend(leg: str):
    """Snapshot the resolved backend + probe state for one leg."""
    import jax

    from pydcop_tpu.utils.cleanenv import diag_events, is_probe_failure

    failures = [e for e in diag_events() if is_probe_failure(e)]
    last = failures[-1] if failures else None
    _LEG_BACKENDS[leg] = {
        "backend": jax.default_backend(),
        "n_devices": len(jax.devices()),
        "probe_failures": len(failures),
        "last_probe_event": last.get("event") if last else None,
        "last_probe_error": last.get("error") if last else None,
    }
    return _LEG_BACKENDS[leg]


def build_dcop(n_vars: int, seed: int = 0):
    """n_vars-variable 3-coloring: cost-1 equality penalty per edge,
    ~1.5 edges/var (the round-1 bench problem, now as a real DCOP so
    the agent runtime can solve the identical instance)."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP(f"gc_{n_vars}", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    eq = np.eye(N_COLORS, dtype=np.float64)
    seen = set()
    k = 0
    for _ in range(int(n_vars * 1.5)):
        i, j = rng.choice(n_vars, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i], variables[j]], eq, f"c{k}"))
        k += 1
    dcop.add_agents([AgentDef(f"a{a}") for a in range(THREAD_AGENTS)])
    return dcop


def build_grid_dcop(side: int, seed: int = 0):
    """``side x side`` 4-neighbor grid coloring with random integer
    tables — the locally-connected instance the SHARDED leg measures.
    Random graphs are expanders (no partitioner cuts them well); real
    DCOP deployments (sensor nets, smart grids, meeting graphs) are
    spatially local, and a grid is the canonical local topology:
    a BFS-grown min-edge-cut partition lands a single-digit-percent
    cut, which is the regime where halo exchange beats the
    replicated all-reduce."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP(f"grid_{side}", objective="min")
    variables = [Variable(f"v{i}", dom) for i in range(side * side)]
    for v in variables:
        dcop.add_variable(v)
    k = 0
    for r in range(side):
        for c in range(side):
            i = r * side + c
            for rr, cc in ((r + 1, c), (r, c + 1)):
                if rr < side and cc < side:
                    j = rr * side + cc
                    table = rng.integers(
                        0, 10, size=(N_COLORS, N_COLORS))
                    dcop.add_constraint(NAryMatrixRelation(
                        [variables[i], variables[j]],
                        table.astype(np.float64), f"c{k}"))
                    k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def bench_device(dcop, max_cycles: int, timed: bool = True):
    """Compile + run the device engine; returns (cycles/s, result,
    engine).  With timed=True a warmup run precedes the timed run so
    the number is steady-state execution, not compilation."""
    from pydcop_tpu.engine.compile import compile_dcop
    from pydcop_tpu.engine.runner import MaxSumEngine

    graph, meta = compile_dcop(dcop, noise_level=0.01)
    engine = MaxSumEngine(graph, meta)
    if timed:
        engine.run(max_cycles=max_cycles, stop_on_convergence=False)
    res = engine.run(max_cycles=max_cycles, stop_on_convergence=False)
    cps = res.cycles / res.time_s if res.time_s > 0 else 0.0
    return cps, res, engine


def bench_thread(dcop, timeout: float):
    """The repo's own threaded agent runtime on the same DCOP: one
    orchestrator + THREAD_AGENTS OrchestratedAgent threads, in-process
    transport, computations round-robined over agents.  Returns
    (cycles/s, completed cycles, cost at stop, assignment)."""
    from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.distribution.objects import Distribution
    from pydcop_tpu.infrastructure.run import run_local_thread_dcop

    algo_def = AlgorithmDef.build_with_default_param("maxsum", mode="min")
    module = load_algorithm_module("maxsum")
    cg = load_graph_module(module.GRAPH_TYPE).build_computation_graph(dcop)
    agents = sorted(dcop.agents)
    mapping = {a: [] for a in agents}
    for i, node in enumerate(cg.nodes):
        mapping[agents[i % len(agents)]].append(node.name)
    dist = Distribution(mapping)

    orch = run_local_thread_dcop(algo_def, cg, dist, dcop)
    try:
        if not orch.wait_ready(30):
            raise RuntimeError("agents not ready")
        orch.deploy_computations()
        t0 = time.perf_counter()
        orch.run(timeout=timeout)
        elapsed = time.perf_counter() - t0
        orch.stop_agents(10)
        metrics = orch.end_metrics()
        cycles = int(metrics["cycle"])
        cost = float(metrics["cost"]) if metrics["cost"] is not None \
            else float("nan")
        assignment = {
            k: v for k, v in metrics["assignment"].items()
            if k in dcop.variables
        }
        return cycles / elapsed, cycles, cost, assignment
    finally:
        orch.stop_agents(5)
        orch.stop()


def exact_parity():
    """Semantic-equivalence leg of the north-star claim: on a problem
    the BSP trajectory freezes on (send-suppression quiets every edge),
    the device engine and the threaded agent runtime must produce the
    IDENTICAL assignment, hence identical cost.  Larger loopy instances
    oscillate within the stability band and the thread runtime stops on
    wall clock mid-oscillation, so exactness is asserted here and a
    matched-cycle quality bound is asserted at full scale."""
    dcop = build_dcop(PARITY_VARS, seed=PARITY_SEED)
    _, thread_cycles, thread_cost, thread_asg = bench_thread(
        dcop, PARITY_TIMEOUT_S)
    _, res, _ = bench_device(
        dcop, max_cycles=max(thread_cycles, 50), timed=False)
    device_cost, _ = dcop.solution_cost(res.assignment)
    differing = [
        v for v in thread_asg if thread_asg[v] != res.assignment[v]
    ]
    if differing or device_cost != thread_cost:
        print(
            f"bench: EXACT PARITY FAILED device={device_cost} "
            f"thread={thread_cost} differing_vars={len(differing)}",
            file=sys.stderr,
        )
        sys.exit(1)
    return device_cost, thread_cost


# Upper bound for one supervised bench attempt (TPU runs take a few
# minutes incl. compiles; a wedged tunnel hangs forever — this is the
# difference between "no BENCH_r0N.json" and a diagnosed CPU fallback).
CHILD_TIMEOUT_S = 1800
ARTIFACT = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BENCH_TPU_LAST.json")


def _supervise():
    """Run the actual bench in a killable child process.

    A wedged axon tunnel can hang INSIDE a jax call (C++-level, not
    interruptible by signal handlers), so probing once at startup is
    not enough: round 1-3 all fell back to CPU, and a mid-run wedge
    would have produced NO json line at all.  The supervisor probes
    with backoff, runs the bench as a child sharing stdout, kills it on
    timeout, and falls back to a scrubbed-CPU re-exec that always
    emits the result line — with the full probe history embedded."""
    from pydcop_tpu.utils.cleanenv import (
        cpu_fallback_exec,
        probe_with_retries,
        record_diag,
    )

    if probe_with_retries("bench", retries=3):
        env = dict(os.environ)
        env["PYDCOP_BENCH_CHILD"] = "1"
        # Capture the child's stdout so a child that prints its result
        # line and THEN wedges in interpreter teardown still counts as
        # a success (otherwise the CPU fallback would print a second
        # JSON line on the same stream).  stderr stays inherited.
        try:
            proc = subprocess.run(
                [sys.executable] + sys.argv, env=env,
                timeout=CHILD_TIMEOUT_S, stdout=subprocess.PIPE,
                text=True,
            )
            child_out, rc = proc.stdout, proc.returncode
        except subprocess.TimeoutExpired as exc:
            out = exc.stdout
            if isinstance(out, bytes):
                out = out.decode("utf-8", "replace")
            child_out, rc = out or "", None
            record_diag("child_timeout", seconds=CHILD_TIMEOUT_S)
        if _forward_result_line(child_out):
            return
        if rc is None:
            print(
                "bench: supervised run exceeded "
                f"{CHILD_TIMEOUT_S}s (tunnel wedged mid-run); "
                "falling back to CPU", file=sys.stderr,
            )
        else:
            record_diag("child_failed", rc=rc)
            print(f"bench: supervised run failed rc={rc}; falling "
                  "back to CPU", file=sys.stderr)
    cpu_fallback_exec("bench")


def _forward_result_line(child_out: str) -> bool:
    """Print the child's JSON result line if it produced one; every
    other stdout line (informational prints, extra JSON) is forwarded
    to stderr so a supervised run loses nothing (ADVICE r4)."""
    result = None
    for line in (child_out or "").splitlines():
        stripped = line.strip()
        if result is None and stripped.startswith("{"):
            try:
                parsed = json.loads(stripped)
            except ValueError:
                parsed = None
            if parsed is not None and "metric" in parsed:
                result = stripped
                continue
        if stripped:
            print(f"bench[child]: {line}", file=sys.stderr)
    if result is not None:
        print(result)
        return True
    return False


def _try_revive_tpu():
    """On the CPU-fallback path, re-probe the accelerator immediately
    before the headline leg (the wedge is transient — BENCH_r02's chip
    was reachable minutes after its startup probes failed) and restart
    the whole bench on TPU when it answers.  One revival attempt per
    bench invocation (PYDCOP_BENCH_TPU_RETRIED)."""
    from pydcop_tpu.utils.cleanenv import (
        DIAG_ENV,
        default_probe_timeout,
        probe_backend,
        record_diag,
        tpu_env,
    )

    env = tpu_env()
    if env is None or os.environ.get("PYDCOP_BENCH_TPU_RETRIED"):
        return
    # Revival probe budget: 60 s default, PYDCOP_BENCH_PROBE_TIMEOUT
    # overrides (a tunnel that answers in 90 s is revived, not lost).
    ok, error, dt = probe_backend(default_probe_timeout(60), env=env)
    record_diag("revival_probe", ok=ok, error=error,
                seconds=round(dt, 1))
    if not ok:
        return
    print("bench: TPU tunnel revived; restarting on TPU",
          file=sys.stderr)
    env[DIAG_ENV] = os.environ.get(DIAG_ENV, "[]")
    env["PYDCOP_BENCH_TPU_RETRIED"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def _artifact_keys(platform, out):
    """TPU run → persist the result as the last-known-good artifact;
    CPU fallback → reference the artifact so the JSON line always
    carries the best hardware evidence available."""
    if platform == "tpu":
        try:
            with open(ARTIFACT, "w") as fh:
                json.dump(
                    {"recorded_unix": round(time.time(), 1), **out},
                    fh, indent=1)
        except OSError as exc:
            # Never let artifact persistence block the result line.
            print(f"bench: could not write {ARTIFACT}: {exc}",
                  file=sys.stderr)
        return {}
    if not os.path.exists(ARTIFACT):
        return {"last_tpu_artifact": None}
    try:
        with open(ARTIFACT) as fh:
            last = json.load(fh)
    except (OSError, ValueError):
        return {"last_tpu_artifact": "BENCH_TPU_LAST.json (unreadable)"}
    return {
        "last_tpu_artifact": "BENCH_TPU_LAST.json",
        "last_tpu_value": last.get("value"),
        "last_tpu_recorded_unix": last.get("recorded_unix"),
    }


def bench_scale(n_vars: int = SCALE_N_VARS, edge_factor: float = 1.5,
                cycles: int = SCALE_CYCLES, aggregation: str = "scatter",
                layout: str = "edge", return_values: bool = False,
                detail: bool = False):
    """HBM-bound scale leg: a synthetic 1M-variable / 1.5M-factor
    3-coloring whose ~190 MB working set cannot stay VMEM-resident, so
    the measured rate reflects real HBM streaming (the 10k north-star
    problem fits in VMEM and proves nothing about bandwidth).  Arrays
    are built directly (building 1.5M Python constraint objects would
    dominate the bench); the superstep math is identical.

    ``aggregation`` selects the variable-aggregation strategy
    (engine/compile.build_aggregation_arrays); the headline leg runs
    the strategy benchmarks/exp_aggregation.py measured fastest on the
    target backend.  ``layout="lane"`` runs the lane-major superstep
    (ops/maxsum_lane.py; scatter aggregation only) — the layout A/B is
    benchmarks/exp_layout.py.

    Timing is the MARGINAL per-cycle rate via two-point differencing
    (engine/timing.py): the axon tunnel's ``block_until_ready`` is a
    partial sync, and its fixed enqueue+round-trip+fetch overhead
    (~130 ms measured) would otherwise be reported as if it were HBM
    streaming time — round 5 caught a "25,871 cycles/s at 1M vars"
    artifact this way, 10x over the chip's physical HBM peak.  With
    ``cycles < 10`` (parity-only test runs) a single fully-synced call
    is timed instead.

    Returns (cycles/s, graph), or (cycles/s, graph, values) with
    ``return_values=True`` (a full ``cycles``-run's selected assignment
    as numpy — exp_layout's agreement column), or with ``detail=True``
    a trailing dict {sec_per_cycle, fixed_overhead_s}.  With the
    default edge layout the graph feeds roofline accounting; a lane
    graph does NOT (the roofline counters unpack edge-major shapes
    positionally and would count garbage — they reject LaneGraph) and
    is returned for value-parity runs only.
    """
    from functools import partial

    import jax

    from pydcop_tpu.engine.compile import (
        BIG,
        CompiledFactorGraph,
        FactorBucket,
        build_aggregation_arrays,
    )
    from pydcop_tpu.engine.timing import (
        sync,
        timed_call,
        warmed_marginal,
    )
    from pydcop_tpu.ops import maxsum as ops

    if n_vars < 2:
        raise ValueError("bench_scale needs n_vars >= 2")
    rng = np.random.default_rng(7)
    n_factors = int(n_vars * edge_factor)
    var_ids = rng.integers(
        0, n_vars, size=(n_factors, 2)).astype(np.int32)
    # Redraw self-loops (v1 == v2) so the instance is a well-formed
    # coloring problem and the cost semantics stay meaningful.
    loop = var_ids[:, 0] == var_ids[:, 1]
    while loop.any():
        var_ids[loop, 1] = rng.integers(
            0, n_vars, size=int(loop.sum())).astype(np.int32)
        loop = var_ids[:, 0] == var_ids[:, 1]
    eq = np.eye(N_COLORS, dtype=np.float32)
    costs = np.ascontiguousarray(
        np.broadcast_to(eq, (n_factors, N_COLORS, N_COLORS)))
    var_costs = np.full((n_vars + 1, N_COLORS), BIG, np.float32)
    var_costs[:-1] = rng.random((n_vars, N_COLORS)) * 0.01
    var_valid = np.zeros((n_vars + 1, N_COLORS), bool)
    var_valid[:-1] = True
    buckets = (FactorBucket(costs, var_ids),)
    perm, sorted_seg, starts, ends, ell = build_aggregation_arrays(
        buckets, n_vars + 1, aggregation)
    graph = CompiledFactorGraph(
        var_costs=var_costs, var_valid=var_valid, buckets=buckets,
        agg_perm=perm, agg_sorted_seg=sorted_seg,
        agg_starts=starts, agg_ends=ends, agg_ell=ell,
    )
    if layout == "lane":
        if aggregation != "scatter":
            raise ValueError("layout='lane' requires scatter "
                             "aggregation")
        from pydcop_tpu.ops import maxsum_lane as lane_ops

        graph = jax.device_put(lane_ops.to_lane_graph(graph))
        run = lane_ops.run_maxsum
    else:
        graph = jax.device_put(graph)
        run = ops.run_maxsum

    def jitted(c):
        return jax.jit(partial(run, max_cycles=c,
                               stop_on_convergence=False))

    if cycles >= 10:
        lo = max(1, cycles // 5)
        sec_per_cycle, fixed, (state, values) = warmed_marginal(
            jitted, lo, cycles, args=(graph,), reps=3)
        cps = 1.0 / sec_per_cycle if sec_per_cycle > 0 else 0.0
    else:
        # Parity-only runs (tests): a single fully-synced call, warmed
        # so compile time stays out of the window.
        fn = jitted(cycles)
        sync(fn(graph))
        (state, values), elapsed = timed_call(fn, graph)
        sec_per_cycle = elapsed / int(state.cycle)
        fixed = 0.0
        cps = int(state.cycle) / elapsed
    info = {"sec_per_cycle": sec_per_cycle, "fixed_overhead_s": fixed}
    # The flags COMPOSE (ADVICE r5: return_values used to shadow
    # detail and silently drop the timing dict): values come before
    # info, so every single-flag caller keeps its 3-tuple shape and
    # both-flags callers get (cps, graph, values, info).
    out = [cps, graph]
    if return_values:
        out.append(np.asarray(jax.device_get(values)))
    if detail:
        out.append(info)
    return tuple(out) if len(out) > 2 else (cps, graph)


# Sharded-superstep leg: the partitioned engine (min-edge-cut
# partition + shard_map halo exchange, engine/sharding.py) on a
# locally-connected grid.  On TPU the mesh is the real device list;
# on the CPU fallback the leg runs in a CHILD process with
# XLA_FLAGS=--xla_force_host_platform_device_count=8 (jax reads the
# flag at import, so the forced mesh cannot be conjured in-process)
# — the same recipe CI parity tests use, so the 1M-var code path is
# exercised before a TPU ever runs it.
SHARDED_SIDE = 64            # 64x64 grid = 4096 vars, 8064 factors
SHARDED_SHARDS = 8
SHARDED_CYCLES = 100
SHARDED_CHILD_TIMEOUT_S = 600
SCALE_SMOKE_N_VARS = 50_000  # CPU smoke of the 1M-var scale leg
SCALE_SMOKE_CYCLES = 12


def bench_sharded(n_shards: int = SHARDED_SHARDS):
    """Steady-state cycles/s of the partitioned engine on the grid
    instance, plus the partition/communication evidence: cut
    fraction, halo-vs-replicated exchange volume.  Caller guarantees
    >= n_shards devices exist (real or forced-host)."""
    from pydcop_tpu.algorithms.maxsum import build_engine

    dcop = build_grid_dcop(SHARDED_SIDE)
    engine = build_engine(dcop, {"noise": 0.01}, shards=n_shards)
    engine.run(max_cycles=SHARDED_CYCLES, stop_on_convergence=False)
    res = engine.run(
        max_cycles=SHARDED_CYCLES, stop_on_convergence=False)
    cps = res.cycles / res.time_s if res.time_s > 0 else 0.0
    m = res.metrics
    out = {
        "maxsum_cycles_per_sec_sharded": round(cps, 2),
        "sharded_n_vars": SHARDED_SIDE * SHARDED_SIDE,
        "sharded_n_shards": n_shards,
        "sharded_edge_cut_fraction": round(
            m["edge_cut_fraction"], 4),
        "sharded_halo_elems": m[
            "halo_exchange_elems_per_superstep"],
        "sharded_replicated_elems": m[
            "replicated_allreduce_elems_per_superstep"],
        "sharded_balance": round(m["balance"], 3),
    }
    # Shard-loss recovery latency (ISSUE 8): inject a device loss on
    # a FRESH engine for the same instance and report the engine's
    # repartition + state-remap wall time — the time a mid-solve
    # device failure costs on this backend before compute resumes.
    try:
        from pydcop_tpu.resilience.recovery import RecoveryPolicy

        rec_res = build_engine(
            dcop, {"noise": 0.01}, shards=n_shards,
        ).run_checkpointed(
            max_cycles=30, segment_cycles=10,
            stop_on_convergence=False,
            recovery=RecoveryPolicy(trip_shard=((10, 1),)))
        out["shard_recovery_s"] = \
            rec_res.metrics["shard_recovery_s"]
    except Exception as exc:  # noqa: BLE001 — auxiliary sub-leg
        print(f"bench: shard-recovery leg failed ({exc}); "
              "continuing", file=sys.stderr)
        out["shard_recovery_s"] = None
        out["shard_recovery_error"] = \
            f"{type(exc).__name__}: {exc}"[:200]
    return out


def _bench_sharded_forced():
    """CPU path: run bench_sharded in a child with 8 forced host
    devices (the flag must be set before jax imports).  Returns the
    sharded keys, or a None-valued entry with the error — the
    sharded leg never kills the headline line."""
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count="
            f"{SHARDED_SHARDS}").strip()
    env["JAX_PLATFORMS"] = "cpu"
    env["PYDCOP_BENCH_SHARDED_CHILD"] = "1"
    env.pop("PYDCOP_BENCH_CHILD", None)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)], env=env,
            timeout=SHARDED_CHILD_TIMEOUT_S, stdout=subprocess.PIPE,
            text=True,
        )
    except subprocess.TimeoutExpired:
        return {"maxsum_cycles_per_sec_sharded": None,
                "sharded_error": "child timeout"}
    for line in (proc.stdout or "").splitlines():
        line = line.strip()
        if line.startswith("{"):
            try:
                parsed = json.loads(line)
            except ValueError:
                continue
            if "maxsum_cycles_per_sec_sharded" in parsed:
                parsed["sharded_backend"] = "cpu"
                parsed["sharded_forced_host_devices"] = SHARDED_SHARDS
                return parsed
    return {"maxsum_cycles_per_sec_sharded": None,
            "sharded_error": f"child rc={proc.returncode}, "
                             "no result line"}


# Time-to-target-cost leg (ISSUE 10): the headline number of the
# work-reduction stack.  A loopy LARGE-DOMAIN coloring (the regime
# branch-and-bound pruning targets) is traced once to find the
# reference cost — the final (converged-and-frozen) cost of the
# fixed-budget run itself, deterministic for the fixed seed; the
# timed quantity is a warmed PRUNED run of the full TTC_CYCLES budget
# (the serving dispatch shape: batched dispatches never early-exit,
# so the budget wall IS the time the answer takes).  This changes
# what the bench optimizes from cycles/sec to wall-clock to a known
# solution quality — judged by tools/bench_sentinel.py as a
# lower-is-better family per backend.
TTC_N_VARS = 240
TTC_DOMAIN = 128
TTC_EDGE_FACTOR = 1.5
TTC_CYCLES = 160
TTC_UNARY_SPREAD = 400


def build_ttc_graph(seed: int = 11):
    """Loopy D=TTC_DOMAIN coloring with integer unary costs, built
    directly as arrays (same recipe as bench_scale): equality penalty
    1 per edge, unary integers in [0, TTC_UNARY_SPREAD) — integer
    tables keep the pruned trajectory bit-identical to dense
    (ops/maxsum)."""
    from pydcop_tpu.engine.compile import (
        BIG,
        CompiledFactorGraph,
        FactorBucket,
    )

    rng = np.random.default_rng(seed)
    n_factors = int(TTC_N_VARS * TTC_EDGE_FACTOR)
    var_ids = rng.integers(
        0, TTC_N_VARS, size=(n_factors, 2)).astype(np.int32)
    loop = var_ids[:, 0] == var_ids[:, 1]
    var_ids[loop, 1] = (var_ids[loop, 0] + 1) % TTC_N_VARS
    eye = np.eye(TTC_DOMAIN, dtype=np.float32)
    costs = np.ascontiguousarray(np.broadcast_to(
        eye, (n_factors, TTC_DOMAIN, TTC_DOMAIN))).copy()
    var_costs = np.full((TTC_N_VARS + 1, TTC_DOMAIN), BIG, np.float32)
    var_costs[:-1] = rng.integers(
        0, TTC_UNARY_SPREAD,
        size=(TTC_N_VARS, TTC_DOMAIN)).astype(np.float32)
    var_valid = np.zeros((TTC_N_VARS + 1, TTC_DOMAIN), bool)
    var_valid[:-1] = True
    return CompiledFactorGraph(
        var_costs=var_costs, var_valid=var_valid,
        buckets=(FactorBucket(costs, var_ids),))


def bench_time_to_cost():
    """{maxsum_time_to_cost_ms, ...}: wall-clock to the reference cost
    under the SERVING dispatch shape — a fixed ``TTC_CYCLES`` budget
    with no convergence stop (batched dispatches never early-exit:
    engine/batch.run_stacked), so the request's time-to-answer IS the
    full-budget wall and the reference cost is the budget run's final
    (converged-and-frozen) cost.  The pruned trajectory is
    bit-identical to dense, so the ratio against ``ttc_dense_ms``
    isolates the per-cycle work reduction: after the transient the
    survivor sets collapse and most of the budget runs the compacted
    kernel.  Never kills the headline line (caller wraps)."""
    from functools import partial

    import jax

    from pydcop_tpu.engine.timing import sync, timed_call
    from pydcop_tpu.ops import maxsum as ops

    graph = jax.device_put(build_ttc_graph())
    trace_fn = jax.jit(partial(
        ops.run_maxsum_trace, max_cycles=TTC_CYCLES,
        stop_on_convergence=False))
    _state, _values, costs = sync(trace_fn(graph))
    costs = np.asarray(costs)
    ref = float(costs[-1])
    below = np.nonzero(costs <= ref)[0]
    cycles_to_ref = int(below[0]) + 1 if below.size else TTC_CYCLES

    def timed_run(prune: bool) -> float:
        fn = jax.jit(partial(
            ops.run_maxsum, max_cycles=TTC_CYCLES,
            stop_on_convergence=False, prune=prune))
        sync(fn(graph))  # compile + warm
        best = float("inf")
        for _ in range(3):
            _out, elapsed = timed_call(fn, graph)
            best = min(best, elapsed)
        return best

    pruned_s = timed_run(True)
    dense_s = timed_run(False)
    return {
        "maxsum_time_to_cost_ms": round(pruned_s * 1e3, 2),
        "ttc_dense_ms": round(dense_s * 1e3, 2),
        "ttc_ref_cost": ref,
        "ttc_cycles": cycles_to_ref,
        "ttc_n_vars": TTC_N_VARS,
        "ttc_domain": TTC_DOMAIN,
    }


# Serving-throughput leg: closed-loop clients firing small random
# coloring DCOPs at the solve service (pydcop_tpu/serving).  Small
# problems + several structures is the multi-tenant traffic shape the
# service exists for; the number that matters is sustained
# problems/sec with per-request latency percentiles.
SERVE_N_VARS = (24, 30)         # two structure bins
SERVE_POOL_PER_STRUCT = 6       # distinct instances per structure
SERVE_CLIENTS = 4
SERVE_DURATION_S = 4.0
SERVE_MAX_CYCLES = 60


def bench_serving():
    """Sustained service throughput: SERVE_CLIENTS closed-loop client
    threads submit-and-wait random coloring DCOPs for
    SERVE_DURATION_S.  Returns {serve_problems_per_sec, serve_p50_ms,
    serve_p99_ms, serve_batched_fraction} (None values when the
    service completed nothing — never crashes the bench)."""
    import threading

    from pydcop_tpu.serving.service import SolveService

    pool = {
        n: [build_dcop_small(n, seed) for seed in
            range(SERVE_POOL_PER_STRUCT)]
        for n in SERVE_N_VARS
    }
    service = SolveService(max_queue=512, batch_window_s=0.005,
                           max_batch=16).start()
    try:
        params = {"max_cycles": SERVE_MAX_CYCLES}
        # Warm: one dispatch per structure compiles the batched
        # programs so the timed window measures steady state.
        for dcops in pool.values():
            rid = service.submit(dcops[0], params=params)
            service.result(rid, wait=60)
        latencies = []
        completed = [0]
        lock = threading.Lock()
        t_end = time.perf_counter() + SERVE_DURATION_S

        def client(idx):
            n = SERVE_N_VARS[idx % len(SERVE_N_VARS)]
            i = 0
            while time.perf_counter() < t_end:
                dcop = pool[n][i % SERVE_POOL_PER_STRUCT]
                i += 1
                t0 = time.perf_counter()
                rid = service.submit(dcop, params=params)
                res = service.result(rid, wait=60)
                t1 = time.perf_counter()
                if res is not None and res["status"] == "FINISHED":
                    with lock:
                        latencies.append(t1 - t0)
                        completed[0] += 1

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(SERVE_CLIENTS)]
        t_start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=SERVE_DURATION_S + 120)
        elapsed = time.perf_counter() - t_start
        stats = service.stats()
    finally:
        service.stop(drain=False)
    if not latencies or elapsed <= 0:
        return {"serve_problems_per_sec": None}
    lat_ms = np.asarray(latencies) * 1e3
    p99_exemplar = (stats.get("latency_exemplars") or {}).get("p99")
    return {
        "serve_problems_per_sec": round(completed[0] / elapsed, 2),
        "serve_p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
        "serve_p99_ms": round(float(np.percentile(lat_ms, 99)), 2),
        "serve_requests": completed[0],
        "serve_batched_fraction": round(
            stats["batched_dispatches"] / stats["dispatches"], 3)
            if stats["dispatches"] else None,
        # The p99 bucket's exemplar: a flagged regression in the
        # sentinel points at a concrete request trace to open.
        "exemplar_trace_id": (p99_exemplar or {}).get("trace_id"),
        # The efficiency plane's verdict on the leg (ISSUE 14):
        # backend-honest attainment + useful-work fraction + the
        # where-the-time-went component sums, so a throughput number
        # always ships with the evidence of HOW the device time was
        # spent.  Detail key — the sentinel ignores it.
        "serve_efficiency": stats.get("efficiency"),
    }


# Crash-recovery replay leg (ISSUE 8): how long a --recover start
# takes to scan + compact the journal and push REPLAY_N acknowledged
# requests back through the queue — the downtime a serve-process
# crash adds before the service answers again.
REPLAY_N = 8
REPLAY_N_VARS = 24
REPLAY_MAX_CYCLES = 60


def bench_recovery_replay():
    """Time a journal crash-recovery start: REPLAY_N accepted-but-
    unfinished records on disk, ``SolveService(recover=True).start()``
    timed (scan, torn-tail handling, compaction, re-compile, enqueue
    — everything between process start and the queue being live
    again).  Returns {serve_recovery_replay_s, serve_recovery_replayed}
    (None-valued on failure — never kills the headline line)."""
    import shutil
    import tempfile

    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving.journal import (
        RequestJournal,
        accepted_record,
    )
    from pydcop_tpu.serving.service import SolveService

    journal_dir = tempfile.mkdtemp(prefix="bench_replay_")
    try:
        jnl = RequestJournal(journal_dir)
        for i in range(REPLAY_N):
            jnl.append(accepted_record(
                f"r{i}", dcop_yaml(build_dcop_small(REPLAY_N_VARS, i)),
                {"max_cycles": REPLAY_MAX_CYCLES}))
        jnl.close()
        service = SolveService(journal_dir=journal_dir, recover=True,
                               batch_window_s=0.005, max_batch=16)
        t0 = time.perf_counter()
        service.start()
        replay_s = time.perf_counter() - t0
        try:
            for i in range(REPLAY_N):
                service.result(f"r{i}", wait=120)
        finally:
            service.stop(drain=False)
        return {
            "serve_recovery_replay_s": round(replay_s, 4),
            "serve_recovery_replayed": REPLAY_N,
        }
    finally:
        shutil.rmtree(journal_dir, ignore_errors=True)


# Stateful-session leg (ISSUE 13): the dynamic-DCOP serving workload.
# A warm DynamicMaxSumEngine absorbs a seeded change_factor stream;
# per event we time wall-clock until the warm trajectory RECOVERS the
# cost a cold re-solve of the mutated problem reaches, against that
# cold re-solve itself ON THE SAME COMPILED PROGRAM (state reset, not
# a rebuilt engine — isolating the warm-start message benefit from
# compile-cache effects, which would flatter warm for free).
SESSION_N_VARS = 48
SESSION_EVENTS = 16
SESSION_MAX_CYCLES = 400
SESSION_SEGMENT_CYCLES = 25


def bench_sessions():
    """Warm vs cold after scenario events.  Emits
    ``session_time_to_recovered_cost_ms`` (median over the event
    stream, LOWER is better — sentinel family ``session_recovery``),
    ``session_events_per_sec`` (sustained apply+re-converge rate —
    family ``session_events``), the cold-baseline median and the
    warm/cold speedup.  None-valued on failure — never kills the
    headline line."""
    from pydcop_tpu.engine.dynamic import build_dynamic_engine

    rng = np.random.default_rng(1306)
    base = build_dcop_small(SESSION_N_VARS, 0)
    params = {"noise": 0.0}
    warm = build_dynamic_engine(base, params)
    cold = build_dynamic_engine(base, params)
    # Converge the initial problem, then run one throwaway
    # SEGMENT-sized call: max_cycles is part of the superstep
    # program's jit key, so the timed warm loop (segment-sized runs)
    # and the timed cold runs (full-budget runs) each need their
    # program compiled HERE or the first timed event pays a compile.
    warm.run(max_cycles=SESSION_MAX_CYCLES)
    warm.run(max_cycles=SESSION_SEGMENT_CYCLES)
    cold.run(max_cycles=SESSION_MAX_CYCLES)
    names = sorted(warm.factors)
    warm_ms, cold_ms, matched = [], [], 0
    warm_wall = 0.0
    for _ in range(SESSION_EVENTS):
        name = names[int(rng.integers(len(names)))]
        scope = warm.factors[name].dimensions
        table = rng.integers(
            0, 10, size=tuple(len(v.domain) for v in scope)
        ).astype(float)
        from pydcop_tpu.dcop.relations import NAryMatrixRelation

        # Cold baseline: same edit, messages thrown away.
        cold.change_factor(
            name, NAryMatrixRelation(list(scope), table, name))
        cold._state = None
        t0 = time.perf_counter()
        cres = cold.run(max_cycles=SESSION_MAX_CYCLES)
        cold_s = time.perf_counter() - t0
        cold_cost = cold.cost(cres.assignment)
        # Warm path: apply + re-converge from the pre-event fixpoint,
        # in anytime segments, until the cold-solve cost is recovered
        # (or the warm fixpoint is reached — a warm run may settle at
        # a different local optimum).
        t0 = time.perf_counter()
        warm.change_factor(
            name, NAryMatrixRelation(list(scope), table, name))
        recovered_cost = None
        for _seg in range(
                SESSION_MAX_CYCLES // SESSION_SEGMENT_CYCLES + 1):
            wres = warm.run(max_cycles=SESSION_SEGMENT_CYCLES)
            recovered_cost = warm.cost(wres.assignment)
            if recovered_cost <= cold_cost + 1e-9 or wres.converged:
                break
        warm_s = time.perf_counter() - t0
        warm_wall += warm_s
        warm_ms.append(warm_s * 1e3)
        cold_ms.append(cold_s * 1e3)
        if recovered_cost is not None \
                and recovered_cost <= cold_cost + 1e-9:
            matched += 1
    warm_med = float(np.median(warm_ms))
    cold_med = float(np.median(cold_ms))
    return {
        "session_time_to_recovered_cost_ms": round(warm_med, 3),
        "session_cold_resolve_ms": round(cold_med, 3),
        "session_warm_speedup": (round(cold_med / warm_med, 2)
                                 if warm_med > 0 else None),
        "session_events_per_sec": (
            round(SESSION_EVENTS / warm_wall, 2)
            if warm_wall > 0 else None),
        "session_events": SESSION_EVENTS,
        # Fraction of events where warm re-converged to a cost at
        # least as good as the cold re-solve — the quality guard on
        # the speed claim.
        "session_cost_match_fraction": round(
            matched / SESSION_EVENTS, 3),
    }


def build_dcop_small(n_vars: int, seed: int):
    """Ring + chord coloring with random cost tables — the serving
    bench's per-request problem (same topology per n_vars, so same
    structure bin; different tables per seed)."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP(f"serve_{n_vars}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)]
    edges += [(i, (i + n_vars // 2) % n_vars)
              for i in range(0, n_vars, 3)]
    for k, (i, j) in enumerate(edges):
        table = rng.integers(0, 10, size=(N_COLORS, N_COLORS))
        dcop.add_constraint(NAryMatrixRelation(
            [vs[i], vs[j]], table.astype(float), f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


# Mixed-structure serving leg (ISSUE 11): zipf-distributed DISTINCT
# topologies — the production-shaped traffic on which pure structure
# binning degenerates to batch-size-1.  The leg runs the same seeded
# request stream twice, envelope packing ON and OFF, so the JSON line
# carries both the envelope throughput and the no-envelope baseline it
# must beat.
SERVE_MIXED_STRUCTS = 24
SERVE_MIXED_CLIENTS = 8
SERVE_MIXED_DURATION_S = 4.0
SERVE_MIXED_WINDOW_S = 0.005
SERVE_MIXED_MAX_CYCLES = 60
SERVE_MIXED_ZIPF_A = 1.05


def build_dcop_mixed(struct_idx: int, seed: int):
    """One of SERVE_MIXED_STRUCTS structurally DISTINCT small
    colorings: the ring size (``14 + 3*struct_idx`` — distinct per
    index, which alone guarantees distinct structure signatures) plus
    ``struct_idx % 4`` half-way chords, so the edge count varies too;
    different seeds only change cost tables."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    n_vars = 14 + 3 * struct_idx
    dom = Domain("colors", "color", list(range(N_COLORS)))
    dcop = DCOP(f"mix{struct_idx}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)]
    edges += [(i, (i + n_vars // 2) % n_vars)
              for i in range(struct_idx % 4)]
    seen = set()
    for k, (i, j) in enumerate(edges):
        if i == j or (min(i, j), max(i, j)) in seen:
            continue
        seen.add((min(i, j), max(i, j)))
        table = rng.integers(0, 10, size=(N_COLORS, N_COLORS))
        dcop.add_constraint(NAryMatrixRelation(
            [vs[i], vs[j]], table.astype(float), f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def bench_serving_mixed():
    """Sustained throughput under zipf-diverse structures, envelope
    packing ON vs OFF on the same seeded stream.  Emits
    ``serve_mixed_problems_per_sec`` (the sentinel family) +
    latency percentiles + ``serve_mixed_batched_fraction`` (requests
    that shared a device dispatch — ~0 without envelopes on this
    traffic) and the no-envelope baseline keys.  Also emits
    ``serve_overlap_fraction`` (ISSUE 18): the measured-window
    fraction of device execute wall the pipelined scheduler hid
    decode work under."""
    import threading

    from pydcop_tpu.observability.efficiency import (
        tracker as efficiency_tracker,
    )
    from pydcop_tpu.serving.service import SolveService

    # Structure frequencies: zipf over ranks, so a couple of
    # structures dominate and a long tail stays rare — the worst case
    # for pure structure binning (the tail never coalesces).
    ranks = np.arange(1, SERVE_MIXED_STRUCTS + 1, dtype=float)
    probs = ranks ** -SERVE_MIXED_ZIPF_A
    probs /= probs.sum()
    pool = {
        s: [build_dcop_mixed(s, seed) for seed in range(4)]
        for s in range(SERVE_MIXED_STRUCTS)
    }

    def run_once(envelope_packing: bool,
                 duration_s: float = SERVE_MIXED_DURATION_S,
                 pipeline: bool = True):
        service = SolveService(
            max_queue=512, batch_window_s=SERVE_MIXED_WINDOW_S,
            max_batch=16, pipeline=pipeline, speculate=False,
            envelope_packing=envelope_packing).start()
        try:
            params = {"max_cycles": SERVE_MIXED_MAX_CYCLES}
            # Warm pass 1: one request per structure, submit-and-WAIT
            # so each dispatches solo — compiles the layouts and the
            # per-structure solo programs (what leftover singleton
            # groups and the whole no-envelope run reuse; submitted
            # together they would coalesce into one packed dispatch
            # and leave every solo program cold).
            for s in range(SERVE_MIXED_STRUCTS):
                service.result(
                    service.submit(pool[s][0], params=params),
                    wait=60)
            # Warm pass 1b: exact-tier bin programs — same-structure
            # pairs for every structure, plus bin-4 for the zipf head
            # (the sizes structure collisions actually produce).
            for s in range(SERVE_MIXED_STRUCTS):
                for size in ((2, 4) if s < 6 else (2,)):
                    burst = [service.submit(pool[s][i % 4],
                                            params=params)
                             for i in range(size)]
                    for rid in burst:
                        service.result(rid, wait=60)
            # Warm pass 2: concurrent mixed bursts of several sizes —
            # compiles the packed-union programs on the rungs real
            # group compositions land on (binning.UNION_LADDER bounds
            # these; v and row rungs correlate, so a spread of burst
            # sizes covers the set).  Exact-tier bin programs warm
            # organically in the discardable pre-runs below — the jit
            # cache is process-global, so without identical warm
            # treatment whichever measured run went first would eat
            # every compile and the comparison would be ordering
            # noise, not packing.
            for size in (2, 3, 5, 8, 12, SERVE_MIXED_STRUCTS):
                burst = [service.submit(pool[s % SERVE_MIXED_STRUCTS]
                                        [1], params=params)
                         for s in range(size)]
                for rid in burst:
                    service.result(rid, wait=60)
            stats0 = service.stats()
            # Window-scoped efficiency ledger (ISSUE 18): the warm
            # passes above dispatch and decode too, so the overlap
            # fraction must come from a tracker cleared at the
            # measured window's start, not the service-lifetime
            # /stats ratio.
            efficiency_tracker.clear()
            latencies = []
            completed = [0]
            lock = threading.Lock()
            t_end = time.perf_counter() + duration_s

            def client(idx):
                rng = np.random.default_rng(1000 + idx)
                i = 0
                while time.perf_counter() < t_end:
                    s = int(rng.choice(SERVE_MIXED_STRUCTS, p=probs))
                    dcop = pool[s][i % 4]
                    i += 1
                    t0 = time.perf_counter()
                    rid = service.submit(dcop, params=params)
                    res = service.result(rid, wait=60)
                    t1 = time.perf_counter()
                    if res is not None and res["status"] == "FINISHED":
                        with lock:
                            latencies.append(t1 - t0)
                            completed[0] += 1

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(SERVE_MIXED_CLIENTS)]
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=duration_s + 120)
            elapsed = time.perf_counter() - t_start
            stats = service.stats()
            rollup = efficiency_tracker.rollup()
        finally:
            service.stop(drain=False)
        if not latencies or elapsed <= 0:
            return None
        lat_ms = np.asarray(latencies) * 1e3
        # Window-only ledger deltas: the warm passes batched too and
        # must not inflate the fraction.
        batched = (stats["batched_requests"]
                   - stats0["batched_requests"])
        return {
            "pps": round(completed[0] / elapsed, 2),
            "p50": round(float(np.percentile(lat_ms, 50)), 2),
            "p99": round(float(np.percentile(lat_ms, 99)), 2),
            "requests": completed[0],
            # Fraction of completed requests that SHARED their device
            # dispatch — the number that collapses on this traffic
            # without the envelope tier.
            "batched_fraction": round(
                min(batched / completed[0], 1.0), 3)
                if completed[0] else None,
            "envelope_dispatches": (stats["envelope_dispatches"]
                                    - stats0["envelope_dispatches"]),
            "lane_dispatches": (stats["lane_dispatches"]
                                - stats0["lane_dispatches"]),
            # Measured-window decode/dispatch overlap: fraction of
            # device execute wall the pipelined scheduler hid decode
            # work under (0.0 with --no_pipeline).
            "overlap_fraction": rollup.get(
                "pipeline_overlap_fraction"),
            "pipelined_dispatches": (rollup.get("pipeline") or
                                     {}).get("dispatches", 0),
        }

    # Discardable pre-runs (1 s each): the jit caches and process
    # state are GLOBAL, so whichever measured run went first would
    # eat every residual compile and donate its warmth to the other.
    # After one short pass per configuration both measured runs see
    # the same fully-warmed process.
    run_once(True, duration_s=2.0)
    run_once(False, duration_s=2.0)
    on = run_once(True)
    off = run_once(False)
    if on is None:
        return {"serve_mixed_problems_per_sec": None}
    out = {
        "serve_mixed_problems_per_sec": on["pps"],
        "serve_mixed_p50_ms": on["p50"],
        "serve_mixed_p99_ms": on["p99"],
        "serve_mixed_requests": on["requests"],
        "serve_mixed_batched_fraction": on["batched_fraction"],
        "serve_mixed_envelope_dispatches": on["envelope_dispatches"],
        "serve_mixed_lane_dispatches": on["lane_dispatches"],
        # Sentinel family ``serve_overlap`` (ISSUE 18): measured-
        # window pipelined decode/dispatch overlap fraction.
        "serve_overlap_fraction": on["overlap_fraction"],
        "serve_overlap_pipelined_dispatches":
            on["pipelined_dispatches"],
    }
    if off is not None:
        out["serve_mixed_baseline_problems_per_sec"] = off["pps"]
        out["serve_mixed_baseline_batched_fraction"] = \
            off["batched_fraction"]
    return out


# Fleet-serving leg (ISSUE 15): aggregate problems/sec through the
# replicated serve plane — REAL worker subprocesses behind the
# structure-affinity router — at replicas=1/2/4 on the same seeded
# mixed-structure stream, plus the affinity-vs-round-robin A/B at
# replicas=2.  replicas=1 also runs THROUGH the router so every leg
# pays the same wire overhead and the speedup isolates replication.
FLEET_STRUCTS = (20, 24, 28, 32)
FLEET_POOL_PER_STRUCT = 4
FLEET_MAX_CYCLES = 60
FLEET_DURATION_S = 4.0
FLEET_WARM_S = 3.0
FLEET_REPLICA_COUNTS = (1, 2, 4)
# One FIXED closed-loop client pool across every replica count — the
# acceptance's "same stream": with clients scaled per replica the r1
# leg is latency-bound (clients/latency), not capacity-bound, and the
# speedup would measure the client pool, not the fleet.
FLEET_CLIENTS = 12


def _fleet_post(url, payload, timeout=60):
    import urllib.error
    import urllib.request

    req = urllib.request.Request(
        url + "/solve", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def bench_serving_fleet():
    """Closed-loop clients against a real fleet.  Emits
    ``fleet_problems_per_sec_r<N>`` per replica count (the sentinel
    family ``serving_fleet`` judges the r2 value),
    ``fleet_speedup_r2`` (r2/r1 on the same stream),
    ``fleet_affinity_hit_fraction`` and the round-robin A/B
    (``fleet_rr_problems_per_sec`` / ``fleet_affinity_gain``) —
    affinity must BEAT round-robin for the routing complexity to pay
    its way.  None-valued on failure — never kills the headline."""
    import threading

    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving.router import FleetRouter, RouterFrontEnd

    pool = {
        n: [dcop_yaml(build_dcop_small(n, seed))
            for seed in range(FLEET_POOL_PER_STRUCT)]
        for n in FLEET_STRUCTS
    }
    params = {"max_cycles": FLEET_MAX_CYCLES}
    worker_args = ["--batch_window", "0.005", "--max_batch", "16",
                   "--max_queue", "512",
                   "--cycles", str(FLEET_MAX_CYCLES)]

    def run_leg(replicas: int, affinity: str):
        router = FleetRouter(replicas=replicas,
                             worker_args=worker_args,
                             affinity=affinity).start()
        front = RouterFrontEnd(router, port=0).start()
        url = front.url
        try:
            completed = [0]
            latencies = []
            lock = threading.Lock()
            state = {"t_end": 0.0}

            def client(idx, record):
                rng = np.random.default_rng(7000 + idx)
                i = 0
                while time.perf_counter() < state["t_end"]:
                    n = FLEET_STRUCTS[int(rng.integers(
                        len(FLEET_STRUCTS)))]
                    payload = pool[n][i % FLEET_POOL_PER_STRUCT]
                    i += 1
                    t0 = time.perf_counter()
                    status, body = _fleet_post(url, {
                        "dcop": payload, "wait": True,
                        "timeout": 60, "params": params})
                    t1 = time.perf_counter()
                    if record and status == 200 \
                            and body.get("status") == "FINISHED":
                        with lock:
                            latencies.append(t1 - t0)
                            completed[0] += 1

            def drive(duration, record):
                state["t_end"] = time.perf_counter() + duration
                threads = [
                    threading.Thread(target=client,
                                     args=(i, record))
                    for i in range(FLEET_CLIENTS)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=duration + 120)

            drive(FLEET_WARM_S, record=False)   # compile warm-up
            t_start = time.perf_counter()
            drive(FLEET_DURATION_S, record=True)
            elapsed = time.perf_counter() - t_start
            stats = router.stats()
        finally:
            front.stop()
            router.stop(drain=False)
        if not completed[0] or elapsed <= 0:
            return None
        lat_ms = np.asarray(latencies) * 1e3
        return {
            "pps": round(completed[0] / elapsed, 2),
            "p50": round(float(np.percentile(lat_ms, 50)), 2),
            "p99": round(float(np.percentile(lat_ms, 99)), 2),
            "requests": completed[0],
            "affinity_hit_fraction": stats["affinity_hit_fraction"],
        }

    out = {}
    by_replicas = {}
    for replicas in FLEET_REPLICA_COUNTS:
        leg = run_leg(replicas, "structure")
        by_replicas[replicas] = leg
        if leg is None:
            out[f"fleet_problems_per_sec_r{replicas}"] = None
            continue
        out[f"fleet_problems_per_sec_r{replicas}"] = leg["pps"]
        if replicas == 2:
            out["fleet_p50_ms"] = leg["p50"]
            out["fleet_p99_ms"] = leg["p99"]
            out["fleet_requests"] = leg["requests"]
            out["fleet_affinity_hit_fraction"] = \
                leg["affinity_hit_fraction"]
    r1, r2 = by_replicas.get(1), by_replicas.get(2)
    if r1 and r2:
        out["fleet_speedup_r2"] = round(r2["pps"] / r1["pps"], 3)
    rr = run_leg(2, "round_robin")
    if rr and r2:
        out["fleet_rr_problems_per_sec"] = rr["pps"]
        out["fleet_affinity_gain"] = round(r2["pps"] / rr["pps"], 3)
    # Fleet-trace side-channel (ISSUE 20): the SAME r2 leg with the
    # trace plane off (workers inherit the env knob).  The r2 leg
    # above ran with tracing ON (the default), so off/on is the
    # plane's whole cost — context minting, header stamping, span
    # shipping, collector ingest.  The perf-smoke pairwise gate
    # enforces <= 2%; this emits the longer-horizon number for the
    # sentinel history.
    from pydcop_tpu.observability import fleettrace

    prev = os.environ.get(fleettrace.ENV_KNOB)
    os.environ[fleettrace.ENV_KNOB] = "0"
    try:
        off = run_leg(2, "structure")
    finally:
        if prev is None:
            os.environ.pop(fleettrace.ENV_KNOB, None)
        else:
            os.environ[fleettrace.ENV_KNOB] = prev
    if off and r2:
        out["fleet_trace_off_problems_per_sec"] = off["pps"]
        out["fleet_trace_overhead"] = round(
            off["pps"] / r2["pps"], 3)
    return out


# Partition-tolerant fleet leg (ISSUE 19): the SAME closed-loop
# 2-replica stream run clean and then under a seeded 1%-drop /
# 20ms-delay netfault plan on the router->replica /solve links
# (liveness probes spared via the path= scope, so the leg measures
# retry absorption, not false death verdicts).  Every request
# carries a deadline_s; the router's idempotent retry must absorb
# every injected fault — zero acked requests lost, zero retry
# budgets exhausted — or the leg fails.  Sentinel family
# "fleet_faulted" (the faulted problems/sec: its own family, NOT
# compared against the clean serving_fleet numbers).
FLEET_FAULT_SPEC = ("seed=19;link=router>replica-*,path=/solve,"
                    "drop=0.01,delay_ms=20")
FLEET_FAULT_DEADLINE_S = 30.0


def bench_serving_fleet_faulted():
    """Closed-loop clients against a 2-replica fleet with seeded
    drop+delay on the solve links.  Emits
    ``fleet_faulted_problems_per_sec`` (the sentinel value),
    ``fleet_faulted_clean_problems_per_sec`` /
    ``fleet_faulted_throughput_fraction`` (the same stream with the
    plan cleared, same process, for the overhead read),
    ``fleet_faulted_retries`` and the two MUST-be-zero ledgers
    ``fleet_faulted_lost_acked`` / ``fleet_faulted_budget_exceeded``.
    None-valued on failure — never kills the headline."""
    import threading
    import urllib.error
    import urllib.request

    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving import netfault
    from pydcop_tpu.serving.router import FleetRouter, RouterFrontEnd

    pool = {
        n: [dcop_yaml(build_dcop_small(n, seed))
            for seed in range(FLEET_POOL_PER_STRUCT)]
        for n in FLEET_STRUCTS
    }
    params = {"max_cycles": FLEET_MAX_CYCLES}
    worker_args = ["--batch_window", "0.005", "--max_batch", "16",
                   "--max_queue", "512",
                   "--cycles", str(FLEET_MAX_CYCLES)]

    def poll_result(url, rid, deadline):
        while time.perf_counter() < deadline:
            try:
                with urllib.request.urlopen(
                        url + f"/result/{rid}", timeout=10) as resp:
                    body = json.loads(resp.read())
            except urllib.error.HTTPError as err:
                body = json.loads(err.read())
            except OSError:
                time.sleep(0.2)
                continue
            if body.get("status") in ("FINISHED", "ERROR"):
                return body.get("status") == "FINISHED"
            time.sleep(0.2)
        return False

    def run_leg(faulted: bool):
        router = FleetRouter(replicas=2, worker_args=worker_args,
                             affinity="structure").start()
        front = RouterFrontEnd(router, port=0).start()
        url = front.url
        try:
            completed = [0]
            acked_pending = []
            lock = threading.Lock()
            state = {"t_end": 0.0}

            def client(idx, record):
                rng = np.random.default_rng(9000 + idx)
                i = 0
                while time.perf_counter() < state["t_end"]:
                    n = FLEET_STRUCTS[int(rng.integers(
                        len(FLEET_STRUCTS)))]
                    payload = pool[n][i % FLEET_POOL_PER_STRUCT]
                    i += 1
                    status, body = _fleet_post(url, {
                        "dcop": payload, "wait": True,
                        "timeout": 60, "params": params,
                        "deadline_s": FLEET_FAULT_DEADLINE_S})
                    if not record:
                        continue
                    if status == 200 \
                            and body.get("status") == "FINISHED":
                        with lock:
                            completed[0] += 1
                    elif status in (200, 202) and body.get("id"):
                        # Acked but not finished in the wait window:
                        # the zero-loss ledger must resolve it.
                        with lock:
                            acked_pending.append(body["id"])

            def drive(duration, record):
                state["t_end"] = time.perf_counter() + duration
                threads = [
                    threading.Thread(target=client,
                                     args=(i, record))
                    for i in range(FLEET_CLIENTS)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=duration + 120)

            drive(FLEET_WARM_S, record=False)   # clean warm-up
            if faulted:
                netfault.install(FLEET_FAULT_SPEC)
            t_start = time.perf_counter()
            drive(FLEET_DURATION_S, record=True)
            elapsed = time.perf_counter() - t_start
            injected = netfault.counters()
            netfault.clear()
            stats = router.stats()
            # Resolve every acked-but-pending id AFTER the faults are
            # cleared: an ack the fleet cannot honor is a lost
            # request, whatever the link did.
            lost = 0
            poll_deadline = time.perf_counter() + 60.0
            for rid in acked_pending:
                done = poll_result(url, rid, poll_deadline)
                if done:
                    completed[0] += 1
                else:
                    lost += 1
        finally:
            netfault.clear()
            front.stop()
            router.stop(drain=False)
        if not completed[0] or elapsed <= 0:
            return None
        return {
            "pps": round(completed[0] / elapsed, 2),
            "requests": completed[0],
            "lost": lost,
            "retries": stats.get("retries", 0),
            "budget_exceeded": stats.get("retry_budget_exceeded", 0),
            "injected": injected,
        }

    clean = run_leg(faulted=False)
    faulted = run_leg(faulted=True)
    if faulted is None:
        return {"fleet_faulted_problems_per_sec": None,
                "fleet_faulted_error":
                    "faulted leg produced no completions"}
    if faulted["lost"]:
        raise RuntimeError(
            f"{faulted['lost']} acked request(s) lost under the "
            f"injected fault plan (retries {faulted['retries']})")
    if faulted["budget_exceeded"]:
        raise RuntimeError(
            f"{faulted['budget_exceeded']} retry budget(s) exhausted "
            f"under a {FLEET_FAULT_DEADLINE_S:.0f}s deadline")
    out = {
        "fleet_faulted_problems_per_sec": faulted["pps"],
        "fleet_faulted_requests": faulted["requests"],
        "fleet_faulted_lost_acked": faulted["lost"],
        "fleet_faulted_retries": faulted["retries"],
        "fleet_faulted_budget_exceeded": faulted["budget_exceeded"],
        "fleet_faulted_injected_drop":
            faulted["injected"].get("drop", 0),
        "fleet_faulted_injected_delay":
            faulted["injected"].get("delay", 0),
    }
    if clean:
        out["fleet_faulted_clean_problems_per_sec"] = clean["pps"]
        out["fleet_faulted_throughput_fraction"] = round(
            faulted["pps"] / clean["pps"], 3)
    return out


# Elastic-fleet leg (ISSUE 16): one two-host fleet (socket-distinct
# replica processes striped over simulated host identities) driven
# through four phases — baseline throughput, live session migration
# with hard cost parity, a 4x closed-loop traffic step against the
# SLO autoscaler, and a host kill mid-burst that must lose zero
# acknowledged requests or session events.  Sentinel family
# "fleet_elastic" (the baseline problems/sec).
FLEET_ELASTIC_N_VARS = 24
FLEET_ELASTIC_POOL = 4
FLEET_ELASTIC_MAX_CYCLES = 60
FLEET_ELASTIC_BASE_CLIENTS = 3
FLEET_ELASTIC_STEP_CLIENTS = 12      # the 4x traffic step
FLEET_ELASTIC_WARM_S = 2.0
FLEET_ELASTIC_PHASE_S = 4.0
FLEET_ELASTIC_SETTLE_S = 5.0         # autoscale reaction window
FLEET_ELASTIC_BURST = 12
# Session params through the router: admission validates them, so
# only solver keys (no session-only knobs like segment_cycles).
FLEET_ELASTIC_SESSION_PARAMS = {
    "noise": 0.01, "stability": 0.001, "max_cycles": 500}


def _fleet_req(url, method="GET", payload=None, timeout=60):
    import urllib.error
    import urllib.request

    data = (json.dumps(payload).encode()
            if payload is not None else None)
    req = urllib.request.Request(
        url, data=data, method=method,
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def _elastic_session_problem(seed: int, n_batches: int):
    """A 10-variable integer-table path problem, its event batches,
    and the UNINTERRUPTED reference cost (warm engine, every batch
    applied in-process) — migration parity is judged by hard
    equality against this."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine.dynamic import build_dynamic_engine
    from pydcop_tpu.serving.sessions import apply_event_batch

    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"elastic{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(10)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(9):
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[k + 1]],
            rng.integers(0, 10, size=(3, 3)).astype(float), f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    batches = [
        [{"type": "change_factor",
          "name": f"c{int(rng.integers(9))}",
          "table": rng.integers(0, 10, size=(3, 3))
                      .astype(float).tolist()}]
        for _ in range(n_batches)
    ]
    params = dict(FLEET_ELASTIC_SESSION_PARAMS)
    ref = build_dynamic_engine(dcop, params)
    ref.run(max_cycles=params["max_cycles"])
    for batch in batches:
        _asg, _trace, err = apply_event_batch(ref, batch)
        if err is not None:
            raise RuntimeError(f"reference event failed: {err}")
        ref.run(max_cycles=params["max_cycles"])
    expected = ref.cost(
        ref.run(max_cycles=params["max_cycles"]).assignment)
    return dcop_yaml(dcop), batches, expected


def _elastic_patch_acked(url, sid, batch, deadline_s=120.0):
    """PATCH until the batch is acked: 409 (frozen mid-migration)
    and 503 (owner recovering) are the fleet saying retry."""
    deadline = time.perf_counter() + deadline_s
    while True:
        status, out = _fleet_req(
            url + f"/session/{sid}/events", "PATCH",
            {"events": batch, "wait": True, "timeout": 30.0})
        if status == 200:
            return out
        if status not in (409, 503) \
                or time.perf_counter() > deadline:
            raise RuntimeError(f"PATCH not acked: {status} {out}")
        time.sleep(0.2)


def _elastic_close_session(url, sid, deadline_s=120.0):
    deadline = time.perf_counter() + deadline_s
    while time.perf_counter() < deadline:
        status, st = _fleet_req(url + f"/session/{sid}")
        if status == 200:
            last = st.get("last")
            if last and last.get("converged"):
                break
        time.sleep(0.05)
    status, final = _fleet_req(url + f"/session/{sid}", "DELETE")
    if status != 200:
        raise RuntimeError(f"session close failed: {status} {final}")
    return final


def bench_fleet_elastic():
    """Elastic two-host fleet under churn.  Emits
    ``fleet_elastic_problems_per_sec`` (baseline closed-loop
    throughput — the sentinel value), migration cost parity
    (``fleet_elastic_migrate_cost_ok``), the 4x-step p99 ratio vs
    baseline with autoscaler reaction
    (``fleet_elastic_p99_ratio`` / ``fleet_elastic_scale_ups``), and
    the host-kill ledger (``fleet_elastic_lost`` — MUST be 0,
    ``fleet_elastic_session_events_ok``).  None-valued on failure —
    never kills the headline."""
    import shutil
    import tempfile
    import threading

    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving.router import FleetRouter, RouterFrontEnd

    pool = [dcop_yaml(build_dcop_small(FLEET_ELASTIC_N_VARS, seed))
            for seed in range(FLEET_ELASTIC_POOL)]
    params = {"max_cycles": FLEET_ELASTIC_MAX_CYCLES}
    worker_args = ["--batch_window", "0.005", "--max_batch", "16",
                   "--max_queue", "512",
                   "--cycles", str(FLEET_ELASTIC_MAX_CYCLES)]
    journal_dir = tempfile.mkdtemp(prefix="bench_elastic_jnl_")
    cache_dir = tempfile.mkdtemp(prefix="bench_elastic_aot_")
    router = FleetRouter(
        replicas=2, worker_args=worker_args,
        journal_dir=journal_dir, compile_cache_dir=cache_dir,
        hosts=2, min_replicas=2, max_replicas=4,
        autoscale_interval_s=1.0, heartbeat_s=0.2).start()
    front = RouterFrontEnd(router, port=0).start()
    url = front.url
    out = {}
    try:
        lock = threading.Lock()
        state = {"t_end": 0.0}

        def drive(n_clients, duration, record):
            completed = [0]
            latencies = []

            def client(idx):
                rng = np.random.default_rng(8100 + idx)
                while time.perf_counter() < state["t_end"]:
                    payload = pool[int(rng.integers(len(pool)))]
                    t0 = time.perf_counter()
                    status, body = _fleet_post(url, {
                        "dcop": payload, "wait": True,
                        "timeout": 60, "params": params})
                    t1 = time.perf_counter()
                    if record and status == 200 \
                            and body.get("status") == "FINISHED":
                        with lock:
                            latencies.append(t1 - t0)
                            completed[0] += 1

            state["t_end"] = time.perf_counter() + duration
            t_start = time.perf_counter()
            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(n_clients)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=duration + 120)
            elapsed = time.perf_counter() - t_start
            if not record or not completed[0] or elapsed <= 0:
                return None
            lat_ms = np.asarray(latencies) * 1e3
            return {
                "pps": round(completed[0] / elapsed, 2),
                "p50": round(float(np.percentile(lat_ms, 50)), 2),
                "p99": round(float(np.percentile(lat_ms, 99)), 2),
                "requests": completed[0],
            }

        # Phase A — baseline throughput/latency on the 2-host floor.
        drive(FLEET_ELASTIC_BASE_CLIENTS, FLEET_ELASTIC_WARM_S,
              record=False)
        base = drive(FLEET_ELASTIC_BASE_CLIENTS,
                     FLEET_ELASTIC_PHASE_S, record=True)
        if base is None:
            return {"fleet_elastic_problems_per_sec": None,
                    "fleet_elastic_error":
                        "baseline produced no completions"}
        out["fleet_elastic_problems_per_sec"] = base["pps"]
        out["fleet_elastic_p50_ms"] = base["p50"]
        out["fleet_elastic_p99_ms"] = base["p99"]
        out["fleet_elastic_requests"] = base["requests"]

        # Phase B — live migration with hard cost parity: the
        # migrated session must finish at EXACTLY the uninterrupted
        # reference cost on integer tables.
        yaml_a, batches_a, expected_a = \
            _elastic_session_problem(4201, 4)
        status, body = _fleet_req(
            url + "/session", "POST",
            {"dcop": yaml_a,
             "params": FLEET_ELASTIC_SESSION_PARAMS})
        if status != 201:
            raise RuntimeError(
                f"session open failed: {status} {body}")
        sid = body["session_id"]
        for batch in batches_a[:2]:
            _elastic_patch_acked(url, sid, batch)
        src = router.pinned(sid, router._session_pins)
        status, body = _fleet_req(url + "/admin/migrate", "POST",
                                  {"session_id": sid})
        dst = router.pinned(sid, router._session_pins)
        moved = (status == 200 and src is not None
                 and dst is not None and dst.index != src.index)
        for batch in batches_a[2:]:
            _elastic_patch_acked(url, sid, batch)
        final = _elastic_close_session(url, sid)
        out["fleet_elastic_migrate_cost_ok"] = bool(
            moved and final.get("cost") == expected_a)
        out["fleet_elastic_migrations"] = router.migrations

        # Phase C — 4x traffic step against the autoscaler.  The SLO
        # is pegged to the measured baseline (armed only now, so the
        # baseline itself ran on the fixed floor), the settle window
        # gives the control loop time to spawn, and the recorded
        # window judges the post-reaction p99.
        router.slo_p99_ms = max(1.5 * base["p99"], 25.0)
        out["fleet_elastic_slo_p99_ms"] = round(
            router.slo_p99_ms, 2)
        drive(FLEET_ELASTIC_STEP_CLIENTS, FLEET_ELASTIC_SETTLE_S,
              record=False)
        step = drive(FLEET_ELASTIC_STEP_CLIENTS,
                     FLEET_ELASTIC_PHASE_S, record=True)
        out["fleet_elastic_scale_ups"] = router.scale_ups
        out["fleet_elastic_replicas_after_step"] = router.up_count()
        if step is not None:
            out["fleet_elastic_step_p99_ms"] = step["p99"]
            ratio = (step["p99"] / base["p99"]
                     if base["p99"] > 0 else None)
            out["fleet_elastic_p99_ratio"] = (
                round(ratio, 3) if ratio is not None else None)
            out["fleet_elastic_p99_within_2x"] = bool(
                ratio is not None and ratio <= 2.0)
        # Freeze the fleet size for the kill phase: a concurrent
        # scale-down would blur whose journal replays what.
        router.slo_p99_ms = None

        # Phase D — host kill mid-burst.  Every 202 and every acked
        # event batch is a durability promise; killing the host that
        # owns the warm session (both its replica processes) must
        # lose none of them.
        yaml_b, batches_b, expected_b = \
            _elastic_session_problem(4301, 3)
        status, body = _fleet_req(
            url + "/session", "POST",
            {"dcop": yaml_b,
             "params": FLEET_ELASTIC_SESSION_PARAMS})
        if status != 201:
            raise RuntimeError(
                f"session open failed: {status} {body}")
        sid_b = body["session_id"]
        for batch in batches_b[:2]:
            _elastic_patch_acked(url, sid_b, batch)
        pinned = router.pinned(sid_b, router._session_pins)
        victim_host = pinned.host_id if pinned else "host0"
        acked = []
        for k in range(FLEET_ELASTIC_BURST):
            status, body = _fleet_post(url, {
                "dcop": pool[k % len(pool)], "params": params})
            if status == 202:
                acked.append(body["id"])
        t_kill = time.perf_counter()
        victims = [r for r in router.replicas
                   if r.host_id == victim_host and r.managed
                   and not r.retired and r.proc is not None
                   and r.proc.poll() is None]
        for r in victims:
            r.proc.kill()
        out["fleet_elastic_burst_acked"] = len(acked)
        out["fleet_elastic_host_killed"] = len(victims)
        remaining = set(acked)
        deadline = time.perf_counter() + 180.0
        while remaining and time.perf_counter() < deadline:
            for rid in list(remaining):
                status, body = _fleet_req(url + f"/result/{rid}")
                if status == 200 \
                        and body.get("status") == "FINISHED":
                    remaining.discard(rid)
            if remaining:
                time.sleep(0.25)
        out["fleet_elastic_lost"] = len(remaining)
        out["fleet_elastic_kill_recover_s"] = round(
            time.perf_counter() - t_kill, 2)
        # The acked events survived iff the next batch lands as seq 3
        # and the session still converges to the reference cost.
        ack3 = _elastic_patch_acked(url, sid_b, batches_b[2],
                                    deadline_s=180.0)
        final_b = _elastic_close_session(url, sid_b,
                                         deadline_s=180.0)
        out["fleet_elastic_session_events_ok"] = bool(
            ack3.get("seq") == 3
            and final_b.get("cost") == expected_b)
        out["fleet_elastic_deaths"] = router.deaths
        return out
    finally:
        front.stop()
        router.stop(drain=False)
        shutil.rmtree(journal_dir, ignore_errors=True)
        shutil.rmtree(cache_dir, ignore_errors=True)


# Cold-start leg (ISSUE 15): time-to-first-result of a FRESH serve
# worker on a known structure, empty disk cache vs warm.  The warm
# process must serve its first same-structure request with the jit
# compile collapsed to the cache-retrieval wall (``compile`` ≈ 0 in
# its PR-14 request ledger) — the fleet's replicas and restarts live
# or die on this.  Workers run with PYDCOP_XLA_PROFILE=0 so the
# profiler's untimed throwaway AOT compile cannot seed the disk cache
# mid-dispatch and blur the A/B.  The instance is deliberately the
# COMPILE-HEAVIEST serving shape we have — domain 8, mixed
# binary/ternary buckets, branch-and-bound pruning enabled (the
# pruned program roughly triples XLA's work on this family) — because
# the leg exists to measure compile avoidance, not solve speed.
COLD_START_N_VARS = 48
COLD_START_TERNARY = 8
COLD_START_DOMAIN = 8
COLD_START_MAX_CYCLES = 200


def build_cold_start_dcop(seed: int = 3):
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    d = COLD_START_DOMAIN
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("coldstart", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(COLD_START_N_VARS)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(COLD_START_N_VARS):
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % COLD_START_N_VARS]],
            rng.integers(0, 10, size=(d, d)).astype(float), f"c{k}"))
    for k in range(COLD_START_TERNARY):
        i, j, l = rng.choice(COLD_START_N_VARS, size=3,
                             replace=False)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[i], vs[j], vs[l]],
            rng.integers(0, 10, size=(d, d, d)).astype(float),
            f"t{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def bench_serve_cold_start():
    """Two fresh serve subprocesses against one cache directory:
    round 1 compiles (and populates the cache), round 2 must
    deserialize.  Emits ``serve_cold_start_warm_s`` (warm
    time-to-first-result — the ``serve_cold_start`` sentinel family,
    LOWER is better), the cold baseline, and both request ledgers'
    ``compile`` components.  None-valued on failure."""
    import shutil
    import signal as signal_mod
    import subprocess as sp
    import tempfile
    import urllib.request

    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    cache_dir = tempfile.mkdtemp(prefix="bench_aot_")
    run_dir = tempfile.mkdtemp(prefix="bench_cold_")
    payload = dcop_yaml(build_cold_start_dcop())
    request_params = {"max_cycles": COLD_START_MAX_CYCLES,
                      "prune": 1}

    def one_round(tag):
        port_file = os.path.join(run_dir, f"{tag}.port")
        env = dict(os.environ, JAX_PLATFORMS=os.environ.get(
            "JAX_PLATFORMS", "cpu"), PYDCOP_XLA_PROFILE="0")
        log = open(os.path.join(run_dir, f"{tag}.log"), "wb")
        proc = sp.Popen(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "serve",
             "--port", "0", "--port_file", port_file,
             "--compile_cache_dir", cache_dir,
             "--batch_window", "0.005",
             "--cycles", str(COLD_START_MAX_CYCLES)],
            env=env, stdout=log, stderr=log)
        log.close()
        try:
            deadline = time.monotonic() + 120
            port = None
            while time.monotonic() < deadline:
                if proc.poll() is not None:
                    raise RuntimeError(
                        f"cold-start worker died (exit "
                        f"{proc.returncode})")
                try:
                    with open(port_file, encoding="utf-8") as f:
                        port = int(f.read().strip())
                    break
                except (OSError, ValueError):
                    time.sleep(0.05)
            if port is None:
                raise RuntimeError("cold-start worker never listened")
            url = f"http://127.0.0.1:{port}"
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(url + "/healthz",
                                                timeout=2):
                        break
                except OSError:
                    time.sleep(0.05)
            t0 = time.perf_counter()
            status, body = _fleet_post(url, {
                "dcop": payload, "wait": True, "timeout": 120,
                "params": request_params}, timeout=150)
            ttfr = time.perf_counter() - t0
            if status != 200 or body.get("status") != "FINISHED":
                raise RuntimeError(
                    f"cold-start request failed ({status})")
            ledger = body.get("ledger") or {}
            return {
                "ttfr_s": round(ttfr, 4),
                "compile_s": round(
                    float(ledger.get("compile_s", 0.0)), 4),
                "execute_s": round(
                    float(ledger.get("execute_s", 0.0)), 4),
            }
        finally:
            if proc.poll() is None:
                proc.send_signal(signal_mod.SIGTERM)
                try:
                    proc.wait(timeout=60)
                except sp.TimeoutExpired:
                    proc.kill()

    try:
        cold = one_round("cold")
        warm = one_round("warm")
        return {
            "serve_cold_start_warm_s": warm["ttfr_s"],
            "serve_cold_start_cold_s": cold["ttfr_s"],
            "serve_cold_start_warm_compile_s": warm["compile_s"],
            "serve_cold_start_cold_compile_s": cold["compile_s"],
            "serve_cold_start_speedup": round(
                cold["ttfr_s"] / warm["ttfr_s"], 3)
                if warm["ttfr_s"] else None,
        }
    finally:
        shutil.rmtree(cache_dir, ignore_errors=True)
        shutil.rmtree(run_dir, ignore_errors=True)


DPOP_EXACT_N = 300
DPOP_EXACT_D = 8
DPOP_EXACT_REPS = 5


def build_dpop_exact_dcop(n: int = DPOP_EXACT_N,
                          d: int = DPOP_EXACT_D, seed: int = 1709):
    """Width-bounded exact-inference instance: a random spanning tree
    (induced width stays small) over a mid-sized domain, seeded so
    every round solves the same problem."""
    import numpy as np

    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("dpop_exact", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(1, n):
        p = int(rng.integers(max(0, i - 3), i))
        dcop.add_constraint(NAryMatrixRelation(
            [vs[p], vs[i]], rng.random((d, d)), f"c{i}"))
    # Short-range cross edges push the induced width past 1 so the
    # UTIL sweep carries real separators, while the bounded bandwidth
    # keeps the hypercubes far under the element cap.
    for k in range(5, n, 5):
        lo = max(0, k - 4)
        q = int(rng.integers(lo, k))
        dcop.add_constraint(NAryMatrixRelation(
            [vs[q], vs[k]], rng.random((d, d)), f"x{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def bench_dpop_exact():
    """Exact-inference leg: warmed, best-of-N wall time for a full
    DPOP sweep (UTIL up + VALUE down, CEC on) on the width-bounded
    seeded instance — sentinel family ``dpop_exact`` (ms, LOWER is
    better).  The warm-up run eats every signature-bucket compile, so
    the measured reps are the serving-steady-state cost of an exact
    answer."""
    from pydcop_tpu.computations_graph import pseudotree as pt
    from pydcop_tpu.engine.dpop import DpopEngine
    from pydcop_tpu.ops.dpop import tree_stats

    dcop = build_dpop_exact_dcop()
    tree = pt.build_computation_graph(dcop)
    stats = tree_stats(tree)
    engine = DpopEngine(tree, mode="min", cec=True)
    warm = engine.run()   # compiles + caches CEC survivors
    best = None
    for _ in range(DPOP_EXACT_REPS):
        t0 = time.perf_counter()
        res = engine.run()
        elapsed = time.perf_counter() - t0
        best = elapsed if best is None else min(best, elapsed)
    cost, violations = dcop.solution_cost(res.assignment)
    if violations:
        raise RuntimeError("exact sweep produced violations")
    return {
        "dpop_exact_ms": round(best * 1000.0, 3),
        "dpop_exact_cold_ms": round(warm.time_s * 1000.0, 3),
        "dpop_exact_induced_width": stats["induced_width"],
        "dpop_exact_levels": stats["levels"],
        "dpop_exact_cec_pruned": res.metrics.get("cec_pruned"),
        "dpop_exact_cost": round(float(cost), 4),
    }


def run_bench():
    import jax

    from pydcop_tpu.observability.profiler import profiler
    from pydcop_tpu.utils.cleanenv import diag_events
    from pydcop_tpu.engine.roofline import roofline_report

    # XLA cost attribution for the roofline: the engine's cold
    # dispatch captures measured flops/bytes per compiled program
    # (PYDCOP_XLA_PROFILE=0 vetoes — the capture adds one AOT compile
    # per program, which a wedge-prone tunnel may not tolerate).
    profiler.enabled = True
    dev = jax.devices()[0]
    platform = dev.platform
    device_kind = getattr(dev, "device_kind", None)
    parity_device_cost, parity_thread_cost = exact_parity()

    dcop = build_dcop(N_VARS)
    if platform != "tpu":
        _try_revive_tpu()   # re-probe right before the headline leg
    record_leg_backend("headline")
    device_cps, res, engine = bench_device(dcop, DEVICE_CYCLES)
    thread_cps, thread_cycles, thread_cost, _asg = bench_thread(
        dcop, THREAD_TIMEOUT_S)
    if thread_cycles <= 0 or thread_cps <= 0:
        # Degenerate baseline (no full BSP cycle within the timeout):
        # still emit the JSON line rather than dying on a divide.
        out = {
            "metric": "maxsum_cycles_per_sec_10kvar_graphcoloring",
            "value": round(device_cps, 2),
            "unit": "cycles/s",
            "vs_baseline": None,
            "backend": platform,
            "host_cpus": os.cpu_count(),
            "baseline_cycles_completed": thread_cycles,
            "note": "threaded baseline completed no full cycle in "
                    f"{THREAD_TIMEOUT_S}s",
        }
        out.update(_artifact_keys(platform, out))
        out["probe_diagnostics"] = diag_events()
        out["leg_backends"] = dict(_LEG_BACKENDS)
        print(json.dumps(out))
        return

    # Cost-vs-cycle trace on the device: the quality check is one-sided
    # (fail only if the device is WORSE than the thread runtime at the
    # matched cycle count, beyond skew tolerance), and the trace gives
    # the north-star number — wall-clock to reach the thread runtime's
    # final cost.
    trace_res = engine.run_trace(max_cycles=thread_cycles)
    trace = trace_res.metrics["cost_trace"]
    quality_cost = float(trace[thread_cycles - 1])
    n_constraints = len(dcop.constraints)
    if quality_cost - thread_cost > QUALITY_TOL_FRAC * n_constraints:
        print(
            f"bench: QUALITY CHECK FAILED device@{thread_cycles}="
            f"{quality_cost} thread={thread_cost} "
            f"tol={QUALITY_TOL_FRAC * n_constraints}", file=sys.stderr,
        )
        sys.exit(1)
    # First cycle at which the device matches the thread's final cost.
    below = np.nonzero(trace <= thread_cost)[0]
    cycles_to_cost = int(below[0]) + 1 if below.size else None
    time_to_cost = (
        cycles_to_cost / device_cps if cycles_to_cost else None
    )
    thread_elapsed = thread_cycles / thread_cps
    speedup_equal_cost = (
        round(thread_elapsed / time_to_cost, 1)
        if time_to_cost else None
    )

    # Marginal (tunnel-overhead-free) per-cycle rate: the end-to-end
    # device_cps above includes the tunnel's fixed ~130 ms sync
    # latency per engine call, which at 200 cycles dominates a
    # VMEM-resident 10k-var superstep (~1 us) completely.  Differencing
    # two cycle counts cancels the fixed cost; the delta is chosen so
    # real compute (~120 ms on-chip) dominates observed round-trip
    # jitter (tens of ms).  This is the rate utilization claims are
    # based on.  TPU only: the CPU fallback has no tunnel (its
    # dispatch is synchronous and ~us-cheap, so end-to-end IS
    # marginal there) and 201k-cycle CPU runs would add ~an hour.
    marginal_cps = None
    fixed_latency = None
    if platform == "tpu":
        from pydcop_tpu.engine.timing import warmed_marginal
        from pydcop_tpu.utils.cleanenv import record_diag

        # Adaptive ladder: a fixed long program is dangerous — the
        # first attempt used 201k cycles sized from a prior "0.6 us/
        # cycle" estimate that was itself a block_until_ready artifact,
        # and the real program ran long enough that the tunnel KILLED
        # the TPU worker (observed twice, ~3 min in: "TPU worker
        # process crashed or restarted").  Start with a short delta and
        # escalate 10x only while the measured slope projects the next
        # rung comfortably under the watchdog.  A dead worker must not
        # kill the bench either way: end-to-end numbers still stand.
        try:
            lo, hi = 200, 2_200
            while True:
                sec_per_cycle, fixed_latency, _ = warmed_marginal(
                    lambda c: engine._fn(c, False), lo, hi,
                    args=(engine.graph,), reps=3)
                delta_s = sec_per_cycle * (hi - lo)
                next_hi = hi * 10
                if (delta_s >= 0.5 or next_hi > 3_000_000
                        or sec_per_cycle * next_hi > 45):
                    break
                hi = next_hi
            marginal_cps = (
                1.0 / sec_per_cycle if sec_per_cycle > 0 else None)
            record_diag("marginal_leg", hi_cycles=hi,
                        sec_per_cycle=sec_per_cycle)
        except Exception as exc:   # noqa: BLE001 — tunnel/worker death
            record_diag("marginal_leg_failed",
                        error=f"{type(exc).__name__}: {exc}"[:200])
            print(f"bench: marginal leg failed ({exc}); continuing "
                  "with end-to-end timing only", file=sys.stderr)
            fixed_latency = None

    # Measured (XLA-reported) per-cycle cost when the backend offered
    # one: the headline program is one while-loop whose body is a
    # superstep, and XLA's cost analysis counts a loop body ONCE
    # (trip-count-independent — verified in the perf-intel battery),
    # so the reported flops/bytes ARE per-cycle numbers.
    # bench_device's engine compiles exactly one program, so take the
    # sole entry rather than reverse-engineering the jit cache key
    # (whose format belongs to the engine).
    xla_entries = list((res.metrics.get("xla_cost") or {}).values())
    xla_entry = xla_entries[0] if len(xla_entries) == 1 else {}
    measured = None
    if xla_entry.get("available"):
        measured = {
            "flops_per_cycle": xla_entry.get("flops"),
            "bytes_per_cycle": xla_entry.get("bytes_accessed"),
        }
    roofline = roofline_report(
        engine.graph, marginal_cps or device_cps, platform, device_kind,
        measured=measured)
    roofline["roofline_rate_basis"] = (
        "marginal" if marginal_cps else "end_to_end")
    if xla_entry.get("peak_bytes"):
        roofline["xla_peak_bytes"] = xla_entry["peak_bytes"]
    # HBM-bound scale leg: TPU only — on the CPU-fallback path it
    # would add minutes and say nothing about HBM streaming.
    if platform == "tpu":
        scale_cps, scale_graph, scale_info = bench_scale(detail=True)
        scale_keys = {
            "scale_n_vars": SCALE_N_VARS,
            "scale_fixed_latency_s": round(
                scale_info["fixed_overhead_s"], 3),
        }
        if scale_cps > 0:
            scale_roofline = roofline_report(
                scale_graph, scale_cps, platform, device_kind)
            scale_keys.update({
                "scale_cycles_per_s": round(scale_cps, 2),
                "scale_ms_per_cycle": round(
                    scale_info["sec_per_cycle"] * 1e3, 4),
                "scale_hbm_util": scale_roofline["hbm_util"],
                "scale_achieved_gbps": scale_roofline["achieved_gbps"],
                "scale_vmem_resident": scale_roofline["vmem_resident"],
                "scale_hbm_util_exceeds_peak": scale_roofline[
                    "hbm_util_exceeds_peak"],
            })
        else:
            # Jitter-floored slope: no rate claim (matches the
            # headline leg's None convention) rather than a 0.0 that
            # reads as a dead chip.
            scale_keys.update({
                "scale_cycles_per_s": None,
                "scale_timing_below_jitter": True,
            })
        del scale_graph
    else:
        # CPU smoke of the same scale-leg code path (shrunk from 1M
        # vars so the fallback adds seconds, not minutes): the array
        # builder, aggregation layout and marginal-timing ladder all
        # execute before a TPU ever runs them at full scale.  No HBM
        # claim is made — the keys are namespaced "smoke".
        try:
            smoke_cps, _smoke_graph, smoke_info = bench_scale(
                n_vars=SCALE_SMOKE_N_VARS, cycles=SCALE_SMOKE_CYCLES,
                detail=True)
            scale_keys = {
                "scale_smoke_n_vars": SCALE_SMOKE_N_VARS,
                "scale_smoke_cycles_per_s": round(smoke_cps, 2),
                "scale_smoke_ms_per_cycle": round(
                    smoke_info["sec_per_cycle"] * 1e3, 4),
            }
            del _smoke_graph
        except Exception as exc:  # noqa: BLE001 — auxiliary leg
            print(f"bench: scale smoke failed ({exc}); continuing",
                  file=sys.stderr)
            scale_keys = {
                "scale_smoke_cycles_per_s": None,
                "scale_smoke_error":
                    f"{type(exc).__name__}: {exc}"[:200],
            }
    # Time-to-target-cost leg (both backends — the work-reduction
    # stack's headline; sentinel family "time_to_cost", lower is
    # better).  Never kills the headline line.
    try:
        record_leg_backend("time_to_cost")
        ttc_keys = bench_time_to_cost()
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: time-to-cost leg failed ({exc}); continuing",
              file=sys.stderr)
        ttc_keys = {"maxsum_time_to_cost_ms": None,
                    "ttc_error": f"{type(exc).__name__}: {exc}"[:200]}
    # Serving-throughput leg (both backends: the request plane exists
    # on the CPU fallback too, and its trajectory is what the
    # sentinel tracks per backend).  Never kills the headline line.
    try:
        record_leg_backend("serve")
        serve_keys = bench_serving()
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: serving leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys = {"serve_problems_per_sec": None,
                      "serve_error": f"{type(exc).__name__}: {exc}"[:200]}
    # Mixed-structure serving leg (ISSUE 11): zipf-diverse topologies,
    # envelope packing vs the no-envelope baseline on the same stream;
    # sentinel family "serve_mixed".  Never kills the headline line.
    try:
        record_leg_backend("serve_mixed")
        serve_keys.update(bench_serving_mixed())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: mixed serving leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys.update({
            "serve_mixed_problems_per_sec": None,
            "serve_mixed_error":
                f"{type(exc).__name__}: {exc}"[:200]})
    # Crash-recovery replay leg: journal scan + replay downtime —
    # the sentinel tracks it per backend like any other metric, so a
    # change that slows recovery is a tracked regression.
    try:
        record_leg_backend("serve_recovery")
        serve_keys.update(bench_recovery_replay())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: recovery-replay leg failed ({exc}); "
              "continuing", file=sys.stderr)
        serve_keys.update({
            "serve_recovery_replay_s": None,
            "serve_recovery_error":
                f"{type(exc).__name__}: {exc}"[:200],
        })
    # Fleet-serving leg (ISSUE 15): aggregate problems/sec through
    # the replicated router at replicas=1/2/4 on the same seeded
    # stream + the affinity-vs-round-robin A/B — sentinel family
    # "serving_fleet" (the r2 value).  Never kills the headline.
    try:
        record_leg_backend("serving_fleet")
        serve_keys.update(bench_serving_fleet())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: fleet leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys.update({
            "fleet_problems_per_sec_r2": None,
            "fleet_error": f"{type(exc).__name__}: {exc}"[:200],
        })
    # Partition-tolerant fleet leg (ISSUE 19): the same closed-loop
    # stream under a seeded 1%-drop/20ms-delay plan on the solve
    # links, zero-acked-loss + deadline-budget ledgers — sentinel
    # family "fleet_faulted" (its own family, never compared against
    # the clean fleet numbers).  Never kills the headline.
    try:
        record_leg_backend("fleet_faulted")
        serve_keys.update(bench_serving_fleet_faulted())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: faulted-fleet leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys.update({
            "fleet_faulted_problems_per_sec": None,
            "fleet_faulted_error":
                f"{type(exc).__name__}: {exc}"[:200],
        })
    # Elastic-fleet leg (ISSUE 16): two-host fleet under churn —
    # baseline throughput, live-migration cost parity, a 4x traffic
    # step against the SLO autoscaler, and a host kill mid-burst
    # with a zero-acked-loss ledger — sentinel family
    # "fleet_elastic".  Never kills the headline.
    try:
        record_leg_backend("fleet_elastic")
        serve_keys.update(bench_fleet_elastic())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: elastic-fleet leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys.update({
            "fleet_elastic_problems_per_sec": None,
            "fleet_elastic_error":
                f"{type(exc).__name__}: {exc}"[:200],
        })
    # Cold-start leg (ISSUE 15): fresh-worker time-to-first-result,
    # warm disk compile cache vs empty — sentinel family
    # "serve_cold_start" (warm TTFR, lower is better).
    try:
        record_leg_backend("serve_cold_start")
        serve_keys.update(bench_serve_cold_start())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: cold-start leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys.update({
            "serve_cold_start_warm_s": None,
            "serve_cold_start_error":
                f"{type(exc).__name__}: {exc}"[:200],
        })
    # Stateful-session leg (ISSUE 13): warm time-to-recovered-cost
    # after scenario events vs a cold re-solve on the same compiled
    # program, plus sustained events/sec — sentinel families
    # "session_recovery" (lower is better) and "session_events".
    try:
        record_leg_backend("sessions")
        serve_keys.update(bench_sessions())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: session leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys.update({
            "session_time_to_recovered_cost_ms": None,
            "session_events_per_sec": None,
            "session_error": f"{type(exc).__name__}: {exc}"[:200],
        })
    # Exact-inference leg (ISSUE 17): warmed best-of-N full DPOP
    # sweep on the width-bounded seeded instance — sentinel family
    # "dpop_exact" (lower is better).
    try:
        record_leg_backend("dpop_exact")
        serve_keys.update(bench_dpop_exact())
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: dpop exact leg failed ({exc}); continuing",
              file=sys.stderr)
        serve_keys.update({
            "dpop_exact_ms": None,
            "dpop_exact_error": f"{type(exc).__name__}: {exc}"[:200],
        })
    # Sharded-superstep leg: real mesh on TPU (when the tunnel gave
    # us more than one chip), forced-host-device child on CPU.
    try:
        record_leg_backend("sharded")
        if platform == "tpu" and len(jax.devices()) >= 2:
            shard_keys = bench_sharded(
                min(SHARDED_SHARDS, len(jax.devices())))
            shard_keys["sharded_backend"] = "tpu"
        else:
            shard_keys = _bench_sharded_forced()
        # The leg record must name the backend the leg's values
        # actually came from: on a single-chip TPU round the leg runs
        # in a FORCED-CPU child while this process's default backend
        # says tpu — the sentinel prefers the leg record over the
        # sharded_backend fallback, so a stale parent-process label
        # would pad the tpu sharded baseline with forced-host values.
        if shard_keys.get("sharded_backend"):
            _LEG_BACKENDS["sharded"]["backend"] = \
                shard_keys["sharded_backend"]
    except Exception as exc:  # noqa: BLE001 — auxiliary leg
        print(f"bench: sharded leg failed ({exc}); continuing",
              file=sys.stderr)
        shard_keys = {
            "maxsum_cycles_per_sec_sharded": None,
            "sharded_error": f"{type(exc).__name__}: {exc}"[:200],
        }
    out = {
        "metric": "maxsum_cycles_per_sec_10kvar_graphcoloring",
        "value": round(device_cps, 2),
        "unit": "cycles/s",
        "vs_baseline": round(device_cps / thread_cps, 1),
        "backend": platform,
        # Host hardware class: CPU-fallback rates scale with the core
        # count of the bench box, so the sentinel keys CPU baselines on
        # it (a 1-core round must not be judged against an 8-core
        # history — same refusal the backend split already applies).
        "host_cpus": os.cpu_count(),
        "device_kind": device_kind,
        "baseline": "own threaded agent runtime "
                    f"({THREAD_AGENTS} agent threads, same problem)",
        "baseline_cycles_per_s": round(thread_cps, 3),
        "baseline_cycles_completed": thread_cycles,
        "parity_cost_device": round(parity_device_cost, 4),
        "parity_cost_thread": round(parity_thread_cost, 4),
        "quality_cost_device_matched_cycles": round(quality_cost, 2),
        "quality_cost_thread": round(thread_cost, 2),
        "device_cycles_to_thread_cost": cycles_to_cost,
        "device_seconds_to_thread_cost": (
            round(time_to_cost, 4) if time_to_cost else None
        ),
        "speedup_at_equal_cost": speedup_equal_cost,
        "marginal_cycles_per_s": (
            round(marginal_cps, 1) if marginal_cps else None
        ),
        "tunnel_fixed_latency_s": (
            round(fixed_latency, 4) if fixed_latency is not None
            else None
        ),
        **roofline,
        **scale_keys,
        **ttc_keys,
        **serve_keys,
        **shard_keys,
    }
    out.update(_artifact_keys(platform, out))
    out["probe_diagnostics"] = diag_events()
    out["leg_backends"] = dict(_LEG_BACKENDS)
    print(json.dumps(out))


def main():
    if os.environ.get("PYDCOP_BENCH_SHARDED_CHILD"):
        # Forced-host-device child of the sharded leg: one JSON line
        # with the sharded keys, nothing else on stdout.
        print(json.dumps(bench_sharded()))
        return
    if (os.environ.get("PYDCOP_BENCH_CHILD")
            or os.environ.get("PYDCOP_BENCH_NO_PROBE")):
        run_bench()
        return
    _supervise()


if __name__ == "__main__":
    main()

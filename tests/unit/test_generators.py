"""Generator tests: structure, determinism, solvability."""

import numpy as np
import pytest

from pydcop_tpu.dcop.yamldcop import dcop_yaml, load_dcop
from pydcop_tpu.generators import graphs
from pydcop_tpu.generators.agents_gen import generate_agents
from pydcop_tpu.generators.graphcoloring import generate_graph_coloring
from pydcop_tpu.generators.iot import generate_iot
from pydcop_tpu.generators.ising import generate_ising
from pydcop_tpu.generators.meetingscheduling import generate_meetings
from pydcop_tpu.generators.scenario_gen import generate_scenario
from pydcop_tpu.generators.secp import generate_secp
from pydcop_tpu.generators.smallworld import generate_small_world


class TestGraphs:
    def test_random_connected(self):
        edges = graphs.random_graph(30, 0.05, seed=0)
        # connectivity check by BFS
        adj = {i: set() for i in range(30)}
        for a, b in edges:
            adj[a].add(b)
            adj[b].add(a)
        seen, stack = {0}, [0]
        while stack:
            for nb in adj[stack.pop()]:
                if nb not in seen:
                    seen.add(nb)
                    stack.append(nb)
        assert len(seen) == 30

    def test_random_deterministic(self):
        assert graphs.random_graph(20, 0.2, seed=5) == \
            graphs.random_graph(20, 0.2, seed=5)

    def test_grid_requires_square(self):
        with pytest.raises(ValueError):
            graphs.grid_graph(7)
        edges = graphs.grid_graph(9)
        assert len(edges) == 12  # 3x3 grid: 2*3*2

    def test_grid_2d_toroidal_degree(self):
        edges = graphs.grid_2d_graph(4, 4, periodic=True)
        # toroidal grid: every node has degree 4 -> 2*n edges
        assert len(edges) == 32

    def test_scalefree(self):
        edges = graphs.scalefree_graph(30, 2, seed=0)
        assert len(edges) >= 28 * 2 * 0.9
        degs = {}
        for a, b in edges:
            degs[a] = degs.get(a, 0) + 1
            degs[b] = degs.get(b, 0) + 1
        assert max(degs.values()) > 4  # hubs exist

    def test_small_world(self):
        edges = graphs.small_world_graph(20, k=4, seed=0)
        # ~n*k/2, minus rewiring collisions with lattice edges
        assert 35 <= len(edges) <= 40


class TestGraphColoring:
    def test_basic(self):
        dcop = generate_graph_coloring(
            10, 3, "random", p_edge=0.3, seed=1)
        assert len(dcop.variables) == 10
        assert len(dcop.agents) == 10
        assert all(c.arity == 2 for c in dcop.constraints.values())

    def test_deterministic(self):
        d1 = generate_graph_coloring(10, 3, "random", p_edge=0.3, seed=7)
        d2 = generate_graph_coloring(10, 3, "random", p_edge=0.3, seed=7)
        assert dcop_yaml(d1) == dcop_yaml(d2)

    def test_soft_random_costs(self):
        dcop = generate_graph_coloring(
            10, 3, "random", soft=True, p_edge=0.3, seed=1)
        c = next(iter(dcop.constraints.values()))
        assert c.to_array().max() <= 9

    def test_intentional_hard(self):
        dcop = generate_graph_coloring(
            6, 3, "random", intentional=True, p_edge=0.3, seed=1)
        c = next(iter(dcop.constraints.values()))
        v1, v2 = c.dimensions
        assert c(**{v1.name: "R", v2.name: "R"}) == 1000
        assert c(**{v1.name: "R", v2.name: "G"}) == 0

    def test_yaml_roundtrip(self):
        dcop = generate_graph_coloring(
            8, 3, "random", p_edge=0.3, seed=2)
        again = load_dcop(dcop_yaml(dcop))
        assert set(again.variables) == set(dcop.variables)
        asst = {v: "R" for v in dcop.variables}
        assert again.solution_cost(asst) == dcop.solution_cost(asst)


class TestIsing:
    def test_structure(self):
        dcop, var_map, fg_map = generate_ising(
            4, 4, seed=0, var_dist=True, fg_dist=True)
        assert len(dcop.variables) == 16
        # 16 unary + 32 binary (toroidal degree 4)
        arities = [c.arity for c in dcop.constraints.values()]
        assert arities.count(1) == 16
        assert arities.count(2) == 32
        assert len(var_map) == 16
        # fg mapping: every computation appears exactly once and every
        # constraint/variable is covered.
        comps = [c for lst in fg_map.values() for c in lst]
        assert len(comps) == len(set(comps))
        assert set(comps) == set(dcop.constraints) | set(dcop.variables)

    def test_cost_symmetry(self):
        dcop, _, _ = generate_ising(3, 3, seed=1)
        for c in dcop.constraints.values():
            if c.arity == 2:
                arr = c.to_array()
                assert arr[0, 0] == arr[1, 1] == -arr[0, 1]


class TestOtherGenerators:
    def test_meetings(self):
        dcop = generate_meetings(4, 3, 3, 2, seed=0)
        assert dcop.objective == "max"
        assert dcop.variables
        # every variable's domain includes the unscheduled slot 0
        v = next(iter(dcop.variables.values()))
        assert 0 in v.domain

    def test_secp(self):
        dcop = generate_secp(6, 2, 3, seed=0)
        assert sum(1 for v in dcop.variables if v.startswith("l")) == 6
        assert sum(1 for v in dcop.variables if v.startswith("m")) == 2
        assert len(dcop.agents) == 6

    def test_iot_and_smallworld_solvable(self):
        from pydcop_tpu.api import solve

        for dcop in (generate_iot(12, seed=0),
                     generate_small_world(12, 4, seed=0)):
            res = solve(dcop, "dsa", max_cycles=20)
            assert res["violations"] == 0

    def test_agents_count_mode(self):
        agents = generate_agents(
            mode="count", count=5, capacity=50)
        assert len(agents) == 5
        assert agents[0].capacity == 50

    def test_agents_variables_mode(self):
        agents = generate_agents(
            mode="variables", variables=["v1", "v2"], capacity=10,
            hosting="name_mapping", hosting_default=100,
        )
        assert len(agents) == 2
        assert agents[0].hosting_cost("v1") == 0
        assert agents[0].hosting_cost("v2") == 100

    def test_scenario(self):
        s = generate_scenario(
            2, 1, 5, ["a1", "a2", "a3", "a4"], seed=0)
        removals = [
            a.args["agent"] for e in s.events if e.actions
            for a in e.actions
        ]
        assert len(removals) == len(set(removals)) == 2

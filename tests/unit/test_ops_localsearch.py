"""Direct unit tests for the shared local-search kernel primitives
(ops/localsearch.py) against hand-computed values on a known tiny
graph — these primitives back every local-search algorithm's device
path (dsa/mgm/mgm2/dba/gdba/mixeddsa), which are otherwise only
exercised end-to-end."""

import jax.numpy as jnp
import numpy as np

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.compile import compile_factor_graph
from pydcop_tpu.ops import localsearch as ls


def _graph():
    """Chain v0 - c01 - v1 - c12 - v2 over domain {0,1} with
    hand-picked tables; unary noise disabled."""
    d = Domain("d", "", [0, 1])
    vs = [Variable(f"v{i}", d) for i in range(3)]
    t01 = np.array([[0.0, 1.0], [2.0, 3.0]])
    t12 = np.array([[5.0, 0.0], [0.0, 5.0]])
    cs = [
        NAryMatrixRelation([vs[0], vs[1]], t01, "c01"),
        NAryMatrixRelation([vs[1], vs[2]], t12, "c12"),
    ]
    graph, meta = compile_factor_graph(vs, cs, noise_level=0.0)
    return graph, meta


def test_assignment_cost_matches_hand_sum():
    graph, _ = _graph()
    # values (v0, v1, v2) = (1, 0, 1): c01[1,0]=2, c12[0,1]=0.
    values = jnp.array([1, 0, 1, 0], dtype=jnp.int32)  # + sentinel
    assert float(ls.assignment_cost(graph, values)) == 2.0
    # (0, 1, 1): c01[0,1]=1, c12[1,1]=5.
    values = jnp.array([0, 1, 1, 0], dtype=jnp.int32)
    assert float(ls.assignment_cost(graph, values)) == 6.0


def test_factor_current_costs():
    graph, _ = _graph()
    values = jnp.array([1, 1, 0, 0], dtype=jnp.int32)
    (costs,) = ls.factor_current_costs(graph, values)
    # c01[1,1]=3 and c12[1,0]=0 (order = bucket row order).
    assert sorted(np.asarray(costs)[:2].tolist()) == [0.0, 3.0]


def test_candidate_costs_are_one_flip_costs():
    graph, _ = _graph()
    values = jnp.array([0, 0, 0, 0], dtype=jnp.int32)
    cand = np.asarray(ls.candidate_costs(graph, values))
    # v0: keeping 0 -> c01[0,0]=0; flipping to 1 -> c01[1,0]=2.
    assert cand[0, 0] == 0.0 and cand[0, 1] == 2.0
    # v1: value 0 -> c01[0,0] + c12[0,0] = 0+5; value 1 -> c01[0,1]
    # + c12[1,0] = 1+0.
    assert cand[1, 0] == 5.0 and cand[1, 1] == 1.0
    # v2: value 0 -> c12[0,0]=5; value 1 -> c12[0,1]=0.
    assert cand[2, 0] == 5.0 and cand[2, 1] == 0.0


def _mixed_arity_pair(seed=12):
    """(scatter graph, same graph with ell lists) over a random mixed
    binary + ternary problem."""
    from pydcop_tpu.engine.compile import build_aggregation_arrays

    rng = np.random.default_rng(seed)
    d = Domain("d", "", [0, 1, 2])
    vs = [Variable(f"v{i}", d) for i in range(40)]
    cs = []
    for k in range(50):
        i, j = rng.choice(40, size=2, replace=False)
        cs.append(NAryMatrixRelation(
            [vs[i], vs[j]], rng.random((3, 3)).round(3), f"b{k}"))
    for k in range(15):
        i, j, m = rng.choice(40, size=3, replace=False)
        cs.append(NAryMatrixRelation(
            [vs[i], vs[j], vs[m]], rng.random((3, 3, 3)).round(3),
            f"t{k}"))
    graph, _ = compile_factor_graph(vs, cs, noise_level=0.0)
    _, _, _, _, ell = build_aggregation_arrays(
        graph.buckets, graph.var_costs.shape[0], "ell")
    return graph, graph._replace(agg_ell=ell), rng


def test_candidate_costs_ell_matches_scatter():
    """The dense-gather (ell) branch must reproduce the scatter branch
    exactly up to float reassociation — including across MIXED-arity
    buckets, whose flattened edge orders must line up with the
    compile-time ell lists."""
    graph, g_ell, rng = _mixed_arity_pair()
    values = jnp.asarray(
        np.append(rng.integers(0, 3, size=40), 0).astype(np.int32))
    base = np.asarray(ls.candidate_costs(graph, values))
    got = np.asarray(ls.candidate_costs(g_ell, values))
    np.testing.assert_allclose(got, base, rtol=1e-5, atol=1e-4)


def test_neighbor_max_ell_matches_scatter():
    graph, g_ell, rng = _mixed_arity_pair(seed=21)
    per_var = jnp.asarray(
        rng.normal(size=graph.var_costs.shape[0]).astype(np.float32))
    base = np.asarray(ls.neighbor_max(graph, per_var))
    got = np.asarray(ls.neighbor_max(g_ell, per_var))
    np.testing.assert_array_equal(got[:-1], base[:-1])  # maxima: exact


def test_neighbor_min_rank_where_ell_matches_scatter():
    graph, g_ell, rng = _mixed_arity_pair(seed=22)
    n = graph.var_costs.shape[0]
    # Coarse-grained values so eligibility ties actually occur.
    per_var = jnp.asarray(
        rng.integers(0, 3, size=n).astype(np.float32))
    target = jnp.asarray(
        rng.integers(0, 3, size=n).astype(np.float32))
    ranks = jnp.asarray(rng.permutation(n).astype(np.float32))
    base = np.asarray(
        ls.neighbor_min_rank_where(graph, per_var, target, ranks))
    got = np.asarray(
        ls.neighbor_min_rank_where(g_ell, per_var, target, ranks))
    np.testing.assert_array_equal(got[:-1], base[:-1])


def test_candidate_costs_consistent_with_assignment_cost():
    """Flipping variable i to value k changes the total by exactly
    cand[i,k] - cand[i,current] (the local-search invariant)."""
    graph, _ = _graph()
    rng = np.random.default_rng(0)
    values = jnp.asarray(
        np.append(rng.integers(0, 2, size=3), 0).astype(np.int32))
    cand = np.asarray(ls.candidate_costs(graph, values))
    base = float(ls.assignment_cost(graph, values))
    for i in range(3):
        for k in range(2):
            flipped = np.asarray(values).copy()
            flipped[i] = k
            delta = float(
                ls.assignment_cost(graph, jnp.asarray(flipped))) - base
            local = cand[i, k] - cand[i, int(np.asarray(values)[i])]
            assert abs(delta - local) < 1e-6, (i, k)


def test_neighbor_max_excludes_self():
    graph, _ = _graph()
    per_var = jnp.array([10.0, 1.0, 7.0, 0.0])
    out = np.asarray(ls.neighbor_max(graph, per_var))
    assert out[0] == 1.0       # v0's only neighbor is v1
    assert out[1] == 10.0      # v1 sees v0 (10) and v2 (7)
    assert out[2] == 1.0       # v2's only neighbor is v1


def test_neighborhood_winners_unique_max():
    import jax

    graph, _ = _graph()
    values = jnp.zeros(4, dtype=jnp.int32)
    # Per-candidate costs crafted so improvements are v0=3, v1=1, v2=2.
    cand = jnp.array([[3.0, 0.0], [1.0, 0.0], [2.0, 0.0], [0.0, 0.0]])
    ranks = jnp.arange(4, dtype=jnp.float32)
    improve, proposed, nmax, wins = ls.neighborhood_winners(
        graph, cand, values, jax.random.PRNGKey(0), ranks)
    assert np.asarray(improve)[:3].tolist() == [3.0, 1.0, 2.0]
    # v0 (3) beats v1 (1); v2 (2) beats v1; v1 loses to both.
    wins = np.asarray(wins)
    assert bool(wins[0]) and not bool(wins[1]) and bool(wins[2])
    # The proposed move is the improving slot.
    assert np.asarray(proposed)[:3].tolist() == [1, 1, 1]


def test_neighborhood_winners_tie_breaks_by_rank():
    import jax

    graph, _ = _graph()
    values = jnp.zeros(4, dtype=jnp.int32)
    cand = jnp.array([[2.0, 0.0], [2.0, 0.0], [2.0, 0.0], [0.0, 0.0]])
    ranks = jnp.arange(4, dtype=jnp.float32)
    *_, wins = ls.neighborhood_winners(
        graph, cand, values, jax.random.PRNGKey(0), ranks)
    wins = np.asarray(wins)
    # All improvements tie at 2: lowest rank wins its neighborhood —
    # v0 beats v1; v1 loses to v0; v2 loses to v1 (rank 1 < 2).
    assert bool(wins[0]) and not bool(wins[1]) and not bool(wins[2])


class TestStaggeredSchedule:
    """adsa's graph-colored (staggered) schedule (VERDICT r4 next #6)."""

    def _coloring_dcop(self, n=30, seed=3):
        import numpy as np

        from pydcop_tpu.dcop.dcop import DCOP
        from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
        from pydcop_tpu.dcop.relations import NAryMatrixRelation

        rng = np.random.default_rng(seed)
        dom = Domain("c", "", [0, 1, 2])
        dcop = DCOP("stag", objective="min")
        vs = [Variable(f"v{i}", dom) for i in range(n)]
        for v in vs:
            dcop.add_variable(v)
        eq = np.eye(3)
        for k in range(int(n * 1.5)):
            i, j = rng.choice(n, size=2, replace=False)
            dcop.add_constraint(NAryMatrixRelation(
                [vs[i], vs[j]], eq, f"c{k}"))
        dcop.add_agents([AgentDef(f"a{i}") for i in range(4)])
        return dcop

    def test_greedy_classes_is_proper_coloring(self):
        import numpy as np

        from pydcop_tpu.engine.compile import compile_dcop
        from pydcop_tpu.ops.dsa import greedy_classes

        graph, _ = compile_dcop(self._coloring_dcop())
        classes, n_classes = greedy_classes(graph)
        assert n_classes >= 2
        assert classes.min() >= 0 and classes.max() == n_classes - 1
        # No two variables sharing a constraint share a class.
        sentinel = graph.var_costs.shape[0] - 1
        for bucket in graph.buckets:
            ids = np.asarray(bucket.var_ids)
            for p in range(ids.shape[1]):
                for q in range(p + 1, ids.shape[1]):
                    for a, b in zip(ids[:, p], ids[:, q]):
                        if a != b and a != sentinel and b != sentinel:
                            assert classes[a] != classes[b], (a, b)

    def test_staggered_never_flips_neighbors_together(self):
        """Step the kernel cycle by cycle and assert that within one
        superstep no two adjacent variables both changed value — the
        schedule's defining property."""
        import numpy as np

        from pydcop_tpu.engine.compile import compile_dcop
        from pydcop_tpu.ops import dsa as ops

        graph, _ = compile_dcop(self._coloring_dcop())
        classes, n_classes = ops.greedy_classes(graph)
        classes_j = jnp.asarray(classes)
        adj = set()
        sentinel = graph.var_costs.shape[0] - 1
        for bucket in graph.buckets:
            ids = np.asarray(bucket.var_ids)
            for p in range(ids.shape[1]):
                for q in range(p + 1, ids.shape[1]):
                    for a, b in zip(ids[:, p], ids[:, q]):
                        if a != b and a != sentinel and b != sentinel:
                            adj.add((int(a), int(b)))
        state = ops.init_state(graph, seed=5)
        prev = np.asarray(state.values)
        for _ in range(3 * n_classes):
            state = ops.dsa_step(
                state, graph, variant="B", probability=0.9,
                classes=classes_j, n_classes=n_classes)
            cur = np.asarray(state.values)
            changed = set(np.nonzero(cur != prev)[0].tolist())
            for a, b in adj:
                assert not (a in changed and b in changed), (a, b)
            prev = cur

    def test_staggered_solve_matches_budget_accounting(self):
        from pydcop_tpu.api import solve

        dcop = self._coloring_dcop()
        res = solve(dcop, "adsa", max_cycles=50, algo_params={
            "seed": 2, "stop_cycle": 20, "schedule": "staggered"})
        assert res["status"] == "FINISHED"
        # Reported cycles are full sweeps (budget-comparable units).
        assert res["cycles"] == 20

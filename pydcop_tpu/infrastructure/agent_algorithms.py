"""Agent-mode algorithm computations (thread/process/multi-machine).

These implement the same message semantics as the device kernels, but as
per-computation message handlers running on agent threads — the
reference's execution model (and its testing trick: drive computations
directly with a mocked message sender).

Reference parity:
- maxsum: pydcop/algorithms/maxsum.py:279-721 (BSP via the synchronous
  mixin; factor update :382, variable update :623, damping :679,
  SAME_COUNT send suppression :106/:366-377);
- dsa: pydcop/algorithms/dsa.py:214-431 (async with per-cycle value
  bookkeeping);
- mgm: pydcop/algorithms/mgm.py:213-609 (value/gain two-phase rounds
  with postponed-message queues).
"""

import random
from typing import Any, Dict, List, Optional, Tuple

from pydcop_tpu.dcop.objects import VariableNoisyCostFunc
from pydcop_tpu.dcop.relations import (
    assignment_cost,
    find_optimal,
    find_optimum,
    optimal_cost_value,
)
from pydcop_tpu.infrastructure.computations import (
    DcopComputation,
    Message,
    SynchronousComputationMixin,
    VariableComputation,
    message_type,
    register,
)

SAME_COUNT = 4


# --------------------------------------------------------------------- #
# Shared MaxSum math (dict form — the device form lives in ops/maxsum.py)


def factor_costs_for_var(factor, variable, recv_costs: Dict, mode: str
                         ) -> Dict:
    """Marginal costs a factor sends to one of its variables: min (or
    max) over the other variables' assignments of factor cost + their
    received costs (reference maxsum.py:382)."""
    from pydcop_tpu.dcop.relations import generate_assignment_as_dict

    other_vars = [v for v in factor.dimensions if v != variable]
    costs = {}
    better = (lambda a, b: a < b) if mode == "min" else (lambda a, b: a > b)
    for d in variable.domain:
        best = None
        for asst in generate_assignment_as_dict(other_vars):
            f_val = factor(**asst, **{variable.name: d})
            sum_cost = 0
            for other, val in asst.items():
                if other in recv_costs and val in recv_costs[other]:
                    sum_cost += recv_costs[other][val]
            current = f_val + sum_cost
            if best is None or better(current, best):
                best = current
        costs[d] = best
    return costs


def costs_for_factor(variable, factor_name: str, factors: List,
                     costs: Dict) -> Dict:
    """Message a variable sends to one factor: own costs + sum of other
    factors' costs, mean-normalized (reference maxsum.py:623-674)."""
    msg_costs = {d: variable.cost_for_val(d) for d in variable.domain}
    sum_cost = 0
    for d in variable.domain:
        for f in factors:
            if f == factor_name or f not in costs:
                continue
            if d not in costs[f]:
                continue
            c = costs[f][d]
            sum_cost += c
            msg_costs[d] += c
    avg = sum_cost / len(msg_costs)
    return {d: c - avg for d, c in msg_costs.items()}


def apply_damping(costs: Dict, prev_costs: Optional[Dict],
                  damping: float) -> Dict:
    if prev_costs is None:
        return costs
    return {
        d: damping * prev_costs[d] + (1 - damping) * c
        for d, c in costs.items()
    }


def approx_match(costs: Dict, prev_costs: Optional[Dict],
                 stability: float) -> bool:
    if prev_costs is None:
        return False
    for d, c in costs.items():
        prev = prev_costs[d]
        if prev != c:
            delta = abs(prev - c)
            if prev + c == 0 or not (2 * delta / abs(prev + c)) < stability:
                return False
    return True


def select_value(variable, costs: Dict[str, Dict], mode: str
                 ) -> Tuple[Any, float]:
    """Pick the domain value minimizing own + received costs; first
    optimum in domain order wins ties (reference maxsum.py:584)."""
    best_d, best_c = None, None
    better = (lambda a, b: a < b) if mode == "min" else (lambda a, b: a > b)
    for d in variable.domain:
        c = variable.cost_for_val(d)
        for f_costs in costs.values():
            if d in f_costs:
                c += f_costs[d]
        if best_c is None or better(c, best_c):
            best_d, best_c = d, c
    return best_d, best_c


class MaxSumMessage(Message):
    def __init__(self, costs: Dict):
        super().__init__("max_sum", None)
        self._costs = costs

    @property
    def costs(self) -> Dict:
        return dict(self._costs)

    @property
    def size(self) -> int:
        return 2 * len(self._costs)

    def __eq__(self, other):
        return (
            isinstance(other, MaxSumMessage) and self._costs == other._costs
        )

    def _simple_repr(self):
        vals, costs = (
            zip(*self._costs.items()) if self._costs else ((), ())
        )
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "vals": list(vals),
            "costs": list(costs),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(dict(zip(r["vals"], r["costs"])))

    def __repr__(self):
        return f"MaxSumMessage({self._costs})"


class MaxSumFactorComputation(SynchronousComputationMixin,
                              DcopComputation):
    """One computation per factor (constraint) in the factor graph."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.factor.name, comp_def)
        self.factor = comp_def.node.factor
        self.variables = self.factor.dimensions
        self._costs: Dict[str, Dict] = {}
        params = comp_def.algo.params
        self.damping = params.get("damping", 0.5)
        self.damping_nodes = params.get("damping_nodes", "both")
        self.stability = params.get("stability", 0.1)
        self._prev: Dict[str, Tuple[Optional[Dict], int]] = {}

    @register("max_sum")
    def _on_maxsum_msg(self, sender, msg, t):
        pass  # collected by the synchronous mixin

    def footprint(self) -> float:
        return super().footprint()

    def on_new_cycle(self, messages, cycle_id):
        for sender, (msg, t) in messages.items():
            self._costs[sender] = msg.costs
        for v in self.variables:
            costs_v = factor_costs_for_var(
                self.factor, v, self._costs, self.mode
            )
            prev, count = self._prev.get(v.name, (None, 0))
            if self.damping_nodes in ("factors", "both"):
                costs_v = apply_damping(costs_v, prev, self.damping)
            if not approx_match(costs_v, prev, self.stability):
                self.post_msg(v.name, MaxSumMessage(costs_v))
                self._prev[v.name] = (costs_v, 1)
            elif count < SAME_COUNT:
                self.post_msg(v.name, MaxSumMessage(costs_v))
                self._prev[v.name] = (costs_v, count + 1)
            # else: send suppression (reference :366-377); the sync
            # mixin emits a filler instead.
        return None


class MaxSumVariableComputation(SynchronousComputationMixin,
                                VariableComputation):
    """One computation per variable in the factor graph."""

    def __init__(self, comp_def):
        variable = comp_def.node.variable
        params = comp_def.algo.params
        noise = params.get("noise", 0.01)
        if noise and not isinstance(variable, VariableNoisyCostFunc):
            cost_func = (
                variable.cost_func
                if hasattr(variable, "cost_func")
                else (lambda _: 0)
            )
            variable = VariableNoisyCostFunc(
                variable.name, variable.domain, cost_func,
                initial_value=variable.initial_value, noise_level=noise,
            )
        super().__init__(variable, comp_def)
        self.factor_names = [l.factor_node for l in comp_def.node.links]
        self._costs: Dict[str, Dict] = {}
        self.damping = params.get("damping", 0.5)
        self.damping_nodes = params.get("damping_nodes", "both")
        self.stability = params.get("stability", 0.1)
        self._prev: Dict[str, Tuple[Optional[Dict], int]] = {}

    @register("max_sum")
    def _on_maxsum_msg(self, sender, msg, t):
        pass  # collected by the synchronous mixin

    def on_start(self):
        # Select an initial value from own costs.
        value, cost = optimal_cost_value(self._variable, self.mode)
        self.value_selection(value, cost)

    def on_new_cycle(self, messages, cycle_id):
        for sender, (msg, t) in messages.items():
            self._costs[sender] = msg.costs
        value, cost = select_value(self._variable, self._costs, self.mode)
        self.value_selection(value, cost)
        for f_name in self.factor_names:
            costs_f = costs_for_factor(
                self._variable, f_name, self.factor_names, self._costs
            )
            prev, count = self._prev.get(f_name, (None, 0))
            if self.damping_nodes in ("vars", "both"):
                costs_f = apply_damping(costs_f, prev, self.damping)
            if not approx_match(costs_f, prev, self.stability):
                self.post_msg(f_name, MaxSumMessage(costs_f))
                self._prev[f_name] = (costs_f, 1)
            elif count < SAME_COUNT:
                self.post_msg(f_name, MaxSumMessage(costs_f))
                self._prev[f_name] = (costs_f, count + 1)
        return None


# --------------------------------------------------------------------- #
# DSA (asynchronous, cycle bookkeeping)

DsaMessage = message_type("dsa_value", ["value"])


class DsaComputation(VariableComputation):
    """DSA-A/B/C with per-cycle neighbor value maps (reference
    dsa.py:214-431)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.probability = params.get("probability", 0.7)
        self.variant = params.get("variant", "B")
        self.stop_cycle = params.get("stop_cycle", 0)
        self.constraints = list(comp_def.node.constraints)
        self._neighbors = [
            v.name for c in self.constraints for v in c.dimensions
            if v.name != self.name
        ]
        self._neighbors = list(dict.fromkeys(self._neighbors))
        if params.get("p_mode") == "arity":
            n_count = sum(len(c.dimensions) - 1 for c in self.constraints)
            if n_count:
                self.probability = 1.2 / n_count
        self.current_cycle: Dict[str, Any] = {}
        self.next_cycle: Dict[str, Any] = {}
        if self.variant == "B":
            self._best_constraint_costs = {
                c.name: find_optimum(c, self.mode) for c in self.constraints
            }

    @property
    def neighbors(self) -> List[str]:
        return self._neighbors

    def on_start(self):
        if not self._neighbors:
            value, cost = optimal_cost_value(self._variable, self.mode)
            self.value_selection(value, cost)
            self.finished()
            self.stop()
            return
        self.random_value_selection()
        self.post_to_all_neighbors(DsaMessage(self.current_value))
        self._evaluate_cycle()

    @register("dsa_value")
    def _on_value_msg(self, sender, msg, t):
        if not self._running:
            return
        if sender not in self.current_cycle:
            self.current_cycle[sender] = msg.value
            self._evaluate_cycle()
        else:
            self.next_cycle[sender] = msg.value

    def _evaluate_cycle(self):
        if len(self.current_cycle) < len(self._neighbors):
            return
        self.current_cycle[self.name] = self.current_value
        asst = dict(self.current_cycle)
        best_values, best_cost = find_optimal(
            self._variable, asst, self.constraints, self.mode
        )
        current_cost = assignment_cost(asst, self.constraints)
        delta = abs(current_cost - best_cost)

        if self.variant == "A":
            if delta > 0:
                self._probabilistic_change(best_cost, best_values)
        elif self.variant == "B":
            if delta > 0:
                self._probabilistic_change(best_cost, best_values)
            elif delta == 0 and self._exists_violated():
                if len(best_values) > 1 and \
                        self.current_value in best_values:
                    best_values.remove(self.current_value)
                self._probabilistic_change(best_cost, best_values)
        else:  # C
            if delta > 0:
                self._probabilistic_change(best_cost, best_values)
            elif delta == 0:
                if len(best_values) > 1 and \
                        self.current_value in best_values:
                    best_values.remove(self.current_value)
                self._probabilistic_change(best_cost, best_values)

        self.new_cycle()
        self.current_cycle, self.next_cycle = self.next_cycle, {}
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()
            return
        self.post_to_all_neighbors(DsaMessage(self.current_value))

    def _probabilistic_change(self, best_cost, best_values):
        if self.probability > random.random():
            self.value_selection(random.choice(best_values), best_cost)

    def _exists_violated(self) -> bool:
        asst = dict(self.current_cycle)
        asst[self.name] = self.current_value
        for c in self.constraints:
            cost = c(**{v.name: asst[v.name] for v in c.dimensions})
            if cost != self._best_constraint_costs[c.name]:
                return True
        return False


# --------------------------------------------------------------------- #
# MGM (two-phase rounds)

MgmValueMessage = message_type("mgm_value", ["value"])
MgmGainMessage = message_type("mgm_gain", ["value", "random_nb"])


class MgmComputation(VariableComputation):
    """MGM rounds: value phase then gain phase, with postponed queues
    for early messages (reference mgm.py:213-609)."""

    def __init__(self, comp_def):
        super().__init__(comp_def.node.variable, comp_def)
        params = comp_def.algo.params
        self.break_mode = params.get("break_mode", "lexic")
        self.stop_cycle = params.get("stop_cycle", 0)
        self.constraints = list(comp_def.node.constraints)
        self._neighbors = list(dict.fromkeys(
            v.name for c in self.constraints for v in c.dimensions
            if v.name != self.name
        ))
        self._state = "values"
        self._neighbors_values: Dict[str, Any] = {}
        self._neighbors_gains: Dict[str, Tuple[float, float]] = {}
        self._postponed_values: List[Tuple] = []
        self._postponed_gains: List[Tuple] = []
        self._gain = 0.0
        self._new_value = None
        self._random_nb = 0.0

    @property
    def neighbors(self) -> List[str]:
        return self._neighbors

    def on_start(self):
        if not self._neighbors:
            value, cost = optimal_cost_value(self._variable, self.mode)
            self.value_selection(value, cost)
            self.finished()
            self.stop()
            return
        self.random_value_selection()
        self._send_value()

    def _send_value(self):
        self.new_cycle()
        if self.stop_cycle and self.cycle_count >= self.stop_cycle:
            self.finished()
            self.stop()
            return
        self.post_to_all_neighbors(MgmValueMessage(self.current_value))

    @register("mgm_value")
    def _on_value_msg(self, sender, msg, t):
        if self._state == "values":
            self._handle_value(sender, msg.value)
        else:
            self._postponed_values.append((sender, msg.value))

    def _handle_value(self, sender, value):
        self._neighbors_values[sender] = value
        if len(self._neighbors_values) < len(self._neighbors):
            return
        # All values in: compute current cost, best response and gain.
        asst = dict(self._neighbors_values)
        asst[self.name] = self.current_value
        current_cost = assignment_cost(asst, self.constraints)
        current_cost += self._variable.cost_for_val(self.current_value)
        self.value_selection(self.current_value, current_cost)

        best_values, best_cost = find_optimal(
            self._variable, self._neighbors_values, self.constraints,
            self.mode,
        )
        # Include own unary cost in the comparison:
        best_with_unary = None
        chosen = []
        for v in best_values:
            c = best_cost + self._variable.cost_for_val(v)
            if best_with_unary is None or c < best_with_unary:
                best_with_unary, chosen = c, [v]
            elif c == best_with_unary:
                chosen.append(v)
        self._gain = current_cost - best_with_unary
        if (self.mode == "min" and self._gain > 0) or (
            self.mode == "max" and self._gain < 0
        ):
            self._new_value = random.choice(chosen)
        else:
            self._new_value = self.current_value
        self._random_nb = random.random()
        self.post_to_all_neighbors(
            MgmGainMessage(self._gain, self._random_nb)
        )
        self._state = "gain"
        for sender2, msg2 in self._postponed_gains:
            self._handle_gain(sender2, msg2)
        self._postponed_gains.clear()

    @register("mgm_gain")
    def _on_gain_msg(self, sender, msg, t):
        if self._state == "gain":
            self._handle_gain(sender, msg)
        else:
            self._postponed_gains.append((sender, msg))

    def _handle_gain(self, sender, msg):
        self._neighbors_gains[sender] = (msg.value, msg.random_nb)
        if len(self._neighbors_gains) < len(self._neighbors):
            return
        max_gain = max(g for g, _ in self._neighbors_gains.values())
        if self._gain > max_gain:
            self.value_selection(
                self._new_value, self.current_cost - self._gain
            )
        elif self._gain == max_gain:
            if self.break_mode == "random":
                ties = sorted(
                    [
                        (rnd, name)
                        for name, (g, rnd) in
                        self._neighbors_gains.items()
                        if g == max_gain
                    ]
                    + [(self._random_nb, self.name)]
                )
            else:
                ties = sorted(
                    [
                        (name, name)
                        for name, (g, _) in
                        self._neighbors_gains.items()
                        if g == max_gain
                    ]
                    + [(self.name, self.name)]
                )
            if ties[0][1] == self.name:
                self.value_selection(
                    self._new_value, self.current_cost - self._gain
                )
        self._neighbors_gains.clear()
        self._neighbors_values.clear()
        self._state = "values"
        self._send_value()
        for sender2, value in self._postponed_values:
            self._handle_value(sender2, value)
        self._postponed_values.clear()


# --------------------------------------------------------------------- #
# Registry


# Algorithms with an agent-mode (message-passing) computation; others
# are device-engine only for now and rejected up front.
AGENT_MODE_ALGOS = frozenset(
    {"maxsum", "amaxsum", "dsa", "adsa", "dsatuto", "mgm"}
)


def has_agent_computation(algo_name: str) -> bool:
    return algo_name in AGENT_MODE_ALGOS


def build(algo_name: str, comp_def):
    from pydcop_tpu.computations_graph.factor_graph import (
        FactorComputationNode,
        VariableComputationNode,
    )

    if algo_name in ("maxsum", "amaxsum"):
        node = comp_def.node
        if isinstance(node, FactorComputationNode):
            return MaxSumFactorComputation(comp_def)
        if isinstance(node, VariableComputationNode):
            return MaxSumVariableComputation(comp_def)
        raise TypeError(f"Unsupported node for maxsum: {node}")
    if algo_name in ("dsa", "adsa", "dsatuto"):
        return DsaComputation(comp_def)
    if algo_name == "mgm":
        return MgmComputation(comp_def)
    raise NotImplementedError(
        f"No agent-mode computation for algorithm {algo_name!r} yet"
    )

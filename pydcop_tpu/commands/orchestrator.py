"""``pydcop orchestrator``: standalone orchestrator for multi-machine
deployments.

Reference parity: pydcop/commands/orchestrator.py (run_cmd :391) — the
orchestrator listens on an HTTP transport, waits for standalone agents
(``pydcop agent`` on other machines/shells) to register through the
directory, then deploys, runs and reports like ``pydcop solve``.
"""

import logging

from pydcop_tpu.commands._utils import build_algo_def, emit_result

logger = logging.getLogger("pydcop.cli.orchestrator")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "orchestrator",
        help="standalone orchestrator for multi-machine runs")
    parser.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    parser.add_argument("-a", "--algo", required=True,
                        help="algorithm name")
    parser.add_argument("-p", "--algo_params", action="append",
                        help="algorithm parameter as name:value")
    parser.add_argument("-d", "--distribution", default="oneagent",
                        help="distribution method or file")
    parser.add_argument("--address", default="127.0.0.1",
                        help="address to listen on")
    parser.add_argument("--port", type=int, default=9000,
                        help="port to listen on")
    parser.add_argument("-s", "--scenario", default=None,
                        help="optional scenario yaml (dynamic run)")
    parser.add_argument("-k", "--ktarget", type=int, default=0,
                        help="replicate computations k times before "
                             "running (requires agents started with "
                             "--replication)")
    parser.add_argument("--wait_ready_timeout", type=float, default=60,
                        help="how long to wait for agents to register")
    parser.add_argument("--collect_on", default="value_change",
                        choices=["value_change", "cycle_change",
                                 "period"],
                        help="when metrics rows are collected")
    parser.add_argument("--period", type=float, default=1.0,
                        help="collection period for --collect_on "
                             "period")
    parser.add_argument("--run_metrics", default=None,
                        help="stream metrics rows to this csv during "
                             "the run")
    parser.add_argument("--end_metrics", default=None,
                        help="append the final summary row to this "
                             "csv")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.dcop.yamldcop import (
        load_dcop_from_file,
        load_scenario_from_file,
    )
    from pydcop_tpu.infrastructure.communication import (
        HttpCommunicationLayer,
    )
    from pydcop_tpu.infrastructure.orchestrator import Orchestrator
    from pydcop_tpu.infrastructure.run import _build_distribution

    dcop = load_dcop_from_file(args.dcop_files)
    scenario = (
        load_scenario_from_file(args.scenario)
        if args.scenario else None
    )
    algo_def = build_algo_def(args.algo, args.algo_params, dcop.objective)
    algo_module = load_algorithm_module(algo_def.algo)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    distribution = _build_distribution(
        dcop, cg, algo_module, args.distribution
    )

    collector = None
    if args.run_metrics:
        from pydcop_tpu.commands.metrics_io import add_csvline

        def collector(metrics):
            add_csvline(args.run_metrics, args.collect_on, metrics)

    comm = HttpCommunicationLayer((args.address, args.port))
    orchestrator = Orchestrator(
        algo_def, cg, distribution, comm, dcop, args.infinity
        if hasattr(args, "infinity") else float("inf"),
        collector=collector, collect_moment=args.collect_on,
        collect_period=args.period,
    )
    orchestrator.start()
    stopped = False
    try:
        logger.info(
            "Orchestrator on %s:%s, waiting for agents...",
            args.address, args.port,
        )
        if not orchestrator.wait_ready(args.wait_ready_timeout):
            print("Error: agents did not register in time")
            return 3
        orchestrator.deploy_computations()
        replica_mapping = None
        if args.ktarget:
            replica_mapping = orchestrator.start_replication(
                args.ktarget
            ).mapping
        timeout = args.timeout if args.timeout is not None else 30.0
        orchestrator.run(scenario=scenario, timeout=timeout)
        orchestrator.stop_agents(10)
        stopped = True
        metrics = orchestrator.end_metrics()
        result = {
            "status": metrics["status"],
            "assignment": {
                k: v for k, v in metrics["assignment"].items()
                if k in dcop.variables
            },
            "cost": metrics["cost"],
            "violation": metrics["violation"],
            "time": metrics["time"],
            "msg_count": metrics["msg_count"],
            "msg_size": metrics["msg_size"],
            "cycle": metrics["cycle"],
            "agt_metrics": metrics["agt_metrics"],
            "backend": "multi-machine",
        }
        if replica_mapping is not None:
            result["replication"] = {
                "ktarget": args.ktarget,
                "replica_distribution": replica_mapping,
                "repaired": sorted(
                    orchestrator.mgt.repaired_computations
                ),
            }
    finally:
        if not stopped:
            orchestrator.stop_agents(10)
        orchestrator.stop()

    if args.run_metrics or args.end_metrics:
        from pydcop_tpu.commands.metrics_io import add_csvline

        # Run metrics streamed live above; both files always get the
        # final summary row so they exist even when no collection
        # event fired (same guarantee as solve.py).
        for path in (args.run_metrics, args.end_metrics):
            if path:
                add_csvline(path, args.collect_on, result)

    emit_result(result, args.output)
    return 0

"""Fleet-serving battery (ISSUE 15): structure-affinity routing,
replicated workers, and the persistent AOT compile cache.

- the router-side affinity key partitions traffic EXACTLY like the
  workers' serving bin key (partition-equivalence over topologies,
  domains and solver params) without paying the cost-table fill;
- rendezvous hashing is deterministic across processes, spreads
  structures over replicas, and remaps ONLY a dead replica's keys
  (the property that keeps disk- and jit-warm programs warm through
  membership change);
- routing policy logic without any subprocess: affinity hits,
  least-loaded spillover past ``spill_slack``, breaker-aware
  shedding to 503, round-robin A/B mode, request-pin retention;
- the persistent AOT compile cache: enable/latch handling, hit
  accounting, the cold-call compile split (disk hit → compile =
  retrieval wall, any miss → whole-interval convention), and a
  REAL two-process proof that a fresh process serves a
  known-structure solve without recompiling;
- a real 2-replica fleet over HTTP: burst parity with solo
  ``api.solve``, ``affinity_hit_fraction`` on /stats, pinned
  /result polling, fleet /healthz, SIGTERM-equivalent drain to
  exit 0 (the SIGKILL handoff lives in tools/chaos_soak.py
  ``replica_kill`` and tools/serve_smoke.py).
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine import aotcache
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.serving import binning
from pydcop_tpu.serving.router import (
    DOWN,
    UP,
    FleetRouter,
    FleetUnavailable,
    Replica,
    _rendezvous_score,
)

REPO = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


def _ring(n: int, seed: int, colors: int = 3) -> DCOP:
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(colors)))
    dcop = DCOP(f"fleet_{n}_{colors}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n):
        table = rng.integers(0, 10,
                             size=(colors, colors)).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [vs[k], vs[(k + 1) % n]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


# ------------------------------------------------------------------ #
# affinity key


class TestAffinityKey:
    def test_partition_equivalent_to_bin_key(self):
        """Two DCOPs share an affinity key iff they share a serving
        bin key — over same-structure/different-cost pairs, different
        topologies and different domain sizes."""
        instances = (
            [_ring(8, s) for s in range(3)]        # one structure
            + [_ring(11, s) for s in range(2)]     # another
            + [_ring(8, 7, colors=4)]              # domain differs
        )
        params = binning.normalize_params({"max_cycles": 60})
        keys = []
        for dcop in instances:
            graph, _meta = compile_dcop(dcop, noise_level=0.01)
            keys.append((binning.affinity_key(
                dcop, {"max_cycles": 60}),
                binning.bin_key(graph, params)))
        for i, (aff_i, bin_i) in enumerate(keys):
            for j, (aff_j, bin_j) in enumerate(keys):
                assert (aff_i == aff_j) == (bin_i == bin_j), (
                    f"affinity/bin partition disagreement between "
                    f"instance {i} and {j}")

    def test_params_ride_in_the_key(self):
        dcop = _ring(8, 0)
        assert binning.affinity_key(dcop, {"max_cycles": 60}) \
            != binning.affinity_key(dcop, {"max_cycles": 61})
        assert binning.affinity_key(dcop, {"max_cycles": 60}) \
            == binning.affinity_key(dcop, {"max_cycles": 60})

    def test_bad_params_reject_like_submit(self):
        with pytest.raises(ValueError):
            binning.affinity_key(_ring(8, 0), {"bogus": 1})

    def test_service_defaults_merge_into_the_key(self):
        """A client spelling a service default explicitly must hash
        to the same affinity key as one omitting it — the router
        merges its fleet default_params under the request params
        before keying (otherwise same-bin traffic splits across
        replicas whenever the fleet runs non-module defaults)."""
        router = FleetRouter(replicas=1,
                             default_params={"max_cycles": 60})
        dcop = _ring(8, 0)
        merged = dict(router.default_params)   # params={} request
        implicit = binning.affinity_key(dcop, merged)
        explicit = binning.affinity_key(dcop, {"max_cycles": 60})
        assert implicit == explicit
        assert implicit != binning.affinity_key(dcop, None)

    def test_no_cost_tables_needed(self):
        """The key is computable for a problem whose cost tables
        would be huge — the whole point of not compiling at the
        router (here just asserted cheap + stable)."""
        dcop = _ring(64, 3)
        t0 = time.perf_counter()
        digest = binning.affinity_key(dcop, None)
        assert time.perf_counter() - t0 < 0.5
        assert digest == binning.affinity_key(_ring(64, 99), None)


# ------------------------------------------------------------------ #
# rendezvous hashing


class TestRendezvous:
    def test_deterministic_and_spread(self):
        digests = [f"structure-{i}" for i in range(64)]
        owners = {
            d: max(range(4),
                   key=lambda k: _rendezvous_score(d, k))
            for d in digests
        }
        again = {
            d: max(range(4),
                   key=lambda k: _rendezvous_score(d, k))
            for d in digests
        }
        assert owners == again
        counts = [list(owners.values()).count(k) for k in range(4)]
        assert all(c > 0 for c in counts), counts

    def test_membership_change_remaps_only_dead_keys(self):
        """Remove replica 2: every key it did NOT own keeps its
        owner — the rendezvous property that preserves warm caches
        through a replica death."""
        digests = [f"structure-{i}" for i in range(128)]
        owners = {
            d: max(range(4),
                   key=lambda k: _rendezvous_score(d, k))
            for d in digests
        }
        survivors = [0, 1, 3]
        after = {
            d: max(survivors,
                   key=lambda k: _rendezvous_score(d, k))
            for d in digests
        }
        for d in digests:
            if owners[d] != 2:
                assert after[d] == owners[d]


# ------------------------------------------------------------------ #
# routing policy (no subprocesses)


def _bench_router(n=3, **kw) -> FleetRouter:
    """A router with synthetic UP replicas and no processes —
    pick()/pin()/stats() are pure bookkeeping."""
    router = FleetRouter(replicas=n, **kw)
    for k in range(n):
        replica = Replica(k, None, f"/dev/null-{k}")
        replica.status = UP
        replica.port = 1  # non-None: counts as addressable
        router.replicas.append(replica)
    return router


class TestRoutingPolicy:
    def test_affinity_hits_accumulate(self):
        router = _bench_router()
        first, hit0 = router.pick("digest-a")
        router.release(first)
        assert hit0 is False
        second, hit1 = router.pick("digest-a")
        router.release(second)
        assert hit1 is True and second is first
        stats = router.stats()
        assert stats["affinity_hit_fraction"] == 0.5

    def test_spillover_past_slack(self):
        router = _bench_router(spill_slack=2)
        primary, _hit = router.pick("digest-b")
        primary.in_flight = 10  # deep backlog on the warm replica
        chosen, _hit = router.pick("digest-b")
        assert chosen is not primary
        assert chosen.in_flight == 1
        assert router.spillovers == 1

    def test_breaker_aware_shedding(self):
        router = _bench_router(n=2)
        router.replicas[0].breaker_open = True
        chosen, _hit = router.pick("digest-c")
        assert chosen is router.replicas[1]
        router.replicas[1].status = DOWN
        with pytest.raises(FleetUnavailable):
            router.pick("digest-c")
        assert router.stats()["shed"] == 1

    def test_round_robin_mode_cycles(self):
        router = _bench_router(affinity="round_robin")
        picks = []
        for _ in range(6):
            replica, hit = router.pick("same-digest")
            router.release(replica)
            picks.append(replica.index)
        assert set(picks) == {0, 1, 2}

    def test_pin_table_bounded(self):
        import pydcop_tpu.serving.router as router_mod

        router = _bench_router(n=1)
        replica = router.replicas[0]
        keep = router_mod.PIN_KEEP
        for i in range(keep + 10):
            router.pin(f"r{i}", replica)
        assert len(router._pins) == keep
        assert router.pinned("r0") is None          # evicted oldest
        assert router.pinned(f"r{keep + 9}") is replica

    def test_rejects_bad_config(self):
        with pytest.raises(ValueError):
            FleetRouter(replicas=0)
        with pytest.raises(ValueError):
            FleetRouter(affinity="sticky")


# ------------------------------------------------------------------ #
# persistent AOT compile cache


class TestAotCache:
    def test_split_cold_call_contract(self):
        before = {"hits": 2, "misses": 1, "retrieval_s": 0.5,
                  "saved_s": 0.0}
        pure_hit = {"hits": 4, "misses": 1, "retrieval_s": 0.56,
                    "saved_s": 0.0}
        with_miss = {"hits": 4, "misses": 2, "retrieval_s": 0.56,
                     "saved_s": 0.0}
        no_activity = dict(before)
        from pydcop_tpu.engine.aotcache import _lock, _state

        with _lock:
            was = _state["enabled"]
            _state["enabled"] = True
        try:
            got = aotcache.split_cold_call(1.0, before, pure_hit)
            assert got == pytest.approx(0.06)
            # Clamped into the measured interval.
            assert aotcache.split_cold_call(
                0.01, before, pure_hit) == pytest.approx(0.01)
            # Any miss → the whole-interval convention stands.
            assert aotcache.split_cold_call(
                1.0, before, with_miss) is None
            assert aotcache.split_cold_call(
                1.0, before, no_activity) is None
        finally:
            with _lock:
                _state["enabled"] = was
        if not was:
            assert aotcache.split_cold_call(
                1.0, before, pure_hit) is None  # disabled → None

    def test_enable_resolves_env(self, tmp_path, monkeypatch):
        monkeypatch.delenv(aotcache.ENV_DIR, raising=False)
        assert aotcache.maybe_enable_from_env() is None

    def test_fresh_process_serves_without_recompiling(self, tmp_path):
        """THE acceptance mechanism: process A compiles a structure
        (disk miss), process B solves the same structure with its
        compile component collapsed to the cache-retrieval wall."""
        cache = str(tmp_path / "aot")
        code = (
            "import os, sys, json\n"
            f"sys.path.insert(0, {REPO!r})\n"
            "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
            "from pydcop_tpu.engine import aotcache\n"
            "aotcache.enable_persistent_compile_cache("
            f"{cache!r})\n"
            "from tests.unit.test_fleet_battery import _ring\n"
            "from pydcop_tpu.api import solve\n"
            "res = solve(_ring(16, 5), 'maxsum', max_cycles=60)\n"
            "print(json.dumps({'compile': res['compile_time'],"
            " 'counters': aotcache.counters()}))\n"
        )
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PYTHONPATH=REPO)
        runs = []
        for _ in range(2):
            proc = subprocess.run(
                [sys.executable, "-c", code], env=env, cwd=REPO,
                capture_output=True, text=True, timeout=300)
            assert proc.returncode == 0, proc.stderr[-800:]
            runs.append(json.loads(proc.stdout.splitlines()[-1]))
        cold, warm = runs
        assert cold["counters"]["misses"] >= 1
        assert cold["counters"]["hits"] == 0
        assert warm["counters"]["hits"] >= 1
        assert warm["counters"]["misses"] == 0
        # The ledger claim: a warm-disk cold call's compile component
        # is the retrieval wall — far under the real compile.
        assert warm["compile"] < 0.5 * cold["compile"], (cold, warm)

    def test_stats_counts_disk_entries(self, tmp_path):
        from pydcop_tpu.engine.aotcache import _lock, _state

        (tmp_path / "a-cache").write_bytes(b"x" * 10)
        (tmp_path / "b-cache").write_bytes(b"y" * 20)
        (tmp_path / "b-atime").write_bytes(b"")
        with _lock:
            prior = dict(_state)
            _state["enabled"] = True
            _state["dir"] = str(tmp_path)
        try:
            stats = aotcache.stats()
        finally:
            with _lock:
                _state.update(prior)
        assert stats["entries"] == 2
        assert stats["bytes"] >= 30


# ------------------------------------------------------------------ #
# the real fleet, end to end


def _post(url, body, timeout=120):
    req = urllib.request.Request(
        url + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


class TestFleetEndToEnd:
    def test_two_replica_fleet_serves_like_one_service(self):
        from pydcop_tpu import api
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        handle = api.serve(port=0, replicas=2, batch_window_s=0.05,
                           max_batch=8, heartbeat_s=0.2)
        try:
            url = handle.url
            dcops = ([_ring(9, 30 + s) for s in range(3)]
                     + [_ring(12, 60 + s) for s in range(3)])
            payloads = [dcop_yaml(d) for d in dcops]
            results = [None] * len(dcops)

            def client(i):
                results[i] = _post(url, {
                    "dcop": payloads[i], "wait": True,
                    "timeout": 120,
                    "params": {"max_cycles": 60}})

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(dcops))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            assert all(r is not None and r[0] == 200
                       and r[1]["status"] == "FINISHED"
                       for r in results), results

            # Wire parity: the fleet answers exactly like solo
            # api.solve — clients cannot tell the fleet exists.
            for dcop, (_, res) in zip(dcops, results):
                solo = api.solve(dcop, "maxsum", backend="device",
                                 max_cycles=60)
                assert res["assignment"] == solo["assignment"]
                assert res["cost"] == solo["cost"]

            # Async path rides the pin table.
            status, ack = _post(url, {"dcop": payloads[0],
                                      "params": {"max_cycles": 60}})
            assert status == 202 and ack["id"].startswith("f")
            deadline = time.monotonic() + 60
            body = None
            while time.monotonic() < deadline:
                try:
                    with urllib.request.urlopen(
                            url + "/result/" + ack["id"],
                            timeout=10) as resp:
                        if resp.status == 200:
                            body = json.loads(resp.read())
                            break
                except urllib.error.HTTPError:
                    pass
                time.sleep(0.1)
            assert body is not None \
                and body["status"] == "FINISHED"

            with urllib.request.urlopen(url + "/stats",
                                        timeout=30) as resp:
                stats = json.loads(resp.read())
            assert stats["up"] == 2
            assert stats["routed"] >= 7
            assert stats["affinity_hit_fraction"] is not None
            assert stats["affinity_hit_fraction"] > 0
            # Both structures warmed SOME replica; same-structure
            # traffic stayed put (rendezvous is deterministic).
            assert sum(w["forwarded"]
                       for w in stats["workers"]) == stats["routed"]
            with urllib.request.urlopen(url + "/healthz",
                                        timeout=10) as resp:
                assert json.loads(resp.read())["status"] == "ok"
        finally:
            summary = handle.stop()
        # Fleet drain: every worker exits 0 (the SIGTERM contract).
        assert [w["exit"] for w in summary["workers"]] == [0, 0]

    def test_unknown_result_404_and_bad_body_400(self):
        from pydcop_tpu import api

        handle = api.serve(port=0, replicas=2, batch_window_s=0.02)
        try:
            url = handle.url
            with pytest.raises(urllib.error.HTTPError) as err:
                urllib.request.urlopen(url + "/result/nope",
                                       timeout=10)
            assert err.value.code == 404
            status, body = _post(url, {"dcop": "   "})
            assert status == 400
            status, body = _post(url, {"dcop": "not: [valid"})
            assert status == 400
        finally:
            handle.stop()


# ------------------------------------------------------------------ #
# CLI knobs


class TestServeCli:
    def test_fleet_knobs_parse(self):
        import argparse

        from pydcop_tpu.commands import serve as serve_cmd

        parser = argparse.ArgumentParser()
        sub = parser.add_subparsers()
        serve_cmd.set_parser(sub)
        args = parser.parse_args(
            ["serve", "--replicas", "4", "--affinity", "round_robin",
             "--compile_cache_dir", "/tmp/aot", "--heartbeat",
             "0.5", "--spill_slack", "7", "--port_file", "/tmp/p"])
        assert args.replicas == 4
        assert args.affinity == "round_robin"
        assert args.compile_cache_dir == "/tmp/aot"
        assert args.heartbeat == 0.5
        assert args.spill_slack == 7
        assert args.port_file == "/tmp/p"

    def test_params_json_knob_parses(self):
        import argparse

        from pydcop_tpu.commands import serve as serve_cmd

        parser = argparse.ArgumentParser()
        sub = parser.add_subparsers()
        serve_cmd.set_parser(sub)
        args = parser.parse_args(
            ["serve", "--params_json", '{"prune": 1}'])
        assert args.params_json == '{"prune": 1}'

    def test_fleet_forwards_full_default_params(self):
        """api.serve's fleet path must hand EVERY default-param key
        to the workers — a replicas=2 service silently dropping the
        caller's stability/prune defaults would solve differently
        than replicas=1."""
        import json as json_mod
        from unittest import mock

        from pydcop_tpu import api

        captured = {}

        class FakeRouter:
            def __init__(self, **kw):
                captured.update(kw)
                raise RuntimeError("stop here")

        with mock.patch(
                "pydcop_tpu.serving.router.FleetRouter", FakeRouter):
            with pytest.raises(RuntimeError, match="stop here"):
                api.serve(replicas=2, default_params={
                    "max_cycles": 99, "damping": 0.7,
                    "stability": 0.05, "prune": 1})
        worker_args = captured["worker_args"]
        assert worker_args[worker_args.index("--cycles") + 1] == "99"
        assert worker_args[
            worker_args.index("--damping") + 1] == "0.7"
        extra = json_mod.loads(
            worker_args[worker_args.index("--params_json") + 1])
        assert extra == {"stability": 0.05, "prune": 1}

    def test_affinity_choices_enforced(self):
        import argparse

        from pydcop_tpu.commands import serve as serve_cmd

        parser = argparse.ArgumentParser()
        sub = parser.add_subparsers()
        serve_cmd.set_parser(sub)
        with pytest.raises(SystemExit):
            parser.parse_args(["serve", "--affinity", "sticky"])

"""Battery over algorithms/__init__.py — parameter validation,
AlgorithmDef/ComputationDef, discovery and default injection
(reference test_algorithms_base.py depth)."""

import pytest

from pydcop_tpu.algorithms import (
    AlgoParameterDef,
    AlgoParameterException,
    AlgorithmDef,
    check_param_value,
    list_available_algorithms,
    load_algorithm_module,
    prepare_algo_params,
)


class TestCheckParamValue:
    def test_none_gives_default(self):
        p = AlgoParameterDef("d", "float", None, 0.5)
        assert check_param_value(None, p) == 0.5

    def test_int_coercion_from_string(self):
        p = AlgoParameterDef("n", "int", None, 0)
        assert check_param_value("42", p) == 42

    def test_float_coercion(self):
        p = AlgoParameterDef("f", "float", None, 0.0)
        assert check_param_value("0.25", p) == 0.25
        assert check_param_value(1, p) == 1.0

    def test_bool_string_forms(self):
        p = AlgoParameterDef("b", "bool", None, False)
        assert check_param_value("true", p) is True
        assert check_param_value("YES", p) is True
        assert check_param_value("1", p) is True
        assert check_param_value("false", p) is False
        assert check_param_value("0", p) is False

    def test_bool_non_string(self):
        p = AlgoParameterDef("b", "bool", None, False)
        assert check_param_value(1, p) is True
        assert check_param_value(0, p) is False

    def test_str_coercion(self):
        p = AlgoParameterDef("s", "str", None, "")
        assert check_param_value(3, p) == "3"

    def test_invalid_int_raises(self):
        p = AlgoParameterDef("n", "int", None, 0)
        with pytest.raises(AlgoParameterException, match="Invalid"):
            check_param_value("not-a-number", p)

    def test_allowed_values_enforced(self):
        p = AlgoParameterDef("v", "str", ["A", "B"], "A")
        assert check_param_value("B", p) == "B"
        with pytest.raises(AlgoParameterException, match="allowed"):
            check_param_value("C", p)

    def test_allowed_values_checked_after_coercion(self):
        p = AlgoParameterDef("n", "int", [1, 2], 1)
        assert check_param_value("2", p) == 2
        with pytest.raises(AlgoParameterException):
            check_param_value("3", p)


class TestPrepareAlgoParams:
    DEFS = [
        AlgoParameterDef("damping", "float", None, 0.5),
        AlgoParameterDef("variant", "str", ["A", "B"], "B"),
    ]

    def test_defaults_filled(self):
        out = prepare_algo_params({}, self.DEFS)
        assert out == {"damping": 0.5, "variant": "B"}

    def test_given_values_validated(self):
        out = prepare_algo_params({"damping": "0.8"}, self.DEFS)
        assert out["damping"] == 0.8

    def test_unknown_param_rejected(self):
        with pytest.raises(AlgoParameterException, match="Unknown"):
            prepare_algo_params({"nope": 1}, self.DEFS)

    def test_error_lists_supported_names(self):
        with pytest.raises(AlgoParameterException,
                           match="damping.*variant"):
            prepare_algo_params({"zz": 1}, self.DEFS)


class TestAlgorithmDef:
    def test_build_with_defaults_from_module(self):
        ad = AlgorithmDef.build_with_default_param("maxsum", mode="min")
        assert ad.algo == "maxsum"
        assert ad.params["damping"] == 0.5
        assert ad.mode == "min"

    def test_build_validates_params(self):
        with pytest.raises(AlgoParameterException):
            AlgorithmDef.build_with_default_param(
                "dsa", {"variant": "Z"})

    def test_param_value(self):
        ad = AlgorithmDef.build_with_default_param("dsa")
        assert ad.param_value("variant") == "B"
        with pytest.raises(KeyError):
            ad.param_value("nope")

    def test_params_copy_not_alias(self):
        ad = AlgorithmDef("a", {"k": 1})
        ad.params["k"] = 99
        assert ad.param_value("k") == 1

    def test_equality(self):
        a = AlgorithmDef("x", {"k": 1}, "min")
        assert a == AlgorithmDef("x", {"k": 1}, "min")
        assert a != AlgorithmDef("x", {"k": 2}, "min")
        assert a != AlgorithmDef("x", {"k": 1}, "max")

    def test_wire_roundtrip(self):
        from pydcop_tpu.utils.simple_repr import from_repr, simple_repr

        ad = AlgorithmDef("dsa", {"variant": "A"}, "max")
        ad2 = from_repr(simple_repr(ad))
        assert ad2 == ad


class TestDiscoveryAndDefaults:
    def test_all_14_algorithms_listed(self):
        algos = list_available_algorithms()
        expected = {
            "maxsum", "amaxsum", "maxsum_dynamic", "dpop", "dsa",
            "adsa", "dsatuto", "mgm", "mgm2", "dba", "gdba", "syncbb",
            "ncbb", "mixeddsa",
        }
        assert expected <= set(algos)

    def test_unknown_algorithm_raises(self):
        with pytest.raises(Exception):
            load_algorithm_module("definitely_not_an_algo")

    def test_every_module_has_contract_surface(self):
        # The plugin contract: GRAPH_TYPE, algo_params,
        # computation_memory, communication_load (defaults injected at
        # load, reference algorithms/__init__.py:528-566).
        for name in list_available_algorithms():
            mod = load_algorithm_module(name)
            assert isinstance(mod.GRAPH_TYPE, str), name
            assert isinstance(mod.algo_params, list), name
            assert callable(mod.computation_memory), name
            assert callable(mod.communication_load), name
            assert callable(mod.build_computation), name

    def test_module_cached(self):
        m1 = load_algorithm_module("dsa")
        m2 = load_algorithm_module("dsa")
        assert m1 is m2

"""Computation graphs: how a DCOP maps onto communicating computations.

Reference parity: pydcop/computations_graph/ — four graph models, each
exposing ``build_computation_graph(dcop) -> ComputationGraph``:

- ``factor_graph``: bipartite variable/factor nodes (maxsum family);
- ``constraints_hypergraph``: one node per variable (local-search family);
- ``pseudotree``: DFS pseudo-tree (dpop, ncbb);
- ``ordered_graph``: total variable order (syncbb).

TPU-native addition: every graph can be *compiled* to a dense, padded,
bucketed array form by pydcop_tpu.engine.compile for on-device execution.
"""

import importlib


def load_graph_module(name: str):
    return importlib.import_module(f"pydcop_tpu.computations_graph.{name}")

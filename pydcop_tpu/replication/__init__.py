"""Resilience: k-replication of computations across agents.

Reference parity: pydcop/replication/ (dist_ucs_hostingcosts.py — the
AAMAS-18 distributed UCS replica placement; objects.py
ReplicaDistribution :40; path_utils.py path-table algebra).
"""

from pydcop_tpu.replication.objects import ReplicaDistribution
from pydcop_tpu.replication.dist_ucs_hostingcosts import (
    UCSReplication,
    build_replication_computation,
    replication_computation_name,
)

__all__ = [
    "ReplicaDistribution",
    "UCSReplication",
    "build_replication_computation",
    "replication_computation_name",
]

"""Static consistency gate (the reference runs mypy, Makefile:20;
mypy is not installable in this zero-egress image, so this is the
stdlib equivalent): byte-compile every source file, then import every
module of the package under a scrubbed CPU backend — catching syntax
errors, missing imports, and module-level typos across the whole tree
in one pass.

Also a fault-injection seam lint (ISSUE 19): every socket-touching
call in ``pydcop_tpu/serving/`` must route through
``serving/netfault.py`` — raw ``http.client``/``urllib``/``socket``
use in the serve plane would silently bypass the injectable link
faults the chaos gate relies on, making partition scenarios prove
nothing about the code path production runs.

Run:  python tools/static_check.py      (exit 0 = clean)
"""

import compileall
import importlib
import os
import pkgutil
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Tokens that open sockets directly.  serving/netfault.py is the one
# allowed user (it IS the seam); serving/http.py and telemetry.py are
# SERVER-side (socketserver binds, no outbound links to fault), so
# only outbound-client tokens are banned there.
_SOCKET_TOKENS = (
    "http.client",
    "HTTPConnection(",
    "urllib.request",
    "urlopen(",
    "socket.create_connection",
)
_SEAM_ALLOWLIST = ("netfault.py",)


def check_netfault_seam() -> int:
    serving = os.path.join(REPO, "pydcop_tpu", "serving")
    bad = []
    for fname in sorted(os.listdir(serving)):
        if not fname.endswith(".py") or fname in _SEAM_ALLOWLIST:
            continue
        path = os.path.join(serving, fname)
        with open(path, encoding="utf-8") as f:
            for lineno, line in enumerate(f, 1):
                code = line.split("#", 1)[0]
                for tok in _SOCKET_TOKENS:
                    if tok in code:
                        bad.append((fname, lineno, tok,
                                    line.strip()))
    if bad:
        print("static_check: raw socket I/O in the serve plane must "
              "route through serving/netfault.py (the fault-"
              "injection seam):")
        for fname, lineno, tok, line in bad:
            print(f"  pydcop_tpu/serving/{fname}:{lineno}: "
                  f"{tok!r} in: {line}")
        return 1
    return 0


def _call_sites(src: str, needle: str):
    """Yield (lineno, full_call_text) for every ``needle(`` call in
    ``src`` with balanced-paren capture (calls span lines).  ``def
    needle(`` definitions are skipped — the lint is about callers."""
    lines = src.splitlines()
    i = 0
    while i < len(lines):
        code = lines[i].split("#", 1)[0]
        col = code.find(needle + "(")
        if col < 0 or code.lstrip().startswith("def "):
            i += 1
            continue
        depth, j, text = 0, i, []
        pos = col + len(needle)
        while j < len(lines):
            chunk = lines[j].split("#", 1)[0]
            seg = chunk[pos:] if j == i else chunk
            for ch in seg:
                if ch == "(":
                    depth += 1
                elif ch == ")":
                    depth -= 1
            text.append(seg)
            if depth <= 0 and j >= i:
                break
            pos = 0
            j += 1
        yield i + 1, "\n".join(text)
        i = j + 1


def check_trace_seam() -> int:
    """Fleet-trace context seam (ISSUE 20): every router-side
    ``_forward(``/``open_stream(`` call site must DECIDE about trace
    context explicitly — ``trace=`` (``headers=`` for streams), even
    if the decision is ``trace=None`` (telemetry-plane probes).  A
    forward without the kwarg is a causal-tree hole: the replica
    would mint a fresh trace_id and the hop vanishes from
    ``/fleet/forensics``."""
    bad = []
    for fname in ("router.py", "migration.py"):
        path = os.path.join(REPO, "pydcop_tpu", "serving", fname)
        with open(path, encoding="utf-8") as f:
            src = f.read()
        for lineno, call in _call_sites(src, "_forward"):
            if "trace=" not in call:
                bad.append((fname, lineno, "_forward", "trace="))
        for lineno, call in _call_sites(src, "open_stream"):
            if "headers=" not in call:
                bad.append((fname, lineno, "open_stream", "headers="))
    if bad:
        print("static_check: router forwarding call sites must "
              "attach trace context explicitly (trace=ctx, or "
              "trace=None for telemetry-plane probes) — see "
              "docs/observability.md \"Fleet tracing\":")
        for fname, lineno, fn, kwarg in bad:
            print(f"  pydcop_tpu/serving/{fname}:{lineno}: "
                  f"{fn}(...) without {kwarg}")
        return 1
    return 0


def main() -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    sys.path.insert(0, REPO)

    ok = compileall.compile_dir(
        os.path.join(REPO, "pydcop_tpu"), quiet=1, force=True)
    ok &= compileall.compile_dir(
        os.path.join(REPO, "tests"), quiet=1, force=True)
    if not ok:
        print("static_check: byte-compilation failed")
        return 1

    if check_netfault_seam():
        return 1

    if check_trace_seam():
        return 1

    import pydcop_tpu

    failures = []
    for mod in pkgutil.walk_packages(
            pydcop_tpu.__path__, prefix="pydcop_tpu."):
        try:
            importlib.import_module(mod.name)
        except Exception as exc:  # noqa: BLE001 - report, don't mask
            failures.append((mod.name, f"{type(exc).__name__}: {exc}"))
    if failures:
        print(f"static_check: {len(failures)} module(s) failed to "
              "import:")
        for name, err in failures:
            print(f"  {name}: {err}")
        return 1
    print("static_check: all modules compile and import cleanly")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Battery over utils/simple_repr.py — the wire format's invariants
(reference test_utils_simplerepr.py depth): scalar/container
round-trips, init-signature discovery, _repr_mapping, defaulted args,
JSON compatibility, and the error paths."""

import json

import pytest

from pydcop_tpu.utils.simple_repr import (
    SimpleRepr,
    SimpleReprException,
    from_repr,
    simple_repr,
)


class Point(SimpleRepr):
    def __init__(self, x, y=0):
        self._x = x
        self._y = y

    def __eq__(self, other):
        return isinstance(other, Point) and \
            (self._x, self._y) == (other._x, other._y)


class Mapped(SimpleRepr):
    _repr_mapping = {"value": "stored"}

    def __init__(self, value):
        self._stored = value


class Nested(SimpleRepr):
    def __init__(self, points):
        self._points = list(points)


class NoAttr(SimpleRepr):
    def __init__(self, ghost):
        pass  # never stores ghost


class PublicAttr(SimpleRepr):
    def __init__(self, tag):
        self.tag = tag


class TestScalars:
    @pytest.mark.parametrize("value", [None, 0, 1, -3.5, True, False,
                                       "", "text"])
    def test_scalars_pass_through(self, value):
        assert simple_repr(value) == value
        assert from_repr(simple_repr(value)) == value

    def test_list_and_tuple_become_lists(self):
        assert simple_repr([1, 2]) == [1, 2]
        assert simple_repr((1, 2)) == [1, 2]

    def test_set_becomes_list(self):
        assert sorted(simple_repr({3, 1, 2})) == [1, 2, 3]

    def test_dict_values_recursed(self):
        r = simple_repr({"k": (1, 2)})
        assert r == {"k": [1, 2]}

    def test_unserializable_raises(self):
        with pytest.raises(SimpleReprException, match="no simple repr"):
            simple_repr(object())


class TestMixin:
    def test_roundtrip(self):
        p = Point(3, 4)
        r = simple_repr(p)
        assert r["__qualname__"] == "Point"
        assert r["x"] == 3 and r["y"] == 4
        assert from_repr(r) == p

    def test_private_attribute_lookup(self):
        # init arg x stored as _x: discovered automatically
        assert simple_repr(Point(1))["x"] == 1

    def test_public_attribute_lookup(self):
        assert simple_repr(PublicAttr("t"))["tag"] == "t"

    def test_default_used_when_attribute_missing(self):
        class Defaulted(SimpleRepr):
            def __init__(self, a, b=7):
                self._a = a  # b not stored

        assert simple_repr(Defaulted(1))["b"] == 7

    def test_missing_required_attribute_raises(self):
        with pytest.raises(SimpleReprException, match="ghost"):
            simple_repr(NoAttr(5))

    def test_repr_mapping(self):
        assert simple_repr(Mapped("v"))["value"] == "v"

    def test_nested_objects(self):
        n = Nested([Point(1, 2), Point(3)])
        n2 = from_repr(simple_repr(n))
        assert n2._points == [Point(1, 2), Point(3)]

    def test_json_round_trip(self):
        # The whole point of the wire format: JSON-safe.
        n = Nested([Point(1, 2)])
        wire = json.dumps(simple_repr(n))
        n2 = from_repr(json.loads(wire))
        assert n2._points == [Point(1, 2)]


class TestFromRepr:
    def test_plain_dict_without_marker_stays_dict(self):
        assert from_repr({"a": 1, "b": [2]}) == {"a": 1, "b": [2]}

    def test_unknown_module_raises(self):
        r = {"__module__": "no.such.module", "__qualname__": "X"}
        with pytest.raises(ModuleNotFoundError):
            from_repr(r)

    def test_unknown_class_raises(self):
        r = {"__module__": "builtins", "__qualname__": "NoSuchClass"}
        with pytest.raises(AttributeError):
            from_repr(r)

    def test_non_reprable_input_raises(self):
        with pytest.raises(SimpleReprException, match="Cannot rebuild"):
            from_repr(object())

"""gh_secp_cgdp: SECP-specialized greedy heuristic, constraint graph.

Reference parity: pydcop/distribution/gh_secp_cgdp.py.  SECP placement
preferences are expressed through hosting costs (device computations
have cost 0 on their own agent), so the generic greedy engine with a
strong hosting weight realizes the SECP policy.
"""

from pydcop_tpu.distribution._base import (
    distribution_cost_impl,
    greedy_place,
)


def distribute(computation_graph, agentsdef, hints=None,
               computation_memory=None, communication_load=None, **_):
    return greedy_place(
        computation_graph, agentsdef, hints,
        computation_memory, communication_load,
        order_key=lambda c, fp, nb: -fp[c],
        comm_weight=0.5,
        hosting_weight=1.0,
    )


def distribution_cost(distribution, computation_graph, agentsdef,
                      computation_memory=None, communication_load=None):
    return distribution_cost_impl(
        distribution, computation_graph, agentsdef,
        computation_memory, communication_load)

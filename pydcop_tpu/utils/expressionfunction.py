"""Compile python expression strings into callables — powers YAML
``intention:`` constraints.

Reference parity: pydcop/utils/expressionfunction.py:40 (``ExpressionFunction``:
AST variable-name scan :218, partial application, external source files).

Two forms are accepted (matching the DCOP YAML format spec,
docs/usage/file_formats/dcop_format.yml in the reference):

- a single python expression: ``"1 if v1 == v2 else 0"``;
- a function body containing ``return`` statements (multi-line YAML string),
  which is wrapped into a generated ``def``.

The names the function depends on are discovered by scanning the AST for
loaded-but-never-assigned names, excluding builtins and the modules made
available in the evaluation scope (``math``, ``random``, and — for external
source files — ``source``).
"""

import ast
import builtins
import importlib.util
import math
import random
import textwrap
from typing import Iterable, Optional

_SCOPE_MODULES = {"math": math, "random": random}


def _free_names(tree: ast.AST) -> list:
    loads, stores = [], set()
    nodes = sorted(
        (n for n in ast.walk(tree) if isinstance(n, ast.Name)),
        key=lambda n: (n.lineno, n.col_offset),
    )
    for node in nodes:
        if isinstance(node.ctx, ast.Load):
            if node.id not in loads:
                loads.append(node.id)
        else:
            stores.add(node.id)
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            if not isinstance(node, ast.Lambda):
                stores.add(node.name)
            for a in (node.args.args + node.args.kwonlyargs
                      + node.args.posonlyargs):
                stores.add(a.arg)
            if node.args.vararg:
                stores.add(node.args.vararg.arg)
            if node.args.kwarg:
                stores.add(node.args.kwarg.arg)
    reserved = set(dir(builtins)) | set(_SCOPE_MODULES) | {"source"}
    return [n for n in loads if n not in stores and n not in reserved]


def _load_source_module(path: str):
    spec = importlib.util.spec_from_file_location("_dcop_ext_source", path)
    module = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(module)
    return module


class ExpressionFunction:
    """A callable built from a python expression (or function-body) string.

    >>> f = ExpressionFunction("a + b * 2")
    >>> sorted(f.variable_names)
    ['a', 'b']
    >>> f(a=1, b=2)
    5
    >>> g = f.partial(b=3)
    >>> list(g.variable_names)
    ['a']
    >>> g(a=1)
    7
    """

    def __init__(
        self,
        expression: str,
        source_file: Optional[str] = None,
        **fixed_vars,
    ):
        self._expression = expression
        self._source_file = source_file
        self._fixed_vars = dict(fixed_vars)

        self._scope = dict(_SCOPE_MODULES)
        if source_file:
            self._scope["source"] = _load_source_module(source_file)

        stripped = textwrap.dedent(expression).strip()
        try:
            tree = ast.parse(stripped, mode="eval")
            self._is_body = False
        except SyntaxError:
            tree = ast.parse(
                "def __expr__():\n" + textwrap.indent(textwrap.dedent(expression), "    ")
            )
            self._is_body = True

        names = _free_names(tree)
        self._all_names = [n for n in names]
        self._variable_names = [n for n in names if n not in self._fixed_vars]

        if self._is_body:
            src = "def __expr__({}):\n{}".format(
                ", ".join(self._all_names),
                textwrap.indent(textwrap.dedent(expression), "    "),
            )
            g = dict(self._scope)
            g["__builtins__"] = builtins
            exec(compile(src, "<dcop_expression>", "exec"), g)
            self._func = g["__expr__"]
            self._code = None
        else:
            self._func = None
            self._code = compile(stripped, "<dcop_expression>", "eval")

    @property
    def expression(self) -> str:
        return self._expression

    @property
    def source_file(self) -> Optional[str]:
        return self._source_file

    @property
    def variable_names(self) -> Iterable[str]:
        """Names the function still depends on (fixed vars excluded)."""
        return list(self._variable_names)

    @property
    def fixed_vars(self) -> dict:
        return dict(self._fixed_vars)

    @property
    def __name__(self):
        return self._expression

    def __call__(self, *args, **kwargs):
        if args:
            kwargs.update(zip(self._variable_names, args))
        scope = dict(self._fixed_vars)
        scope.update(kwargs)
        if self._is_body:
            return self._func(**{n: scope[n] for n in self._all_names})
        g = dict(self._scope)
        g["__builtins__"] = builtins
        return eval(self._code, g, scope)

    def partial(self, **kwargs):
        fixed = dict(self._fixed_vars)
        fixed.update(kwargs)
        return ExpressionFunction(
            self._expression, source_file=self._source_file, **fixed
        )

    def __eq__(self, other):
        return (
            isinstance(other, ExpressionFunction)
            and self._expression == other._expression
            and self._fixed_vars == other._fixed_vars
        )

    def __hash__(self):
        return hash((self._expression, tuple(sorted(self._fixed_vars.items()))))

    def __repr__(self):
        return f"ExpressionFunction({self._expression!r})"

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "expression": self._expression,
            "source_file": self._source_file,
            "fixed_vars": dict(self._fixed_vars),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(
            r["expression"],
            source_file=r.get("source_file"),
            **r.get("fixed_vars", {}),
        )

"""Shard-loss recovery battery (ISSUE 8): a device lost mid-sharded-
solve trips the guarded segment, which rolls back to the last
validated snapshot, RE-PARTITIONS the factor graph onto the surviving
mesh, remaps the snapshot onto the new layout and resumes.

Asserted here:

- **repartition-recovery parity** (the acceptance criterion): a
  sharded solve with an injected shard trip finishes with the same
  assignment and cost as the untripped run — on integer cost tables
  the f32 message sums are exact, so parity is exact even though the
  surviving mesh reassociates reductions;
- a solve survives a SEQUENCE of losses (4 -> 3 -> 2 shards) and
  every loss is accounted (``repartitions``, ``lost_shards``,
  ``shard_recovery_s``, ``shard_losses``);
- shard losses do not consume the escalation-ladder restart budget
  (``recovery_attempts`` stays 0) — a numerics intervention makes no
  sense for a dead device;
- losing the LAST device raises :class:`RecoveryExhausted` carrying
  the partial trajectory (last validated snapshot's assignment);
- the guard trip and the repartition rollback are visible in the
  exported trace (``guard_trip`` kind=shard_loss,
  ``recovery_rollback`` action=repartition);
- the failure modes fail loudly: ``trip_shard`` on an engine without
  the repartition hook, malformed trip entries, out-of-range shard
  indices.

Runs on the repo-wide 8-virtual-device CPU platform (root
conftest.py).
"""

import numpy as np
import pytest

import jax

from pydcop_tpu.algorithms.maxsum import build_engine
from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.resilience.recovery import (
    NoSurvivingDevices,
    RecoveryExhausted,
    RecoveryPolicy,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)

MAX_CYCLES = 60
SEGMENT = 10


def _loopy_dcop(n_vars=24, n_edges=36, d=3, seed=0) -> DCOP:
    """Random loopy binary DCOP with INTEGER tables: f32 sums of
    integer costs are exact, so tripped-vs-untripped parity is
    bit-exact despite the repartition's reduction reorder."""
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("loopy", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    seen = set()
    k = 0
    while k < n_edges:
        i, j = rng.choice(n_vars, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        m = rng.integers(0, 10, size=(d, d))
        dcop.add_constraint(
            NAryMatrixRelation([vs[key[0]], vs[key[1]]], m,
                               name=f"c{k}"))
        k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _run(dcop, shards, recovery=None):
    return build_engine(dcop, {}, shards=shards).run_checkpointed(
        max_cycles=MAX_CYCLES, segment_cycles=SEGMENT,
        recovery=recovery)


class TestShardTripParity:
    def test_single_trip_same_assignment_and_cost(self):
        dcop = _loopy_dcop()
        ref = _run(dcop, shards=4)
        res = _run(dcop, shards=4,
                   recovery=RecoveryPolicy(trip_shard=((20, 1),)))
        assert res.assignment == ref.assignment, \
            "repartitioned recovery diverged from the untripped run"
        m = res.metrics
        assert m["shard_losses"] == 1
        assert m["repartitions"] == 1
        assert m["lost_shards"] == [1]
        assert m["shard_recovery_s"] > 0
        assert m["n_shards"] == 3, "metrics must reflect the final mesh"
        assert m["guard_violations"][0]["kind"] == "shard_loss"
        assert m["guard_violations"][0]["shard"] == 1

    def test_trip_does_not_consume_restart_budget(self):
        """max_restarts=0 would exhaust on the FIRST ladder trip;
        a shard loss must sail through it untouched."""
        dcop = _loopy_dcop(seed=1)
        res = _run(dcop, shards=4, recovery=RecoveryPolicy(
            max_restarts=0, trip_shard=((20, 2),)))
        assert res.metrics["shard_losses"] == 1
        assert res.metrics["recovery_attempts"] == 0
        assert res.metrics["recovery_actions"] == ["repartition"]

    def test_loss_sequence_survives_and_accounts(self):
        """4 -> 3 -> 2 shards: the second trip's shard index applies
        to the ALREADY-SHRUNK mesh; parity still holds."""
        dcop = _loopy_dcop(seed=2)
        ref = _run(dcop, shards=4)
        res = _run(dcop, shards=4, recovery=RecoveryPolicy(
            trip_shard=((10, 3), (30, 0))))
        assert res.assignment == ref.assignment
        m = res.metrics
        assert m["shard_losses"] == 2
        assert m["repartitions"] == 2
        assert m["lost_shards"] == [3, 0]
        assert m["n_shards"] == 2

    def test_cost_parity_via_api_solve(self):
        """The same path through api.solve(shards=..., recovery=...):
        identical cost and assignment to the untripped solve."""
        dcop = _loopy_dcop(seed=3)
        ref = solve(dcop, "maxsum", max_cycles=MAX_CYCLES, shards=2)
        res = solve(dcop, "maxsum", max_cycles=MAX_CYCLES, shards=2,
                    recovery=RecoveryPolicy(trip_shard=((15, 0),)))
        assert res["assignment"] == ref["assignment"]
        assert res["cost"] == ref["cost"]
        assert res["metrics"]["shard_losses"] == 1


class TestShardTripTrace:
    def test_trip_and_repartition_visible_in_trace(self, tmp_path):
        from pydcop_tpu.observability.trace import (
            load_trace_file,
            tracer,
        )

        trace_path = str(tmp_path / "shardloss.trace.json")
        tracer.enable()
        try:
            _run(_loopy_dcop(seed=4), shards=4,
                 recovery=RecoveryPolicy(trip_shard=((20, 1),)))
        finally:
            tracer.disable()
            tracer.export(trace_path, "chrome")
        events = load_trace_file(trace_path)
        trips = [e for e in events if e["name"] == "guard_trip"]
        assert any(e["args"].get("kind") == "shard_loss"
                   and e["args"].get("shard") == 1 for e in trips)
        rollbacks = [e for e in events
                     if e["name"] == "recovery_rollback"]
        assert any(e["args"].get("action") == "repartition"
                   and e["args"].get("lost_shard") == 1
                   for e in rollbacks)


class TestShardTripExhaustion:
    def test_last_device_loss_exhausts_with_partial(self):
        """2 -> 1 -> nothing: the second loss leaves an empty mesh;
        RecoveryExhausted must carry the last snapshot's partial
        trajectory instead of crashing bare."""
        dcop = _loopy_dcop(seed=5)
        with pytest.raises(RecoveryExhausted) as err:
            _run(dcop, shards=2, recovery=RecoveryPolicy(
                trip_shard=((10, 1), (11, 0))))
        exc = err.value
        assert "no surviving devices" in str(exc)
        assert exc.partial["assignment"] is not None
        assert set(exc.partial["assignment"]) == \
            {f"v{i}" for i in range(24)}
        assert [v.kind for v in exc.violations] == \
            ["shard_loss", "shard_loss"]
        assert isinstance(exc.__cause__, NoSurvivingDevices)

    def test_unsharded_engine_rejects_trip_shard(self):
        """trip_shard needs the repartition hook: a single-device
        engine must fail loudly, not ignore the injection."""
        dcop = _loopy_dcop(seed=6)
        with pytest.raises(ValueError, match="repartition_after_loss"):
            build_engine(dcop, {}).run_checkpointed(
                max_cycles=MAX_CYCLES, segment_cycles=SEGMENT,
                recovery=RecoveryPolicy(trip_shard=((10, 0),)))

    def test_out_of_range_shard_rejected(self):
        dcop = _loopy_dcop(seed=7)
        with pytest.raises(ValueError, match="out of range"):
            _run(dcop, shards=2,
                 recovery=RecoveryPolicy(trip_shard=((10, 5),)))

    def test_malformed_trip_entry_rejected_at_policy(self):
        with pytest.raises(ValueError, match="cycle, shard"):
            RecoveryPolicy(trip_shard=((10,),))


class TestRepartitionStateRemap:
    def test_remap_preserves_messages_exactly(self):
        """The remap is a pure relabeling: gathering the remapped
        state back to global real-factor row order must reproduce the
        original snapshot's messages bit-for-bit (only the halo is
        recomputed, against the new layout's boundary set)."""
        from pydcop_tpu.engine.partition import partition_compiled
        from pydcop_tpu.engine.runner import ShardedMaxSumEngine

        dcop = _loopy_dcop(seed=8)
        engine = build_engine(dcop, {}, shards=4)
        assert isinstance(engine, ShardedMaxSumEngine)
        # Run a few cycles so messages are non-trivial.
        engine.run(max_cycles=8)
        state = engine.init_state()
        (state, _), _, _ = engine._call(
            engine._segment_key(8, False),
            engine._segment_fn(8, False), engine.graph, state)
        snap = jax.tree_util.tree_map(lambda x: x, state)
        new_state = engine.repartition_after_loss(2, snap)
        assert engine.mesh.size == 3
        assert engine.partition.n_shards == 3
        # Every bucket's per-factor message rows survive the
        # relabeling: compare global gatherings old vs new.
        old_part = partition_compiled(engine._source_graph, 4)
        from pydcop_tpu.engine.sharding import _factor_row_maps

        old_maps = _factor_row_maps(engine._source_graph, old_part)
        new_maps = _factor_row_maps(engine._source_graph,
                                    engine.partition)

        def gather(blocked, maps, i):
            blocked = np.asarray(jax.device_get(blocked))
            rows, per_shard = maps[i]
            out = np.zeros((rows.shape[0],) + blocked.shape[2:],
                           blocked.dtype)
            for s, sel in enumerate(per_shard):
                out[sel] = blocked[s, :sel.shape[0]]
            return out

        for i in range(len(engine._source_graph.buckets)):
            np.testing.assert_array_equal(
                gather(snap.f2v[i], old_maps, i),
                gather(new_state.f2v[i], new_maps, i),
                err_msg=f"f2v bucket {i} corrupted by remap")
            np.testing.assert_array_equal(
                gather(snap.v2f[i], old_maps, i),
                gather(new_state.v2f[i], new_maps, i),
                err_msg=f"v2f bucket {i} corrupted by remap")
        assert int(new_state.cycle) == int(snap.cycle)

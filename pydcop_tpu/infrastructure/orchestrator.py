"""The orchestrator: central bootstrap, monitoring and control.

Reference parity: pydcop/infrastructure/orchestrator.py (Orchestrator
:62 — own agent + directory :128, deploy_computations :203, run :245,
stop_agents :291, wait_ready :318; AgentsMgt :535 — metrics aggregation
:802-900, global_metrics :1215).
"""

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from pydcop_tpu.algorithms import AlgorithmDef, ComputationDef
from pydcop_tpu.computations_graph.objects import ComputationGraph
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.infrastructure.agents import Agent
from pydcop_tpu.infrastructure.communication import (
    CommunicationLayer,
    MSG_MGT,
)
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
    register,
)
from pydcop_tpu.infrastructure.discovery import Directory
from pydcop_tpu.infrastructure.orchestratedagents import (
    AgentReadyMessage,
    AgentStoppedMessage,
    ComputationFinishedMessage,
    CycleChangeMessage,
    DeployMessage,
    ORCHESTRATOR_AGENT,
    ORCHESTRATOR_MGT,
    PauseMessage,
    ResumeMessage,
    RunAgentMessage,
    StopAgentMessage,
    ValueChangeMessage,
)

logger = logging.getLogger("pydcop.orchestrator")


class AgentsMgt(MessagePassingComputation):
    """Orchestrator-side management computation: aggregates value/cycle
    reports into a global view, tracks completion."""

    def __init__(self, orchestrator: "Orchestrator"):
        super().__init__(ORCHESTRATOR_MGT)
        self.orchestrator = orchestrator
        self.assignment: Dict[str, Any] = {}
        self.cycles: Dict[str, int] = {}
        self.agent_metrics: Dict[str, Dict] = {}
        self.finished_computations: set = set()
        self.ready_agents: set = set()
        self.start_time: Optional[float] = None
        self.last_stop_time: Optional[float] = None

    @register("agent_ready")
    def _on_agent_ready(self, sender, msg, t):
        self.ready_agents.add(msg.agent)
        self.orchestrator._ready_evt.set()

    @register("value_change")
    def _on_value_change(self, sender, msg, t):
        self.assignment[msg.computation] = msg.value
        self.cycles[msg.computation] = max(
            self.cycles.get(msg.computation, 0), msg.cycle
        )
        self.orchestrator._on_progress()

    @register("cycle_change")
    def _on_cycle_change(self, sender, msg, t):
        self.cycles[msg.computation] = max(
            self.cycles.get(msg.computation, 0), msg.cycle
        )

    @register("computation_finished")
    def _on_comp_finished(self, sender, msg, t):
        self.finished_computations.add(msg.computation)
        self.orchestrator._check_all_finished()

    @register("agent_stopped")
    def _on_agent_stopped(self, sender, msg, t):
        self.agent_metrics[msg.agent] = msg.metrics
        self.last_stop_time = time.monotonic()
        self.orchestrator._on_agent_stopped(msg.agent)

    def global_metrics(self, status: str) -> Dict:
        """Reference-shaped result dict (orchestrator.py:1215-1274)."""
        dcop = self.orchestrator.dcop
        dcop_assignment = {
            k: v for k, v in self.assignment.items()
            if k in dcop.variables
        }
        try:
            cost, violation = dcop.solution_cost(
                dcop_assignment, self.orchestrator.infinity
            )
        except ValueError:
            cost, violation = None, None
        msg_count, msg_size = 0, 0
        for metrics in self.agent_metrics.values():
            msg_count += sum(metrics.get("count_ext_msg", {}).values())
            msg_size += sum(metrics.get("size_ext_msg", {}).values())
        total_time = (
            time.monotonic() - self.start_time
            if self.start_time else 0
        )
        return {
            "status": status,
            "assignment": self.assignment,
            "cost": cost,
            "violation": violation,
            "time": total_time,
            "msg_count": msg_count,
            "msg_size": msg_size,
            "cycle": max(self.cycles.values(), default=0),
            "agt_metrics": self.agent_metrics,
        }


class Orchestrator:
    """Bootstraps a distributed run: deploys computations onto agents,
    starts them, monitors progress and stops everything."""

    def __init__(self, algo: AlgorithmDef,
                 cg: ComputationGraph,
                 agent_mapping: Distribution,
                 comm: CommunicationLayer,
                 dcop: DCOP,
                 infinity: float = float("inf"),
                 collector=None,
                 collect_moment: str = "value_change"):
        self.algo = algo
        self.cg = cg
        self.distribution = agent_mapping
        self.dcop = dcop
        self.infinity = infinity
        self.status = "INIT"

        self._agent = Agent(ORCHESTRATOR_AGENT, comm)
        self.directory = Directory(self._agent.discovery)
        self._agent.add_computation(self.directory.directory_computation)
        self._agent.discovery.use_directory(
            ORCHESTRATOR_AGENT, comm.address
        )
        self.mgt = AgentsMgt(self)
        self._agent.add_computation(self.mgt)

        # External (read-only/sensor) variables are published by
        # computations hosted on the orchestrator's agent: dynamic
        # factors subscribe to them by name and receive value changes
        # (reference computations.py:1093 ExternalVariableComputation).
        self._external_computations = []
        for ev in dcop.external_variables.values():
            from pydcop_tpu.infrastructure.computations import (
                ExternalVariableComputation,
            )

            comp = ExternalVariableComputation(ev)
            self._agent.add_computation(comp)
            self._external_computations.append(comp)

        self._ready_evt = threading.Event()
        self._finished_evt = threading.Event()
        self._stopped_agents: set = set()
        self._all_stopped_evt = threading.Event()
        self._expected_computations = [
            n.name for n in cg.nodes
        ]

    @property
    def address(self):
        return self._agent.address

    # -- lifecycle ----------------------------------------------------- #

    def start(self):
        self._agent.start()
        self.directory.directory_computation.start()
        self.mgt.start()
        for comp in self._external_computations:
            comp.start()

    def stop(self):
        self._agent.clean_shutdown()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Wait until every agent of the distribution has reported in."""
        expected = {
            a for a in self.distribution.agents
            if self.distribution.computations_hosted(a)
        }
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while not expected <= self.mgt.ready_agents:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
            self._ready_evt.clear()
            self._ready_evt.wait(
                min(0.1, remaining) if remaining else 0.1
            )
        return True

    def deploy_computations(self):
        """Send each computation's definition to its hosting agent
        (reference :203 → DeployMessage per computation :1197-1209)."""
        for comp_name in self._expected_computations:
            agent = self.distribution.agent_for(comp_name)
            node = self.cg.computation(comp_name)
            comp_def = ComputationDef(node, self.algo)
            self.mgt.post_msg(
                f"_mgt_{agent}", DeployMessage(comp_def), MSG_MGT
            )

    def run(self, scenario=None, timeout: Optional[float] = None):
        """Start all computations; block until finished or timeout."""
        self.status = "RUNNING"
        self.mgt.start_time = time.monotonic()
        for agent in self.distribution.agents:
            if self.distribution.computations_hosted(agent):
                self.mgt.post_msg(
                    f"_mgt_{agent}", RunAgentMessage([]), MSG_MGT
                )
        if scenario is not None:
            self._run_scenario(scenario)
        finished = self._finished_evt.wait(timeout)
        if finished:
            self.status = "FINISHED"
        else:
            self.status = "TIMEOUT"

    def _run_scenario(self, scenario):
        from pydcop_tpu.infrastructure.events_handler import (
            run_scenario_events,
        )

        threading.Thread(
            target=run_scenario_events, args=(self, scenario),
            daemon=True, name="scenario",
        ).start()

    def remove_agent(self, agent: str):
        """Scenario-driven agent removal: stop the agent; its orphaned
        computations are tracked (repair-based migration arrives with
        the replication layer)."""
        orphaned = self.distribution.computations_hosted(agent)
        logger.warning(
            "Agent %s removed; orphaned computations: %s", agent, orphaned
        )
        self.mgt.post_msg(f"_mgt_{agent}", StopAgentMessage(), MSG_MGT)

    def pause_agents(self):
        for agent in self.distribution.agents:
            self.mgt.post_msg(f"_mgt_{agent}", PauseMessage([]), MSG_MGT)

    def resume_agents(self):
        for agent in self.distribution.agents:
            self.mgt.post_msg(f"_mgt_{agent}", ResumeMessage([]), MSG_MGT)

    def stop_agents(self, timeout: float = 5):
        for agent in self.distribution.agents:
            if self.distribution.computations_hosted(agent):
                self.mgt.post_msg(
                    f"_mgt_{agent}", StopAgentMessage(), MSG_MGT
                )
        self._all_stopped_evt.wait(timeout)

    # -- callbacks from mgt -------------------------------------------- #

    def _on_progress(self):
        pass

    def _check_all_finished(self):
        if set(self._expected_computations) <= \
                self.mgt.finished_computations:
            self._finished_evt.set()

    def _on_agent_stopped(self, agent: str):
        self._stopped_agents.add(agent)
        expected = {
            a for a in self.distribution.agents
            if self.distribution.computations_hosted(a)
        }
        if expected <= self._stopped_agents:
            self._all_stopped_evt.set()

    # -- results ------------------------------------------------------- #

    def current_global_cost(self):
        metrics = self.mgt.global_metrics(self.status)
        return metrics["cost"], metrics["violation"]

    def end_metrics(self) -> Dict:
        return self.mgt.global_metrics(self.status)

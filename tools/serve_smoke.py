"""Serve-smoke gate: end-to-end proof of the solve service's batching.

Part of ``make test`` (like ``make trace-demo`` / ``make perf-smoke``).
Starts the real service on port 0 and drives it over HTTP:

1. **Coalescing + parity**: a concurrent burst of N same-structure
   requests (plus a second structure mixed in) must complete in FEWER
   than N device dispatches (batch-coalescing counters asserted), at
   least one dispatch must be multi-instance, the two structures must
   never share a dispatch (dispatch count >= 2), and EVERY response's
   assignment must equal the equivalent solo ``api.solve`` run.
2. **Overload**: with a tiny high-water mark and a slowed dispatch,
   a burst past the queue bound must yield 429s — not a hang and not
   a dropped request: every accepted request finishes, every rejected
   one is a clean 429, and ``pydcop_requests_total{status}`` accounts
   for every single request fired.
3. **kill -9 + journal replay** (ISSUE 8 acceptance): a REAL
   ``pydcop serve --journal_dir D`` subprocess is SIGKILLed mid-burst;
   every acknowledged (202) request must have its accepted record on
   disk, and a ``--recover`` start must replay every
   accepted-but-unfinished one to completion — zero acknowledged
   requests lost.
4. **SIGTERM drain** (ISSUE 8 satellite): an orchestrated-restart
   signal makes the serve process drain and exit 0, logging the
   drained/replayable counts — accepted work is never silently
   dropped.
5. **Session kill -9 + whole-session replay** (ISSUE 13
   acceptance): a stateful session is opened over HTTP, 3 event
   batches are acked, the process is SIGKILLed; every acked record
   must be on disk and a ``--recover`` start must resume the
   session, apply the journaled-but-unapplied batches, and close
   with exactly the uninterrupted run's final cost — zero acked
   events lost.
6. **Request-scoped tracing** (ISSUE 9 acceptance): a real-HTTP
   batched burst is traced; ``pydcop trace query --request ID`` (the
   REAL CLI, on the exported trace) must return a single well-nested
   tree holding the submit, queue, ``serve_dispatch`` and
   ``engine_segment`` spans all tagged with that request's trace_id —
   and the p99 bucket of ``pydcop_request_latency_seconds`` must
   expose an exemplar trace_id resolvable by the same query.
7. **Efficiency accounting** (ISSUE 14 acceptance): on a real serve
   burst every served request carries a time ledger whose components
   sum to its measured total latency within 5%, and the
   ``useful_work_fraction`` + attainment rollups are visible in
   ``/stats``, ``/metrics`` (backend-labeled), ``/profile`` and
   ``pydcop profile report --url`` (the real CLI).
8. **2-replica fleet burst** (ISSUE 15 acceptance): a mixed-structure
   burst against a real 2-worker fleet behind the structure-affinity
   router answers every request bit-identical to solo ``api.solve``,
   with affinity accounting on /stats and a clean whole-fleet drain.
9. **Elastic-fleet migration** (ISSUE 16 acceptance): an operator
   ``POST /admin/migrate`` moves a warm session between replicas of a
   host-striped fleet with zero acked events lost — the router pin
   follows the session and the fairness/migration control surfaces
   are live on /stats.
10. **Pipelined flushes + speculative compiles** (ISSUE 18
    acceptance): a real-HTTP mixed burst served with pipelining and
    speculation ON answers bit-identical to solo ``api.solve``;
    ``/stats`` shows ``speculative_compiles_total`` with >= 1 hit
    and >= 2 pipelined dispatches, and the ``/profile`` compile
    waste share is lower than the same workload with both OFF.
11. **Exact-inference tier** (ISSUE 17 acceptance): a request with
    ``params.algo="dpop"`` answers with ``optimal: true`` and the
    assignment the solo exact solve produces, while a problem whose
    UTIL hypercube exceeds the element cap gets a structured 400
    (``status: rejected_width``) — never a 500, and the service
    keeps serving iterative traffic afterwards.

Run:  python tools/serve_smoke.py      (exit 0 = all claims hold)
"""

import json
import os
import re
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
import urllib.error
import urllib.request

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import numpy as np  # noqa: E402

SAME_STRUCTURE_BURST = 8
OTHER_STRUCTURE_BURST = 3
MAX_CYCLES = 120
OVERLOAD_BURST = 10


def build_instance(n_vars: int, seed: int):
    """Small random-cost ring coloring; same ``n_vars`` -> same
    structure bin, different seeds -> different cost tables."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("colors", "", [0, 1, 2])
    dcop = DCOP(f"smoke_{n_vars}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    for k, (i, j) in enumerate(
            [(i, (i + 1) % n_vars) for i in range(n_vars)]):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def post(url: str, body: dict):
    req = urllib.request.Request(
        url + "/solve", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=120) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as err:
        return err.code, json.loads(err.read())


def scrape_requests_total(url: str) -> dict:
    with urllib.request.urlopen(url + "/metrics", timeout=30) as resp:
        text = resp.read().decode()
    out = {}
    for line in text.splitlines():
        m = re.match(
            r'pydcop_requests_total\{status="([^"]+)"\} (\S+)', line)
        if m:
            out[m.group(1)] = float(m.group(2))
    return out


def check(cond, message):
    if not cond:
        print(f"serve_smoke: FAIL — {message}", file=sys.stderr)
        sys.exit(1)
    print(f"serve_smoke: ok — {message}")


def leg_coalescing():
    from pydcop_tpu import api

    handle = api.serve(port=0, batch_window_s=0.3, max_batch=16,
                       max_queue=64)
    try:
        url = handle.url
        dcops = (
            [build_instance(12, seed)
             for seed in range(SAME_STRUCTURE_BURST)]
            + [build_instance(9, 100 + seed)
               for seed in range(OTHER_STRUCTURE_BURST)]
        )
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        payloads = [dcop_yaml(d) for d in dcops]
        results = [None] * len(dcops)

        def client(i):
            results[i] = post(url, {
                "dcop": payloads[i], "wait": True, "timeout": 120,
                "params": {"max_cycles": MAX_CYCLES},
            })

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(dcops))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        check(all(r is not None and r[0] == 200
                  and r[1]["status"] == "FINISHED" for r in results),
              f"all {len(dcops)} burst responses valid")

        stats = handle.service.stats()
        n = len(dcops)
        check(stats["dispatches"] < n,
              f"{n} requests took {stats['dispatches']} device "
              f"dispatches (< {n}: batching coalesced)")
        check(stats["batched_dispatches"] >= 1,
              ">= 1 multi-instance batch dispatched "
              f"({stats['batched_dispatches']})")
        check(stats["dispatches"] >= 2,
              "two structures dispatched separately "
              f"({stats['dispatches']} dispatches)")

        # Every response must match the equivalent solo api.solve.
        for dcop, (_, res) in zip(dcops, results):
            solo = api.solve(dcop, "maxsum", backend="device",
                             max_cycles=MAX_CYCLES)
            if res["assignment"] != solo["assignment"]:
                check(False,
                      f"served assignment for {dcop.name} differs "
                      "from solo api.solve")
        check(True,
              f"all {len(dcops)} served assignments identical to "
              "solo api.solve")
    finally:
        handle.stop()


def leg_mixed_envelope():
    """ISSUE 11 acceptance: a concurrent burst of DISTINCT-structure
    requests — one per topology, so pure structure binning would
    dispatch every one solo — must coalesce below one dispatch per
    request via the envelope tier, with every response bit-identical
    to the solo ``api.solve`` answer (masking proven end-to-end over
    real HTTP, not assumed)."""
    from pydcop_tpu import api

    handle = api.serve(port=0, batch_window_s=0.3, max_batch=16,
                       max_queue=64)
    try:
        url = handle.url
        # Five distinct topologies (different variable counts -> five
        # different structure signatures), ONE request each: zero
        # same-structure coalescing is possible.
        dcops = [build_instance(n, 40 + n)
                 for n in (9, 12, 15, 18, 21)]
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        payloads = [dcop_yaml(d) for d in dcops]
        results = [None] * len(dcops)

        def client(i):
            results[i] = post(url, {
                "dcop": payloads[i], "wait": True, "timeout": 120,
                "params": {"max_cycles": MAX_CYCLES},
            })

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(dcops))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        check(all(r is not None and r[0] == 200
                  and r[1]["status"] == "FINISHED" for r in results),
              f"all {len(dcops)} mixed-structure responses valid")

        stats = handle.service.stats()
        n = len(dcops)
        check(stats["dispatches"] < n,
              f"{n} distinct-structure requests took "
              f"{stats['dispatches']} dispatches (< {n}: envelope "
              "packing coalesced structures)")
        check(stats["envelope_dispatches"] >= 1,
              ">= 1 envelope-packed dispatch "
              f"({stats['envelope_dispatches']}, lane "
              f"{stats['lane_dispatches']})")
        decisions = stats["envelope_decisions"]
        check(any(d.get("packed") for d in decisions),
              "pack-vs-solo cost decision recorded and packed "
              f"({decisions[-1] if decisions else None})")
        packed_responses = [
            r[1] for r in results
            if r[1].get("batch", {}).get("packing") in ("envelope",
                                                        "lane")]
        check(len(packed_responses) >= 2,
              f"{len(packed_responses)} responses carry packed-"
              "dispatch accounting (packing/envelope_waste keys)")

        # THE acceptance bar: every envelope-packed response equals
        # the solo api.solve answer bit for bit.
        for dcop, (_, res) in zip(dcops, results):
            solo = api.solve(dcop, "maxsum", backend="device",
                             max_cycles=MAX_CYCLES)
            if res["assignment"] != solo["assignment"]:
                check(False,
                      f"mixed-burst assignment for {dcop.name} "
                      "differs from solo api.solve")
            if res["cost"] != solo["cost"]:
                check(False,
                      f"mixed-burst cost for {dcop.name} differs "
                      "from solo api.solve")
        check(True,
              f"all {len(dcops)} mixed-burst answers bit-identical "
              "to solo api.solve")
    finally:
        handle.stop()


def leg_efficiency():
    """ISSUE 14 acceptance: on a real serve burst, every served
    request carries a time ledger whose components sum to its
    measured total latency within 5%, and the
    ``useful_work_fraction`` + attainment rollups are visible on all
    four surfaces — ``/stats``, ``/metrics`` (backend-labeled),
    ``/profile`` and ``pydcop profile report --url`` (the real CLI
    entry point)."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.observability.efficiency import (
        ledger_component_sum,
    )

    handle = api.serve(port=0, batch_window_s=0.2, max_batch=16,
                       max_queue=64)
    try:
        url = handle.url
        payloads = [dcop_yaml(build_instance(7, 70 + s))
                    for s in range(4)]
        payloads.append(dcop_yaml(build_instance(11, 90)))

        def burst():
            results = [None] * len(payloads)

            def client(i):
                results[i] = post(url, {
                    "dcop": payloads[i], "wait": True,
                    "timeout": 120,
                    "params": {"max_cycles": MAX_CYCLES},
                })

            threads = [threading.Thread(target=client, args=(i,))
                       for i in range(len(payloads))]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            return results

        burst()            # cold round: compiles + cost captures
        results = burst()  # warm round: the attainment evidence
        check(all(r is not None and r[0] == 200
                  and r[1]["status"] == "FINISHED" for r in results),
              f"all {len(payloads)} efficiency-burst responses "
              "finished")

        # 1. Every served request carries a summing time ledger.
        for _, res in results:
            ledger = res.get("ledger")
            check(isinstance(ledger, dict) and "total_s" in ledger,
                  f"response {res['id']} carries a time ledger")
            total = ledger["total_s"]
            gap = abs(ledger_component_sum(ledger) - total)
            check(total > 0 and gap <= 0.05 * total,
                  f"{res['id']} ledger components sum to the "
                  f"measured total within 5% (gap {gap * 1e3:.3f}ms "
                  f"of {total * 1e3:.1f}ms)")

        # 2. /stats carries the efficiency block with a real number.
        with urllib.request.urlopen(url + "/stats",
                                    timeout=30) as resp:
            stats = json.loads(resp.read())
        eff = stats.get("efficiency") or {}
        check(eff.get("backend") == "cpu",
              f"/stats efficiency block names the resolved backend "
              f"({eff.get('backend')})")
        check(eff.get("useful_work_fraction") is not None
              and 0 < eff["useful_work_fraction"] <= 1.0
              and eff.get("attainment") is not None,
              "/stats useful_work_fraction "
              f"({eff.get('useful_work_fraction')}) and attainment "
              f"({eff.get('attainment')}) populated after the warm "
              "round")
        check(eff.get("ledger_components_s", {}).get("execute", 0)
              > 0,
              "/stats ledger breakdown has device execute seconds")

        # 3. /metrics: backend-labeled gauges in the exposition.
        with urllib.request.urlopen(url + "/metrics",
                                    timeout=30) as resp:
            text = resp.read().decode()
        check(re.search(
            r'pydcop_useful_work_fraction\{backend="cpu"\} \S+',
            text) is not None,
            "backend-labeled pydcop_useful_work_fraction exported "
            "on /metrics")
        check(re.search(
            r'pydcop_device_execute_seconds_total\{backend="cpu"',
            text) is not None,
            "backend-labeled device-execute seconds exported on "
            "/metrics")

        # 4. /profile serves the live rollup.
        with urllib.request.urlopen(url + "/profile",
                                    timeout=30) as resp:
            profile = json.loads(resp.read())
        check(profile.get("backend", {}).get("backend") == "cpu"
              and profile.get("structures")
              and profile.get("waste_by_cause") is not None,
              "/profile serves the rollup (backend + structures + "
              "waste taxonomy)")
        cpu = profile.get("backends", {}).get("cpu") or {}
        check(cpu.get("useful_work_fraction") is not None,
              "/profile per-backend useful_work_fraction "
              f"({cpu.get('useful_work_fraction')})")

        # 5. The REAL CLI: pydcop profile report --url --json.
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "profile",
             "report", "--url", url, "--json"],
            capture_output=True, text=True, timeout=120,
            cwd=REPO)
        check(proc.returncode == 0,
              f"pydcop profile report --url exits 0 "
              f"({proc.stderr.strip()[:200]})")
        doc = json.loads(proc.stdout)
        live = doc.get("live") or {}
        check(live.get("ledger", {}).get("components_s")
              and live.get("backends", {}).get("cpu", {})
              .get("useful_work_fraction") is not None,
              "profile report --json carries the ledger breakdown "
              "and the cpu useful_work_fraction")
    finally:
        handle.stop()


def leg_pipelined_speculation():
    """ISSUE 18 acceptance: a real-HTTP mixed burst served with
    pipelining + speculation ON answers bit-identical to solo
    ``api.solve``; ``/stats`` shows ``speculative_compiles_total``
    with >= 1 hit and >= 1 pipelined dispatch; and the ``/profile``
    compile waste share is LOWER than an identical workload served
    with both knobs OFF (the speculated program lands in the
    persistent AOT cache, so the first real dispatch retrieves
    instead of building)."""
    import tempfile as _tempfile

    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine import batch as engine_batch
    from pydcop_tpu.engine.compile import compile_dcop
    from pydcop_tpu.observability import efficiency
    from pydcop_tpu.serving import binning

    def get_json(url, route):
        with urllib.request.urlopen(url + route, timeout=30) as r:
            return json.loads(r.read())

    def expected_key(dcop):
        graph, _ = compile_dcop(dcop)
        p = binning.normalize_params({"max_cycles": MAX_CYCLES})
        prep = engine_batch._prepare_stacked(
            [graph, graph], p["max_cycles"], p["damping"],
            p["damping_nodes"], p["stability"],
            (1, 2, 4, 8, 16), False, None)
        return str(prep.key)

    def run(on: bool, ns, cache_dir):
        # The comparison runs share one process, so each side gets
        # structures of its OWN sizes — a structure the other side
        # already compiled would serve from the warm jit cache and
        # hide the compile cost this leg exists to compare.
        efficiency.tracker.clear()
        handle = api.serve(
            port=0, batch_window_s=0.25, max_batch=16, max_queue=64,
            pipeline=on, speculate=on, compile_cache_dir=cache_dir)
        pairs = []
        try:
            url = handle.url
            for n in ns:
                # Two sequential solos seed the structure (and, ON,
                # the speculator's arrival histogram).
                for seed in (n * 10, n * 10 + 1):
                    d = build_instance(n, seed)
                    code, res = post(url, {
                        "dcop": dcop_yaml(d), "wait": True,
                        "timeout": 120,
                        "params": {"max_cycles": MAX_CYCLES}})
                    check(code == 200
                          and res["status"] == "FINISHED",
                          f"solo n={n} seed={seed} served "
                          f"(speculation={'on' if on else 'off'})")
                    pairs.append((d, res))
            if on:
                # Wait for the bin-of-2 programs the structures'
                # traffic predicts to land in the AOT cache, then
                # for the speculator to go quiet — on a small box
                # the background builds contend with live compiles
                # for cores, and the measured window below must see
                # only serving work.
                spec = handle.service._speculator
                deadline = time.time() + 120
                for n in ns:
                    want = expected_key(build_instance(n, n * 10))
                    while (time.time() < deadline
                           and want not in spec.compiled_keys):
                        time.sleep(0.1)
                    check(want in spec.compiled_keys,
                          f"speculative bin-of-2 build for n={n} "
                          f"landed ({spec.stats()})")
                while (time.time() < deadline
                       and spec.stats()["queued"] > 0):
                    time.sleep(0.1)
                time.sleep(0.5)
            # The measured serving window: the profile compared
            # below covers ONLY the traffic from here on, the same
            # window on both sides (the seeding solos above pay
            # first-arrival compiles no speculation can predict).
            efficiency.tracker.clear()
            for n in ns:
                # The predicted bin-of-2 arrives — cold in the jit
                # cache; ON, its executable comes off the disk.
                burst = [build_instance(n, n * 10 + s)
                         for s in (2, 3)]
                res2 = [None] * 2

                def client(i, d=None):
                    res2[i] = post(url, {
                        "dcop": dcop_yaml(d), "wait": True,
                        "timeout": 120,
                        "params": {"max_cycles": MAX_CYCLES}})

                threads = [threading.Thread(target=client,
                                            args=(i,),
                                            kwargs={"d": d})
                           for i, d in enumerate(burst)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join(timeout=180)
                check(all(r is not None and r[0] == 200
                          and r[1]["status"] == "FINISHED"
                          for r in res2),
                      f"bin-of-2 burst for n={n} served")
                pairs.extend(
                    (d, r[1]) for d, r in zip(burst, res2))
            # Final mixed burst: both structures warm at bin 2 —
            # the flush the pipelined scheduler overlaps.
            mixed = [build_instance(n, n * 10 + s)
                     for n in ns for s in (4, 5)]
            resm = [None] * len(mixed)

            def mclient(i, d=None):
                resm[i] = post(url, {
                    "dcop": dcop_yaml(d), "wait": True,
                    "timeout": 120,
                    "params": {"max_cycles": MAX_CYCLES}})

            threads = [threading.Thread(target=mclient, args=(i,),
                                        kwargs={"d": d})
                       for i, d in enumerate(mixed)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=180)
            check(all(r is not None and r[0] == 200
                      and r[1]["status"] == "FINISHED"
                      for r in resm),
                  f"mixed {len(mixed)}-request burst served")
            pairs.extend((d, r[1]) for d, r in zip(mixed, resm))
            stats = get_json(url, "/stats")
            profile_doc = get_json(url, "/profile")
        finally:
            handle.stop()
        return pairs, stats, profile_doc

    def compile_share(doc):
        total = doc["ledger"]["total_s"]
        check(total > 0, "profile ledger total positive")
        return doc["waste_by_cause"]["compile_s"] / total

    # Everything (the solo-compare api.solve calls included) runs
    # with the tempdirs alive — the persistent-cache config latches
    # on the last enabled directory, and jit warns on every write
    # into a deleted one.  The XLA cost profiler is vetoed for the
    # comparison: its throwaway AOT build on every cold dispatch
    # runs BEFORE the engine's timed interval and (with the
    # persistent cache on) writes the disk entry the live jit then
    # retrieves, so with it enabled BOTH sides' /profile compile
    # waste collapses to retrieval-sized slivers and the check
    # compares noise.  Vetoed, the OFF side pays its full XLA
    # builds inside the timed interval while the ON side still
    # retrieves what the speculator pre-built.
    prior_profile = os.environ.get("PYDCOP_XLA_PROFILE")
    os.environ["PYDCOP_XLA_PROFILE"] = "0"
    try:
        with _tempfile.TemporaryDirectory() as td_off, \
                _tempfile.TemporaryDirectory() as td_on:
            pairs_off, stats_off, prof_off = run(
                False, (23, 25), td_off)
            pairs_on, stats_on, prof_on = run(True, (26, 29), td_on)
            _check_pipelined_speculation(
                compile_share, pairs_off, stats_off, prof_off,
                pairs_on, stats_on, prof_on)
    finally:
        if prior_profile is None:
            del os.environ["PYDCOP_XLA_PROFILE"]
        else:
            os.environ["PYDCOP_XLA_PROFILE"] = prior_profile


def _check_pipelined_speculation(compile_share, pairs_off, stats_off,
                                 prof_off, pairs_on, stats_on,
                                 prof_on):
    """Assertions for :func:`leg_pipelined_speculation`, run while
    the cache tempdirs are still alive (the solo-compare api.solve
    calls jit into the latched persistent-cache directory)."""
    check(not stats_off["pipeline"]["enabled"]
          and stats_off["pipeline"]["pipelined_dispatches"] == 0,
          "OFF run never pipelined")
    check(stats_on["speculation"]["enabled"],
          "speculation reported enabled on the ON run")
    check(stats_on["speculation"]
          ["speculative_compiles_total"] >= 1,
          "/stats shows speculative_compiles_total >= 1 "
          f"({stats_on['speculation']})")
    check(stats_on["speculation"]["hits"] >= 1,
          ">= 1 speculative hit on a real cold dispatch "
          f"({stats_on['speculation']})")
    check(stats_on["pipeline"]["pipelined_dispatches"] >= 2,
          ">= 2 pipelined dispatches on the mixed flush "
          f"({stats_on['pipeline']})")

    # THE acceptance bar: every ON response (pipelined,
    # speculated, packed or not) equals the solo api.solve
    # answer bit for bit.
    for dcop, res in pairs_on + pairs_off:
        solo = api_solve_cached(dcop)
        if res["assignment"] != solo["assignment"]:
            check(False,
                  f"served assignment for {dcop.name} differs "
                  "from solo api.solve")
    check(True,
          f"all {len(pairs_on) + len(pairs_off)} served "
          "answers bit-identical to solo api.solve")

    share_off = compile_share(prof_off)
    share_on = compile_share(prof_on)
    check(share_on < share_off,
          "compile waste share lower with speculation ON "
          f"({share_on:.3f} < {share_off:.3f})")


_SOLO_CACHE = {}


def api_solve_cached(dcop):
    from pydcop_tpu import api

    if dcop.name not in _SOLO_CACHE:
        _SOLO_CACHE[dcop.name] = api.solve(
            dcop, "maxsum", backend="device", max_cycles=MAX_CYCLES)
    return _SOLO_CACHE[dcop.name]


def leg_overload():
    from pydcop_tpu import api

    handle = api.serve(port=0, batch_window_s=0.01, max_batch=2,
                       max_queue=32, high_water=3)
    try:
        url = handle.url
        # Slow the device call down so the burst genuinely overruns
        # the queue (an unthrottled CPU dispatch drains too fast to
        # ever hit the high-water mark on a quiet box).
        service = handle.service
        real_run = service._run_batch

        def slowed(reqs, params):
            time.sleep(0.25)
            return real_run(reqs, params)

        service._run_batch = slowed
        before = scrape_requests_total(url)
        from pydcop_tpu.dcop.yamldcop import dcop_yaml

        statuses = [None] * OVERLOAD_BURST
        payloads = [dcop_yaml(build_instance(10, 200 + i))
                    for i in range(OVERLOAD_BURST)]

        def client(i):
            statuses[i] = post(url, {
                "dcop": payloads[i],
                "params": {"max_cycles": 40},
            })

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(OVERLOAD_BURST)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        check(all(s is not None for s in statuses),
              "no overload request hung (all POSTs returned)")
        accepted = [s for s in statuses if s[0] == 202]
        rejected = [s for s in statuses if s[0] == 429]
        check(not [s for s in statuses if s[0] not in (202, 429)],
              "overload responses are only 202 or 429")
        check(len(rejected) >= 1,
              f"queue past high-water yielded 429s "
              f"({len(rejected)}/{OVERLOAD_BURST})")
        # Every accepted request must finish — none dropped.
        deadline = time.monotonic() + 60
        for _, body in accepted:
            rid = body["id"]
            while time.monotonic() < deadline:
                result = handle.service.result(rid, wait=1.0)
                if result is not None:
                    break
            check(result is not None
                  and result["status"] == "FINISHED",
                  f"accepted request {rid} completed")
        after = scrape_requests_total(url)
        delta_ok = after.get("ok", 0) - before.get("ok", 0)
        delta_rej = (after.get("rejected_queue_full", 0)
                     - before.get("rejected_queue_full", 0))
        check(delta_ok == len(accepted)
              and delta_rej == len(rejected)
              and delta_ok + delta_rej == OVERLOAD_BURST,
              "pydcop_requests_total accounts for every request "
              f"(ok {delta_ok:.0f} + 429 {delta_rej:.0f} = "
              f"{OVERLOAD_BURST})")
    finally:
        handle.stop()


FLEET_BURST = 10


def leg_fleet_burst():
    """ISSUE 15 acceptance: a concurrent mixed-structure burst
    against a REAL 2-replica fleet (worker subprocesses behind the
    structure-affinity router) must answer every request
    bit-identical to solo ``api.solve`` — the fleet is wire-invisible
    — with both replicas carrying traffic, affinity accounting on
    /stats, and a clean whole-fleet drain (every worker exit 0)."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    handle = api.serve(port=0, replicas=2, batch_window_s=0.1,
                       max_batch=8, heartbeat_s=0.2)
    try:
        url = handle.url
        dcops = ([build_instance(10, 600 + s) for s in range(5)]
                 + [build_instance(14, 650 + s) for s in range(5)])
        payloads = [dcop_yaml(d) for d in dcops]
        results = [None] * len(dcops)

        def client(i):
            results[i] = post(url, {
                "dcop": payloads[i], "wait": True, "timeout": 120,
                "params": {"max_cycles": MAX_CYCLES},
            })

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(len(dcops))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        check(all(r is not None and r[0] == 200
                  and r[1]["status"] == "FINISHED" for r in results),
              f"all {len(dcops)} fleet-burst responses finished")
        for dcop, (_, res) in zip(dcops, results):
            solo = api.solve(dcop, "maxsum", backend="device",
                             max_cycles=MAX_CYCLES)
            if res["assignment"] != solo["assignment"] \
                    or res["cost"] != solo["cost"]:
                check(False,
                      f"fleet answer for {dcop.name} differs from "
                      "solo api.solve")
        check(True, f"all {len(dcops)} fleet answers bit-identical "
              "to solo api.solve")
        with urllib.request.urlopen(url + "/stats",
                                    timeout=30) as resp:
            stats = json.loads(resp.read())
        check(stats["up"] == 2, "both replicas up through the burst")
        loads = [w["forwarded"] for w in stats["workers"]]
        check(all(n > 0 for n in loads),
              f"both replicas carried traffic ({loads})")
        check(stats["affinity_hit_fraction"] is not None
              and stats["affinity_hit_fraction"] > 0,
              "affinity accounting on /stats (hit fraction "
              f"{stats['affinity_hit_fraction']})")
    finally:
        summary = handle.stop()
    check([w["exit"] for w in summary["workers"]] == [0, 0],
          "fleet drain: every worker exited 0 "
          f"({summary['workers']})")


def leg_elastic_fleet():
    """ISSUE 16 acceptance (smoke slice): on a real 2-replica fleet,
    an operator ``POST /admin/migrate`` moves a warm session between
    replicas with zero acked events lost — PATCHes before and after
    the move all land, the router pin follows the session, the final
    close answers from the new owner — and the elastic control
    surfaces (fairness ledger, migrations counter, per-host worker
    identity) are all live on /stats."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    handle = api.serve(port=0, replicas=2, hosts=2,
                       batch_window_s=0.1, max_batch=8,
                       heartbeat_s=0.2)
    try:
        url = handle.url
        base = build_path_instance(10, 1606)
        rng = np.random.default_rng(1606)
        params = {"noise": 0.01, "stability": 0.001,
                  "max_cycles": 500}
        req = urllib.request.Request(
            url + "/session",
            data=json.dumps({"dcop": dcop_yaml(base),
                             "params": params}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            ack = json.loads(resp.read())
            check(resp.status == 201 and ack.get("session_id"),
                  "fleet session opened (201 + id)")
        sid = ack["session_id"]

        def patch(batch):
            deadline = time.monotonic() + 90
            while True:
                req = urllib.request.Request(
                    url + f"/session/{sid}/events",
                    data=json.dumps({"events": batch,
                                     "wait": True}).encode(),
                    method="PATCH",
                    headers={"Content-Type": "application/json"})
                try:
                    with urllib.request.urlopen(req,
                                                timeout=60) as resp:
                        return json.loads(resp.read())
                except urllib.error.HTTPError as err:
                    check(err.code in (409, 503)
                          and time.monotonic() < deadline,
                          f"PATCH retryable during migration "
                          f"(got {err.code})")
                    time.sleep(0.2)

        batch = [{"type": "change_factor", "name": "c3",
                  "table": rng.integers(0, 10, size=(3, 3))
                  .astype(float).tolist()}]
        patch(batch)
        source = handle.router.pinned(
            sid, handle.router._session_pins)
        req = urllib.request.Request(
            url + "/admin/migrate",
            data=json.dumps({"session_id": sid}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=120) as resp:
            moved = json.loads(resp.read())
            check(resp.status == 200
                  and moved["from"] == source.index,
                  f"operator migrate moved the session "
                  f"({moved['from']} -> {moved['to']})")
        target = handle.router.pinned(
            sid, handle.router._session_pins)
        check(target.index != source.index,
              "router pin repointed to the new owner")
        out = patch(batch)
        check(out["seq"] == 2,
              "post-migration PATCH acked on the new owner "
              f"(seq {out['seq']})")
        with urllib.request.urlopen(url + f"/session/{sid}",
                                    timeout=30) as resp:
            st = json.loads(resp.read())
        check(st["applied_seq"] == 2 or st["seq"] == 2,
              f"zero acked events lost across the move ({st['seq']}"
              f"/{st['applied_seq']})")
        req = urllib.request.Request(url + f"/session/{sid}",
                                     method="DELETE")
        with urllib.request.urlopen(req, timeout=120) as resp:
            final = json.loads(resp.read())
        check(resp.status == 200 and final["status"] == "CLOSED",
              "migrated session closes cleanly on the new owner")
        with urllib.request.urlopen(url + "/stats",
                                    timeout=30) as resp:
            stats = json.loads(resp.read())
        check(stats["migrations"] == 1,
              f"migrations counter on /stats ({stats['migrations']})")
        check(stats["fairness"]["admitted"] >= 0
              and "active" in stats["fairness"],
              "weighted-fair admission ledger on /stats")
        hosts = {w["host_id"] for w in stats["workers"]}
        check(hosts == {"host0", "host1"},
              f"replicas striped over simulated hosts ({hosts})")
    finally:
        summary = handle.stop()
    check([w["exit"] for w in summary["workers"]] == [0, 0],
          "elastic fleet drain: every worker exited 0 "
          f"({summary['workers']})")


def build_wide_clique(n_vars: int = 12, d: int = 10):
    """Pairwise clique over a 10-value domain: induced width
    ``n_vars - 1`` puts the root UTIL hypercube at ``d**n_vars``
    cells — astronomically past the element cap, so the exact tier
    must refuse it cleanly."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(17)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("smoke_wide", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    k = 0
    for i in range(n_vars):
        for j in range(i + 1, n_vars):
            dcop.add_constraint(NAryMatrixRelation(
                [vs[i], vs[j]], rng.random((d, d)), f"c{k}"))
            k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def leg_dpop_exact():
    """ISSUE 17 acceptance: the exact tier on the wire.  A
    ``params.algo="dpop"`` request answers ``optimal: true`` with
    the solo exact assignment; an over-width problem gets a
    structured 400 (``rejected_width``) — never a 500 — and the
    service still serves iterative traffic afterwards."""
    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml

    handle = api.serve(port=0, batch_window_s=0.05, max_batch=8,
                       max_queue=64)
    try:
        url = handle.url
        dcop = build_path_instance(14, 1701)
        status, res = post(url, {
            "dcop": dcop_yaml(dcop), "wait": True, "timeout": 120,
            "params": {"algo": "dpop"},
        })
        check(status == 200 and res["status"] == "FINISHED",
              f"dpop request finished over HTTP (status {status})")
        check(res.get("optimal") is True,
              "exact-tier response carries optimal: true")
        solo = api.solve(dcop, "dpop", backend="device")
        check(res["assignment"] == solo["assignment"]
              and res["cost"] == solo["cost"],
              "served exact answer identical to solo api.solve "
              f"(cost {res['cost']})")

        status, body = post(url, {
            "dcop": dcop_yaml(build_wide_clique()), "wait": True,
            "timeout": 120, "params": {"algo": "dpop"},
        })
        check(status == 400,
              f"over-width exact request answers 400 (got {status})")
        check(body.get("status") == "rejected_width"
              and body.get("max_elements", 0)
              > body.get("max_elements_cap", 0)
              and body.get("retry") is False,
              "400 body is structured: rejected_width + element "
              f"count {body.get('max_elements')} > cap "
              f"{body.get('max_elements_cap')}, retry false")

        # The refusal must not poison the service for everyone else.
        status, res = post(url, {
            "dcop": dcop_yaml(build_instance(9, 1702)), "wait": True,
            "timeout": 120, "params": {"max_cycles": MAX_CYCLES},
        })
        check(status == 200 and res["status"] == "FINISHED",
              "iterative traffic still served after the width "
              "refusal")
        stats = handle.service.stats()
        check(stats["dpop_dispatches"] >= 1,
              "exact dispatches accounted on /stats "
              f"({stats['dpop_dispatches']})")
    finally:
        handle.stop()


KILL9_BURST = 10


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _spawn_serve(port: int, journal_dir: str, *extra) -> subprocess.Popen:
    """A REAL ``pydcop serve`` process (the kill target must be a
    process, not a thread)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PALLAS_AXON_POOL_IPS", None)
    return subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "serve",
         "--port", str(port), "--journal_dir", journal_dir,
         "--batch_window", "0.3", "--max_batch", "4",
         "--cycles", "200", *extra],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _wait_listening(proc, url: str, timeout: float = 90.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            _, err = proc.communicate()
            check(False, "serve subprocess died on startup: "
                  + err.decode(errors="replace")[-800:])
        try:
            with urllib.request.urlopen(url + "/healthz", timeout=2):
                return
        except (urllib.error.URLError, ConnectionError, OSError):
            time.sleep(0.25)
    check(False, f"serve subprocess never listened on {url}")


def leg_kill9_replay():
    """SIGKILL a serving process mid-burst; prove the 202 was a
    durable promise: every acked request's accepted record is on
    disk, and --recover replays every unfinished one to completion."""
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving.journal import (
        pending_requests,
        scan_journal,
    )
    from pydcop_tpu.serving.service import SolveService

    journal_dir = tempfile.mkdtemp(prefix="serve_kill9_")
    port = _free_port()
    proc = _spawn_serve(port, journal_dir)
    url = f"http://127.0.0.1:{port}"
    try:
        _wait_listening(proc, url)
        dcops = {}
        acked = []
        for i in range(KILL9_BURST):
            dcop = build_instance(11, 400 + i)
            status, body = post(url, {
                "dcop": dcop_yaml(dcop),
                "params": {"max_cycles": MAX_CYCLES},
            })
            check(status == 202,
                  f"burst request {i} acked (got {status})")
            acked.append(body["id"])
            dcops[body["id"]] = dcop
        # Mid-burst: the batch window is still open, nothing has
        # finished.  No drain, no flush, no mercy.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    records, _, _ = scan_journal(
        os.path.join(journal_dir, "requests.jnl"))
    on_disk = {r["id"] for r in records if r["kind"] == "accepted"}
    check(set(acked) <= on_disk,
          f"all {len(acked)} acked requests journaled before the 202 "
          f"(SIGKILL lost {len(set(acked) - on_disk)})")
    pending = {r["id"] for r in pending_requests(records)}
    finished_before_kill = set(acked) - pending

    # --recover: the same path `pydcop serve --journal_dir D
    # --recover` takes on restart.
    svc = SolveService(journal_dir=journal_dir, recover=True,
                       batch_window_s=0.05, max_batch=4)
    svc.start()
    try:
        check(svc.replayed == len(pending),
              f"recovery replayed exactly the {len(pending)} "
              f"unfinished request(s) ({svc.replayed} replayed, "
              f"{len(finished_before_kill)} completed pre-kill)")
        for rid in sorted(pending):
            result = svc.result(rid, wait=120.0)
            check(result is not None
                  and result["status"] == "FINISHED",
                  f"replayed request {rid} completed after kill -9")
        # Parity: a replayed request's answer equals the solo solve.
        from pydcop_tpu import api

        probe = sorted(pending)[0] if pending else None
        if probe is not None:
            solo = api.solve(dcops[probe], "maxsum",
                             backend="device", max_cycles=MAX_CYCLES)
            check(svc.result(probe)["assignment"]
                  == solo["assignment"],
                  "replayed result identical to solo api.solve")
    finally:
        svc.stop(drain=False)
    check(True, f"kill -9 mid-burst lost zero of {len(acked)} "
          "acknowledged requests")


def build_path_instance(n_vars: int, seed: int):
    """Path (tree) coloring: max-sum is exact here, so the recovered
    session's final cost must EQUAL the uninterrupted replay's."""
    from pydcop_tpu.dcop.dcop import DCOP
    from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
    from pydcop_tpu.dcop.relations import NAryMatrixRelation

    rng = np.random.default_rng(seed)
    dom = Domain("colors", "", [0, 1, 2])
    dcop = DCOP(f"smoke_path_{n_vars}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    for v in vs:
        dcop.add_variable(v)
    for k in range(n_vars - 1):
        table = rng.integers(0, 10, size=(3, 3)).astype(float)
        dcop.add_constraint(
            NAryMatrixRelation([vs[k], vs[k + 1]], table, f"c{k}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


SESSION_PARAMS = {"noise": 0.01, "stability": 0.001,
                  "max_cycles": 600, "segment_cycles": 100}


def leg_session_replay():
    """ISSUE-13 acceptance: SIGKILL a real serve subprocess
    mid-SESSION.  A stateful session is opened over HTTP, 3 event
    batches are acked (200s), the process dies with no drain; every
    acked record must be on disk, and a --recover start must resume
    the session, apply the journaled-but-unapplied batches, and
    close with EXACTLY the final cost an uninterrupted replay of the
    same event stream produces — zero acked events lost."""
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.engine.dynamic import build_dynamic_engine
    from pydcop_tpu.serving.journal import scan_journal
    from pydcop_tpu.serving.service import SolveService
    from pydcop_tpu.serving.sessions import apply_event_batch

    rng = np.random.default_rng(1306)
    base = build_path_instance(10, 1306)
    batches = [
        [{"type": "change_factor", "name": f"c{i}",
          "table": rng.integers(0, 10, size=(3, 3))
          .astype(float).tolist()}]
        for i in range(3)
    ]
    # The uninterrupted reference: the same open + event stream
    # through a local engine (deterministic on CPU).
    ref = build_dynamic_engine(base, SESSION_PARAMS)
    ref.run(max_cycles=SESSION_PARAMS["max_cycles"])
    for batch in batches:
        _applied, _touched, error = apply_event_batch(ref, batch)
        check(error is None, f"reference batch applied ({error})")
        ref.run(max_cycles=SESSION_PARAMS["max_cycles"])
    expected = ref.cost(
        ref.run(max_cycles=SESSION_PARAMS["max_cycles"]).assignment)

    journal_dir = tempfile.mkdtemp(prefix="serve_session_")
    port = _free_port()
    proc = _spawn_serve(port, journal_dir)
    url = f"http://127.0.0.1:{port}"
    try:
        _wait_listening(proc, url)
        req = urllib.request.Request(
            url + "/session",
            data=json.dumps({"dcop": dcop_yaml(base),
                             "params": SESSION_PARAMS}).encode(),
            headers={"Content-Type": "application/json"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            ack = json.loads(resp.read())
            check(resp.status == 201 and ack.get("session_id"),
                  "session opened over HTTP (201 + id)")
        sid = ack["session_id"]
        for i, batch in enumerate(batches):
            req = urllib.request.Request(
                url + f"/session/{sid}/events",
                data=json.dumps({"events": batch}).encode(),
                method="PATCH",
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=60) as resp:
                body = json.loads(resp.read())
                check(resp.status == 200
                      and body["seq"] == i + 1,
                      f"event batch {i + 1} acked (durable 200)")
        # No drain, no close: the acks are the only promise left.
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
    finally:
        if proc.poll() is None:
            proc.kill()
    records, _, _ = scan_journal(
        os.path.join(journal_dir, "requests.jnl"))
    kinds = [r["kind"] for r in records if r.get("id") == sid]
    check(kinds.count("session_open") == 1
          and kinds.count("session_event") == 3,
          "all acked session records on disk after SIGKILL "
          f"(found {kinds})")

    svc = SolveService(journal_dir=journal_dir, recover=True,
                       batch_window_s=0.05, max_batch=4)
    svc.start()
    try:
        status = svc.sessions.status(sid)
        check(status["seq"] == 3 and status["applied_seq"] == 3,
              "--recover resumed the session with ALL 3 acked "
              "event batches applied (zero lost)")
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            status = svc.sessions.status(sid)
            if status["last"] and status["last"].get("converged"):
                break
            time.sleep(0.1)
        final = svc.sessions.close(sid)
        check(final["status"] == "CLOSED"
              and final["cost"] == expected,
              "recovered session's final result equals the "
              f"uninterrupted run ({final['cost']} == {expected})")
    finally:
        svc.stop(drain=False)


def leg_sigterm_drain():
    """SIGTERM (the orchestrated-restart signal): the process drains
    accepted work and exits 0, logging the drained count."""
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.serving.journal import (
        pending_requests,
        scan_journal,
    )

    journal_dir = tempfile.mkdtemp(prefix="serve_sigterm_")
    port = _free_port()
    proc = _spawn_serve(port, journal_dir)
    url = f"http://127.0.0.1:{port}"
    acked = []
    try:
        _wait_listening(proc, url)
        for i in range(4):
            status, body = post(url, {
                "dcop": dcop_yaml(build_instance(9, 500 + i)),
                "params": {"max_cycles": 40},
            })
            check(status == 202, f"drain request {i} acked")
            acked.append(body["id"])
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            check(False, "SIGTERM'd serve process failed to exit")
        _, err = proc.communicate()
        stderr = err.decode(errors="replace")
        check(proc.returncode == 0,
              f"SIGTERM exits 0 (got {proc.returncode}): "
              f"{stderr[-400:]}")
        check("drained" in stderr and "replayable" in stderr,
              "shutdown banner logs the drained/replayable counts")
    finally:
        if proc.poll() is None:
            proc.kill()
    # Zero silently dropped: every acked id either completed inside
    # the drain window (journaled terminal) or is still replayable.
    records, _, _ = scan_journal(
        os.path.join(journal_dir, "requests.jnl"))
    on_disk = {r["id"] for r in records if r["kind"] == "accepted"}
    pending = {r["id"] for r in pending_requests(records)}
    terminal = on_disk - pending
    check(set(acked) <= (terminal | pending),
          f"every accepted request drained ({len(terminal)}) or "
          f"left replayable ({len(pending)}) — zero dropped")


TRACE_BURST = 5


def leg_request_tracing():
    """ISSUE 9 acceptance: per-request causality over real HTTP.

    A traced batched burst must leave every request reconstructable:
    ``pydcop trace query --request ID`` (the real CLI, against the
    exported trace file) returns ONE well-nested tree whose spans
    cover submit → queue → serve_dispatch → engine_segment, all
    tagged with that request's trace_id; and the latency histogram's
    p99 bucket carries an exemplar trace_id the SAME query resolves."""
    import re as _re

    from pydcop_tpu import api
    from pydcop_tpu.dcop.yamldcop import dcop_yaml
    from pydcop_tpu.observability.trace import tracer

    trace_path = os.path.join(
        tempfile.mkdtemp(prefix="serve_trace_"), "serve.jsonl")
    tracer.enable()
    handle = api.serve(port=0, batch_window_s=0.3, max_batch=8,
                       max_queue=32)
    try:
        url = handle.url
        payloads = [dcop_yaml(build_instance(10, 900 + i))
                    for i in range(TRACE_BURST)]
        results = [None] * TRACE_BURST

        def client(i):
            results[i] = post(url, {
                "dcop": payloads[i], "wait": True, "timeout": 120,
                "params": {"max_cycles": MAX_CYCLES},
            })

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(TRACE_BURST)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        check(all(r is not None and r[0] == 200
                  and r[1]["status"] == "FINISHED" for r in results),
              f"traced burst of {TRACE_BURST} completed")
        trace_ids = [r[1].get("trace_id") for r in results]
        check(all(trace_ids) and len(set(trace_ids)) == TRACE_BURST,
              "every response carries a distinct trace_id")
        stats = handle.service.stats()
        check(stats["batched_dispatches"] >= 1,
              "traced burst was genuinely batched "
              f"({stats['batched_dispatches']} multi-instance "
              "dispatch(es))")

        # p99 exemplar: on the exposition AND resolvable below.
        # Exemplars are OpenMetrics-only syntax — negotiate the
        # dialect the way a real Prometheus with exemplar storage
        # does; the classic text format must stay exemplar-free.
        om_req = urllib.request.Request(
            url + "/metrics",
            headers={"Accept": "application/openmetrics-text"})
        with urllib.request.urlopen(om_req, timeout=30) as resp:
            check("openmetrics-text" in resp.headers["Content-Type"],
                  "negotiated scrape answers as OpenMetrics")
            exposition = resp.read().decode()
        check(exposition.rstrip().endswith("# EOF"),
              "OpenMetrics exposition carries the # EOF terminator")
        with urllib.request.urlopen(url + "/metrics",
                                    timeout=30) as resp:
            classic = resp.read().decode()
        check(" # {" not in classic,
              "classic text-format scrape stays exemplar-free "
              "(v0.0.4 parsers reject exemplar suffixes)")
        ex = _re.search(
            r'pydcop_request_latency_seconds_bucket\{[^}]*\}'
            r' \S+ # \{trace_id="([0-9a-f]+)"\}', exposition)
        check(ex is not None,
              "latency histogram exposes an OpenMetrics exemplar")
        with urllib.request.urlopen(url + "/stats",
                                    timeout=30) as resp:
            svc_stats = json.loads(resp.read())
        p99 = (svc_stats.get("latency_exemplars") or {}).get("p99")
        check(p99 is not None and p99["trace_id"] in trace_ids,
              "p99 latency exemplar names a burst trace_id "
              f"({p99 and p99['trace_id']})")
    finally:
        handle.stop()
        tracer.export_jsonl(trace_path)
        tracer.disable()

    def query(trace_id: str) -> dict:
        proc = subprocess.run(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "trace",
             "query", "--request", trace_id, "--json", trace_path],
            capture_output=True, timeout=120)
        check(proc.returncode == 0,
              f"pydcop trace query --request {trace_id} exits 0")
        return json.loads(proc.stdout)

    tree = query(trace_ids[0])
    check(tree["well_nested"],
          "queried request tree is well-nested")
    names = set(tree["names"])
    for needed in ("serve_submit", "serve_queued", "serve_dispatch",
                   "engine_segment"):
        check(needed in names,
              f"request tree contains a {needed} span "
              f"(names: {sorted(names)})")

    def _flat(nodes):
        for node in nodes:
            yield node
            yield from _flat(node["children"])

    for node in _flat(tree["tree"]):
        args = node["args"]
        tagged = (args.get("trace_id") == trace_ids[0]
                  or trace_ids[0] in (args.get("trace_ids") or []))
        check(tagged, f"{node['name']} span tagged with the "
              "request's trace_id")
    # The p99 exemplar is one hop from its trace: the SAME query
    # resolves the trace_id the histogram exposed.
    ex_tree = query(p99["trace_id"])
    check(ex_tree["events"] > 0 and ex_tree["well_nested"]
          and "engine_segment" in ex_tree["names"],
          "p99 exemplar trace_id resolves to a full request tree "
          f"({ex_tree['events']} events)")


def main() -> int:
    t0 = time.perf_counter()
    # The tracing leg runs FIRST: the latency histogram is process-
    # global, so its p99-exemplar assertion needs the burst to be the
    # only traffic observed so far.  The other in-process legs only
    # read per-service stats or scrape deltas — order-independent.
    leg_request_tracing()
    leg_coalescing()
    leg_mixed_envelope()
    leg_efficiency()
    leg_pipelined_speculation()
    leg_overload()
    leg_dpop_exact()
    leg_fleet_burst()
    leg_elastic_fleet()
    leg_kill9_replay()
    leg_session_replay()
    leg_sigterm_drain()
    print(f"serve_smoke: PASS ({time.perf_counter() - t0:.1f}s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Heartbeat failure detection: phi-accrual suspicion, bounded death.

PR-1's failure detection is *passive*: an agent death is only noticed
when a send to it fails (transport retry window) or when the fault
monitor injected the kill and reported it itself.  A silently-wedged
agent — thread crashed, process frozen, partitioned away — keeps its
computations orphaned until some neighbor happens to message it.  This
module makes detection *active*:

- every agent hosts a tiny :class:`HeartbeatEmitter` service
  computation that posts a :data:`HeartbeatMessage` to the
  orchestrator every ``interval`` seconds **over the normal
  CommunicationLayer** — heartbeats ride at value priority
  (:data:`MSG_HEARTBEAT`), so injected drop/delay faults apply to them
  exactly like algorithm traffic (a detector that only works on a
  perfect network detects nothing);
- the orchestrator's :class:`HealthMonitor` scores each agent's
  heartbeat inter-arrival history with a phi-accrual-style estimator
  (Hayashibara et al., "The phi accrual failure detector"): instead of
  a binary alive/dead timeout it computes a *suspicion level* from the
  observed arrival distribution, so a link that is lossy-but-alive
  raises suspicion without triggering migration;
- verdicts escalate ``alive -> suspect -> dead`` and de-escalate back
  to ``alive`` on the next heartbeat.  ``suspect`` is advisory (trace
  instant + counter + ``agent_suspect`` verdict).  ``dead`` is the
  hard, *bounded* verdict — declared only after
  ``dead_misses x expected-interval`` of silence — and feeds
  ``orchestrator.report_agent_failure``, i.e. the exact same
  replication/reparation path PR-1 wired for transport-detected
  deaths.  Detection latency is therefore bounded by
  ``dead_misses * interval + poll`` regardless of message traffic.

Determinism note: verdict *timing* depends on wall-clock scheduling,
but the guarantees the chaos soak asserts are schedule-free — a killed
agent IS reported dead within the miss bound, and pure message-level
faults (drop/dup/delay without a kill) are NEVER escalated past
suspicion, because a live emitter keeps producing heartbeats and the
drop probability of ``dead_misses`` consecutive beats vanishes.
"""

import logging
import math
import threading
import time
from collections import deque
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Tuple

from pydcop_tpu.infrastructure.communication import MSG_VALUE
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
    message_type,
    register,
)
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer

logger = logging.getLogger("pydcop.resilience.health")

# The orchestrator-side computation heartbeats are addressed to.
HEALTH_COMP = "_health_orchestrator"

# Heartbeats ride at VALUE priority on purpose: anything below
# MSG_VALUE is protected management traffic the fault layer never
# touches (FaultyCommunicationLayer.protect_management), and a failure
# detector whose probes bypass the faulty network cannot distinguish a
# lossy link from a healthy one.
MSG_HEARTBEAT = MSG_VALUE

HeartbeatMessage = message_type("heartbeat", ["agent", "seq"])

ALIVE = "alive"
SUSPECT = "suspect"
DEAD = "dead"


@dataclass(frozen=True)
class HealthConfig:
    """Knobs of the heartbeat failure detector (docs/resilience.md).

    ``interval`` — seconds between heartbeats (per agent);
    ``suspect_misses`` — silence longer than this many expected
    intervals (or phi above ``phi_suspect``) marks the agent suspect;
    ``dead_misses`` — silence longer than this many expected intervals
    is the death verdict: the HARD detection bound;
    ``phi_suspect`` — phi-accrual suspicion threshold (phi = k means
    the observed arrival history puts the no-heartbeat probability at
    10^-k);
    ``poll`` — monitor scan period;
    ``window`` — inter-arrival samples kept per agent.
    """

    interval: float = 0.05
    suspect_misses: float = 3.0
    dead_misses: float = 8.0
    phi_suspect: float = 2.0
    poll: float = 0.02
    window: int = 20

    def __post_init__(self):
        if self.interval <= 0:
            raise ValueError(f"heartbeat interval must be > 0: "
                             f"{self.interval}")
        if not 0 < self.suspect_misses < self.dead_misses:
            raise ValueError(
                "need 0 < suspect_misses < dead_misses, got "
                f"{self.suspect_misses} / {self.dead_misses}")


class PhiAccrualEstimator:
    """Suspicion level from one agent's heartbeat arrival history.

    Keeps the last ``window`` inter-arrival intervals; :meth:`phi`
    scores the current silence against their normal fit:
    ``phi(t) = -log10(P[interval > t])``.  With too few samples the
    configured ``expected`` interval stands in for the mean.  The
    standard deviation is floored at 25% of the mean so a perfectly
    regular history cannot make the detector hair-triggered: a gap
    must be several expected intervals long before phi alone crosses
    the suspicion threshold.
    """

    def __init__(self, expected: float, window: int = 20):
        self.expected = expected
        self._intervals: Deque[float] = deque(maxlen=window)
        self.last_beat: Optional[float] = None
        self.beats = 0

    def beat(self, now: float):
        if self.last_beat is not None:
            # Clock hiccups (now <= last) contribute a zero interval.
            self._intervals.append(max(now - self.last_beat, 0.0))
        self.last_beat = now
        self.beats += 1

    def mean_interval(self) -> float:
        if not self._intervals:
            return self.expected
        # Never trust an estimate below the configured cadence: a
        # burst of queued heartbeats (delay fault released) would
        # otherwise shrink the mean toward 0 and make phi
        # hair-triggered on the next ordinary gap.
        return max(sum(self._intervals) / len(self._intervals),
                   self.expected)

    def missed(self, now: float, anchor: float) -> float:
        """Silence so far, in units of the CONFIGURED interval — not
        the adaptive mean: the miss count backs the death verdict,
        whose detection-latency bound (``dead_misses x interval``)
        must hold regardless of what a faulty link did to the observed
        arrival history (only phi, the advisory suspicion score,
        adapts to it)."""
        last = self.last_beat if self.last_beat is not None else anchor
        return max(now - last, 0.0) / self.expected

    def phi(self, now: float, anchor: float) -> float:
        """-log10 of the probability that a live agent stays silent
        this long, under a normal fit of the interval history."""
        last = self.last_beat if self.last_beat is not None else anchor
        elapsed = max(now - last, 0.0)
        mean = self.mean_interval()
        if len(self._intervals) >= 2:
            var = sum((x - mean) ** 2 for x in self._intervals) \
                / len(self._intervals)
            std = math.sqrt(var)
        else:
            std = 0.0
        std = max(std, 0.25 * mean, 1e-6)
        # P[interval > elapsed] under N(mean, std).
        z = (elapsed - mean) / std
        p_longer = 0.5 * math.erfc(z / math.sqrt(2.0))
        if p_longer <= 0.0:
            return float("inf")
        return -math.log10(p_longer)


class HealthComputation(MessagePassingComputation):
    """Orchestrator-side sink for heartbeat messages (``HEALTH_COMP``)."""

    def __init__(self, monitor: "HealthMonitor"):
        super().__init__(HEALTH_COMP)
        self._monitor = monitor

    @register("heartbeat")
    def _on_heartbeat(self, sender, msg, t):
        self._monitor.record(msg.agent, msg.seq)


class HeartbeatEmitter(MessagePassingComputation):
    """Agent-side service computation: one heartbeat every ``interval``
    seconds, posted from the agent's own thread (its periodic-action
    loop) — so a hard-stopped thread stops beating, which is exactly
    the signal the monitor scores."""

    def __init__(self, agent_name: str, interval: float):
        super().__init__(f"_heartbeat_{agent_name}")
        self._agent_name = agent_name
        self._seq = 0
        self.add_periodic_action(interval, self._beat)

    def _beat(self):
        self._seq += 1
        try:
            self.post_msg(
                HEALTH_COMP,
                HeartbeatMessage(self._agent_name, self._seq),
                MSG_HEARTBEAT,
            )
        except Exception:
            # A beat must never kill the agent thread; a missing beat
            # is precisely what the monitor is designed to score.
            self.logger.debug("heartbeat send failed", exc_info=True)


class HealthMonitor:
    """Scores heartbeat arrivals into alive/suspect/dead verdicts.

    ``on_dead(agent)`` fires exactly once per agent on the death
    verdict (default: nothing — the orchestrator wiring passes
    ``report_agent_failure``, routing the death into the PR-1
    replication/reparation path).  ``on_suspect(agent)`` is advisory.

    Verdict changes are published as trace instants
    (``agent_suspect`` / ``agent_dead`` / ``agent_recovered``) and
    counted in ``pydcop_health_verdicts_total{verdict=...}``, so a
    chaos run's detection story is reconstructable from its trace
    alone.  :attr:`verdicts` keeps the in-process history for
    harnesses (chaos soak) to assert against.
    """

    def __init__(self, config: Optional[HealthConfig] = None,
                 on_dead: Optional[Callable[[str], None]] = None,
                 on_suspect: Optional[Callable[[str], None]] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.config = config or HealthConfig()
        self.on_dead = on_dead
        self.on_suspect = on_suspect
        self._clock = clock
        self._lock = threading.Lock()
        self._estimators: Dict[str, PhiAccrualEstimator] = {}
        self._anchors: Dict[str, float] = {}
        self._status: Dict[str, str] = {}
        # Agents removed through the failure path: their in-flight
        # (e.g. delay-faulted) heartbeats must not auto-watch them
        # back into scoring — that silence would later surface as a
        # spurious death verdict.
        self._forgotten: set = set()
        self.verdicts: List[Tuple[float, str, str]] = []
        self.computation = HealthComputation(self)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._m_verdicts = metrics_registry.counter(
            "pydcop_health_verdicts_total",
            "Health verdict transitions by the heartbeat monitor")
        self._m_beats = metrics_registry.counter(
            "pydcop_heartbeats_total",
            "Heartbeats received by the health monitor")

    # -- registration / input ------------------------------------------ #

    def watch(self, agent: str):
        """Start scoring ``agent``; the watch time anchors the silence
        window until its first heartbeat arrives.  An explicit watch
        clears a previous removal (an agent can come back under the
        same name through a scenario event)."""
        with self._lock:
            self._forgotten.discard(agent)
            if agent in self._estimators:
                return
            self._estimators[agent] = PhiAccrualEstimator(
                self.config.interval, self.config.window)
            self._anchors[agent] = self._clock()
            self._status[agent] = ALIVE

    def unwatch(self, agent: str):
        """Forget ``agent`` without a verdict (clean shutdown path:
        a stopped agent is not a dead agent)."""
        with self._lock:
            self._estimators.pop(agent, None)
            self._anchors.pop(agent, None)
            self._status.pop(agent, None)

    def forget_removed(self, agent: str):
        """An agent left through the failure path (scenario removal,
        transport mark, injected kill).  Stop scoring it — a cleanly
        removed agent must not later produce a spurious death verdict
        — but keep the record when THIS monitor already declared it
        dead (the verdict history is the detection evidence)."""
        with self._lock:
            self._forgotten.add(agent)
            if self._status.get(agent) == DEAD:
                return
        self.unwatch(agent)

    def record(self, agent: str, seq: int):
        """One heartbeat arrived (any thread)."""
        now = self._clock()
        recovered = False
        with self._lock:
            if agent in self._forgotten:
                # A straggler beat (delay fault) from an agent already
                # removed through the failure path: scoring it again
                # would end in a spurious death verdict.
                return
            est = self._estimators.get(agent)
            if est is None:
                # Auto-watch: an agent can beat before the runner's
                # explicit watch() (scenario-added agents).
                est = PhiAccrualEstimator(
                    self.config.interval, self.config.window)
                self._estimators[agent] = est
                self._anchors[agent] = now
                self._status[agent] = ALIVE
            est.beat(now)
            # A heartbeat clears suspicion; death is final (the
            # reparation path already migrated the computations — a
            # zombie beat must not resurrect the agent here).
            if self._status.get(agent) == SUSPECT:
                self._status[agent] = ALIVE
                recovered = True
        self._m_beats.inc()
        if recovered:
            self._note_verdict(now, agent, ALIVE, "agent_recovered")

    # -- verdicts ------------------------------------------------------- #

    def _note_verdict(self, now: float, agent: str, status: str,
                      instant: str):
        with self._lock:
            self.verdicts.append((now, agent, status))
        self._m_verdicts.inc(verdict=status)
        if tracer.enabled:
            tracer.instant(instant, "health", agent=agent)
        logger.log(
            logging.WARNING if status == DEAD else logging.INFO,
            "Health verdict: agent %s is %s", agent, status,
        )

    def scan(self) -> Dict[str, str]:
        """One scoring pass over every watched agent; returns the
        post-scan status map.  Called by the monitor thread each
        ``poll``; exposed for deterministic fake-clock tests."""
        now = self._clock()
        cfg = self.config
        suspects: List[str] = []
        deaths: List[str] = []
        with self._lock:
            for agent, est in self._estimators.items():
                status = self._status[agent]
                if status == DEAD:
                    continue
                anchor = self._anchors[agent]
                missed = est.missed(now, anchor)
                if missed >= cfg.dead_misses:
                    self._status[agent] = DEAD
                    deaths.append(agent)
                elif status == ALIVE and (
                        missed >= cfg.suspect_misses
                        or est.phi(now, anchor) >= cfg.phi_suspect):
                    self._status[agent] = SUSPECT
                    suspects.append(agent)
            statuses = dict(self._status)
        for agent in suspects:
            self._note_verdict(now, agent, SUSPECT, "agent_suspect")
            if self.on_suspect is not None:
                try:
                    self.on_suspect(agent)
                except Exception:
                    logger.exception("on_suspect(%s) failed", agent)
        for agent in deaths:
            self._note_verdict(now, agent, DEAD, "agent_dead")
            if self.on_dead is not None:
                try:
                    self.on_dead(agent)
                except Exception:
                    logger.exception("on_dead(%s) failed", agent)
        return statuses

    def statuses(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._status)

    def dead_agents(self) -> List[str]:
        return sorted(
            a for a, s in self.statuses().items() if s == DEAD)

    def summary(self) -> Dict[str, object]:
        """Result-dict payload: final statuses + verdict history."""
        statuses = self.statuses()
        return {
            "statuses": statuses,
            "dead": sorted(a for a, s in statuses.items()
                           if s == DEAD),
            "verdicts": [
                {"t": t, "agent": a, "status": s}
                for t, a, s in list(self.verdicts)
            ],
        }

    # -- lifecycle ------------------------------------------------------ #

    def start(self) -> "HealthMonitor":
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="health_monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(2.0)
            self._thread = None

    def _run(self):
        while not self._stop.is_set():
            try:
                self.scan()
            except Exception:
                logger.exception("Health scan failed")
            self._stop.wait(self.config.poll)


def attach_health(orchestrator, config: HealthConfig) -> HealthMonitor:
    """Build a monitor wired to ``orchestrator``: heartbeats land on
    its agent, a death verdict runs ``report_agent_failure`` (the same
    entry every other detector uses, so verdict handling is latched
    and race-safe there).  The runner is responsible for watching
    agents and installing emitters (infrastructure/run.py)."""
    monitor = HealthMonitor(
        config, on_dead=orchestrator.report_agent_failure)
    orchestrator._agent.add_computation(monitor.computation)
    monitor.computation.start()
    orchestrator.health_monitor = monitor
    return monitor

"""Chaos soak: a seeded scenario matrix asserting global invariants.

The robustness analogue of ``make perf-smoke``: where the perf gate
proves the hot path is *fast*, this gate proves the runtime *heals* —
every scenario injects a distinct failure combination (message drop +
duplicate + delay, network partition with healing, silent agent kill,
engine guard trips, checkpoint corruption) and asserts the system-wide
invariants that define "self-healing":

- **valid assignment** — every variable ends with a value from its
  domain (a migrated computation kept working; nothing was lost);
- **monotone cycle counter** — progress never runs backwards in the
  observable record (trace ``engine_segment`` spans may rewind ONLY
  across an explicit ``recovery_rollback``);
- **no orphaned computations** — a killed agent's computations are
  re-hosted, not dropped (their variables still carry values);
- **health verdicts consistent with the kill schedule** — every
  injected kill is reported ``agent_dead`` within the configured miss
  bound, and scenarios with message faults but NO kill produce zero
  death verdicts (suspicion is allowed: that is the phi-accrual
  detector doing its job on a lossy link).

Every scenario is a pure function of the seed (fault decisions are
seeded per edge+index, heartbeat bounds are schedule-free, guard trips
are cycle-keyed), so a red run REPLAYS: the failure report prints the
scenario name, the seed and the trace file to hand to
``pydcop trace summary``.

Usage::

    python tools/chaos_soak.py                 # full matrix
    python tools/chaos_soak.py --scenarios 6   # quick gate (make test)
    python tools/chaos_soak.py --seed 7 --only kill_detected

``make chaos-soak`` runs the full matrix; ``make test`` wires the
quick 6-scenario gate (fixed seed, < 60 s).
"""

import argparse
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")

from pydcop_tpu.algorithms import AlgorithmDef  # noqa: E402
from pydcop_tpu.dcop.dcop import DCOP  # noqa: E402
from pydcop_tpu.dcop.objects import (  # noqa: E402
    AgentDef,
    Domain,
    Variable,
)
from pydcop_tpu.dcop.relations import constraint_from_str  # noqa: E402
from pydcop_tpu.distribution.objects import Distribution  # noqa: E402

DEFAULT_SEED = int(os.environ.get("PYDCOP_CHAOS_SEED", "42"))


# ------------------------------------------------------------------ #
# fixtures


def coloring_dcop(n_agents=5, n_vars=4):
    """3-colorable chain: fault-free optimum cost is 0."""
    d = Domain("colors", "", ["R", "G", "B"])
    dcop = DCOP("soak", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(n_vars - 1):
        dcop.add_constraint(constraint_from_str(
            f"diff_{i}_{i + 1}",
            f"10 if v{i} == v{i + 1} else 0",
            [variables[i], variables[i + 1]],
        ))
    dcop.add_agents([
        AgentDef(f"a{i}", capacity=100, default_hosting_cost=i)
        for i in range(n_agents)
    ])
    return dcop


def variable_distribution():
    return Distribution({
        "a0": ["v0"], "a1": ["v1"], "a2": ["v2"], "a3": ["v3"],
        "a4": [],
    })


def ring_dcop(n_vars=6):
    d = Domain("c", "", list(range(3)))
    dcop = DCOP("soak_ring", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)] + [(0, 3)]
    for i, j in edges:
        dcop.add_constraint(constraint_from_str(
            f"c{i}_{j}", f"10 if v{i} == v{j} else 0",
            [variables[i], variables[j]],
        ))
    return dcop


# ------------------------------------------------------------------ #
# invariants


def assert_valid_assignment(dcop, assignment):
    """Every variable valued, every value in its domain."""
    for name, variable in dcop.variables.items():
        assert name in assignment, f"variable {name} has NO value " \
            "(orphaned computation?)"
        value = assignment[name]
        assert value in list(variable.domain), \
            f"variable {name} = {value!r} outside its domain"


def assert_health_consistent(health, killed):
    """Dead verdicts == the injected kill schedule, exactly."""
    dead = set(health["dead"])
    assert dead == set(killed), (
        f"health verdicts inconsistent with kill schedule: "
        f"dead={sorted(dead)} killed={sorted(killed)}"
    )


def assert_monotone_segments(trace_path):
    """Engine segment cycles never rewind except across an explicit
    recovery rollback — the monotone-progress invariant."""
    from pydcop_tpu.observability.trace import load_trace_file

    events = sorted(
        (e for e in load_trace_file(trace_path)
         if e.get("name") in ("engine_segment", "recovery_rollback")),
        key=lambda e: e["ts"],
    )
    last_cycle = -1
    for ev in events:
        if ev["name"] == "recovery_rollback":
            last_cycle = -1  # an announced rewind resets the floor
            continue
        start = int(ev.get("args", {}).get("from_cycle", 0))
        assert start >= last_cycle, (
            f"cycle counter rewound without a rollback: segment from "
            f"cycle {start} after cycle {last_cycle}"
        )
        last_cycle = start
    return events


# ------------------------------------------------------------------ #
# scenarios — each returns a dict of observations, raises on failure


def _thread_chaos(seed, trace, *, plan, health=True, algo=None,
                  timeout=20):
    from pydcop_tpu.infrastructure.run import solve_with_agents
    from pydcop_tpu.observability import ObservabilitySession
    from pydcop_tpu.resilience.health import HealthConfig

    dcop = coloring_dcop()
    algo = algo or AlgorithmDef.build_with_default_param(
        "adsa", {"stop_cycle": 40, "period": 0.05}, mode="min")
    config = HealthConfig() if health else None
    with ObservabilitySession(trace, "chrome"):
        res = solve_with_agents(
            dcop, algo, distribution=variable_distribution(),
            timeout=timeout, fault_plan=plan, health_config=config,
        )
    assert_valid_assignment(dcop, res["assignment"])
    assert res.get("cycles", 0) > 0, "no cycle ever completed"
    return res


def scenario_kill_detected(seed, trace):
    """Silent kill mid-run: the heartbeat monitor (not the injector)
    must detect the death and the repair path must migrate the
    victim's computation."""
    from pydcop_tpu.resilience.faults import CrashEvent, FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, crashes=(CrashEvent("a1", 5),), replicas=2,
    ), timeout=45)
    assert res["killed_agents"] == ["a1"]
    assert_health_consistent(res["health"], ["a1"])
    assert res["status"] == "FINISHED", f"run ended {res['status']}"
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"dead": res["health"]["dead"], "cost": res["cost"]}


def scenario_drop_dup_delay(seed, trace):
    """Lossy-but-alive links: drop+dup+delay with NO kill must
    converge to the fault-free cost with ZERO death verdicts
    (suspicion allowed — that is the detector's designed response)."""
    from pydcop_tpu.resilience.faults import FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, drop=0.10, duplicate=0.05, delay=0.05,
        delay_time=0.02,
    ))
    stats = res["fault_stats"]
    assert stats["dropped"] > 0, "no fault injected — not a chaos run"
    assert_health_consistent(res["health"], [])
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"fault_stats": stats,
            "suspects": [v for v in res["health"]["verdicts"]
                         if v["status"] == "suspect"]}


def scenario_delay_only_no_death(seed, trace):
    """Pure delay (30%): heartbeats arrive late, never never-again —
    zero death verdicts."""
    from pydcop_tpu.resilience.faults import FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, delay=0.30, delay_time=0.05,
    ))
    assert_health_consistent(res["health"], [])
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"verdicts": len(res["health"]["verdicts"])}


def scenario_partition_heal(seed, trace):
    """A partition splits the chain mid-problem, then HEALS (per-edge
    index bound): the run must reconverge to the fault-free cost after
    the heal — the assertion PR-1's permanent partitions could never
    make."""
    from pydcop_tpu.resilience.faults import FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed,
        partitions=(frozenset({"a0", "a1"}),
                    frozenset({"a2", "a3", "a4"})),
        partition_heal_index=8,
    ), timeout=30)
    assert res["fault_stats"]["partitioned"] > 0, \
        "partition never blocked a message"
    assert_health_consistent(res["health"], [])
    assert res["cost"] == 0, (
        f"no reconvergence after partition heal: cost {res['cost']}")
    return {"partitioned": res["fault_stats"]["partitioned"]}


def scenario_drop_plus_kill(seed, trace):
    """Combined loss + silent kill: detection and repair under a lossy
    network."""
    from pydcop_tpu.resilience.faults import CrashEvent, FaultPlan

    res = _thread_chaos(seed, trace, plan=FaultPlan(
        seed=seed, drop=0.10, crashes=(CrashEvent("a2", 5),),
        replicas=2,
    ), timeout=45)
    assert res["killed_agents"] == ["a2"]
    assert_health_consistent(res["health"], ["a2"])
    assert res["status"] == "FINISHED", f"run ended {res['status']}"
    assert res["cost"] == 0, f"non-optimal cost {res['cost']}"
    return {"dead": res["health"]["dead"]}


def scenario_guard_trip_device(seed, trace):
    """Injected guard trip on a device solve: rollback + recovery must
    appear in the exported trace, the cycle counter may only rewind
    across the rollback, and the healed run still converges to a valid
    assignment."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.observability import ObservabilitySession
    from pydcop_tpu.resilience.recovery import RecoveryPolicy

    dcop = ring_dcop()
    with ObservabilitySession(trace, "chrome"):
        res = build_engine(dcop, {}).run_checkpointed(
            max_cycles=120, segment_cycles=7,
            recovery=RecoveryPolicy(trip_cycles=(14,),
                                    noise_seed=seed),
        )
    assert res.metrics["guard_trips"] == 1
    assert res.metrics["recovery_attempts"] == 1
    assert res.converged, "recovered run failed to converge"
    assert_valid_assignment(dcop, res.assignment)
    events = assert_monotone_segments(trace)
    names = {e["name"] for e in events}
    assert "recovery_rollback" in names, \
        "recovery span missing from exported trace"
    return {"trace_events": len(events),
            "actions": res.metrics["recovery_actions"]}


def scenario_guard_noop_device(seed, trace):
    """Guard armed, nothing injected: the guarded trajectory must be
    bit-identical to the unguarded one (guards are pure reads)."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.resilience.recovery import RecoveryPolicy

    dcop = ring_dcop()
    ref = build_engine(dcop, {}).run_checkpointed(
        max_cycles=120, segment_cycles=7)
    res = build_engine(dcop, {}).run_checkpointed(
        max_cycles=120, segment_cycles=7, recovery=RecoveryPolicy())
    assert res.metrics["guard_trips"] == 0
    assert res.assignment == ref.assignment, \
        "guarded run diverged from unguarded with no faults"
    assert res.cycles == ref.cycles
    assert_valid_assignment(dcop, res.assignment)
    return {"cycles": res.cycles}


def scenario_checkpoint_corruption(seed, trace):
    """Torn-write simulation: truncate the newest snapshot mid-file;
    resume must fall back to the previous VALID snapshot and still
    reproduce the uninterrupted run; retention keeps exactly N."""
    from pydcop_tpu.algorithms.maxsum import build_engine
    from pydcop_tpu.resilience.checkpoint import (
        CheckpointManager,
        resume_from_checkpoint,
    )

    dcop = ring_dcop()
    ref = build_engine(dcop, {}).run(max_cycles=120)
    with tempfile.TemporaryDirectory() as ckpt_dir:
        manager = CheckpointManager(ckpt_dir, every=5, keep=2)
        build_engine(dcop, {}).run_checkpointed(
            max_cycles=120, manager=manager, max_segments=3)
        on_disk = manager.checkpoints()
        assert len(on_disk) == 2, (
            f"retention kept {len(on_disk)} snapshots, wanted "
            f"exactly 2")
        newest = on_disk[-1][1]
        with open(newest, "r+b") as f:
            f.truncate(os.path.getsize(newest) // 2)
        res = resume_from_checkpoint(
            build_engine(dcop, {}), manager, max_cycles=120)
        assert res.metrics["resumed_from_cycle"] == on_disk[-2][0], \
            "resume did not fall back to the previous valid snapshot"
        assert res.assignment == ref.assignment
        assert res.cycles == ref.cycles
        assert_valid_assignment(dcop, res.assignment)
        return {"resumed_from": res.metrics["resumed_from_cycle"]}


# Quick-gate ordering: the first 6 cover every failure class (kill
# detection, engine recovery, partition healing, lossy links,
# checkpoint corruption, guard purity).
SCENARIOS = [
    ("kill_detected", scenario_kill_detected),
    ("guard_trip_device", scenario_guard_trip_device),
    ("partition_heal", scenario_partition_heal),
    ("drop_dup_delay", scenario_drop_dup_delay),
    ("checkpoint_corruption", scenario_checkpoint_corruption),
    ("guard_noop_device", scenario_guard_noop_device),
    ("delay_only_no_death", scenario_delay_only_no_death),
    ("drop_plus_kill", scenario_drop_plus_kill),
]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--scenarios", type=int, default=0,
                        help="run only the first N scenarios "
                             "(0 = full matrix)")
    parser.add_argument("--seed", type=int, default=DEFAULT_SEED)
    parser.add_argument("--only", default=None,
                        help="run a single scenario by name (replay)")
    parser.add_argument("--out", default=None,
                        help="directory for per-scenario trace files "
                             "(default: a temp dir)")
    args = parser.parse_args(argv)

    selected = SCENARIOS
    if args.only:
        selected = [s for s in SCENARIOS if s[0] == args.only]
        if not selected:
            names = ", ".join(name for name, _ in SCENARIOS)
            print(f"unknown scenario {args.only!r}; have: {names}")
            return 2
    elif args.scenarios:
        selected = SCENARIOS[:args.scenarios]

    out_dir = args.out or tempfile.mkdtemp(prefix="chaos_soak_")
    os.makedirs(out_dir, exist_ok=True)
    print(f"chaos soak: {len(selected)} scenario(s), "
          f"seed={args.seed}, traces in {out_dir}")
    failures = 0
    t_total = time.perf_counter()
    for name, fn in selected:
        trace = os.path.join(out_dir, f"{name}.trace.json")
        t0 = time.perf_counter()
        try:
            obs = fn(args.seed, trace)
        except Exception as e:
            failures += 1
            print(f"FAIL  {name} ({time.perf_counter() - t0:.1f}s): "
                  f"{e}")
            print(f"      replay: python tools/chaos_soak.py "
                  f"--seed {args.seed} --only {name} "
                  f"--out {out_dir}")
            print(f"      trace:  {trace}  "
                  f"(pydcop trace summary {trace})")
            continue
        print(f"ok    {name} ({time.perf_counter() - t0:.1f}s) {obs}")
    status = "FAIL" if failures else "PASS"
    print(f"chaos soak {status}: {len(selected) - failures}/"
          f"{len(selected)} scenarios in "
          f"{time.perf_counter() - t_total:.1f}s")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

"""Batched multi-instance solving: many DCOPs in ONE XLA program.

A capability the reference architecture cannot express: its benchmark
sweeps (`pydcop batch`) run one subprocess per instance
(pydcop/commands/batch.py), paying process + solve overhead per run.
On device, same-shaped compiled graphs stack into batched arrays and
`jax.vmap` turns the whole MaxSum solve into a single program over the
instance axis — N problems cost barely more than one (the MXU/VPU work
batches; the host launches once).

Shape contract: every instance must compile to identical array shapes
(same variable count, same dmax, same bucket layout) — exactly what
seeded generator sweeps produce (same config, different seeds or cost
tables).  A shape mismatch raises instead of silently padding, so the
caller controls the batching granularity.

This module is ALSO the serving hot path (pydcop_tpu/serving/): the
request scheduler stacks same-structure-bin requests and dispatches
them through :func:`run_stacked`.  Two serving-driven extensions:

- **Padding to bin sizes.** A jitted batched program re-traces per
  batch size, so a scheduler dispatching raw batch sizes 3, 5, 7, 6 …
  would compile a fresh program per straggler count.  ``pad_to_bins``
  rounds the stack up to a fixed ladder of sizes (duplicating the
  last instance; padded lanes are computed and discarded), bounding
  the number of compiled programs per structure to ``len(bins)``.

- **Honest padding accounting.** Padded lanes are wasted device work,
  so every padded dispatch reports ``pad_fraction`` (padded lanes /
  batch size) in ``DeviceRunResult.metrics`` — the serving
  batch-occupancy telemetry reads it instead of guessing.

Two heterogeneous-structure extensions (ISSUE 11) relax the
same-shape contract for the serving tier WITHOUT giving up
bit-identical per-request results:

- **Shape-envelope stacking.** :func:`pad_graph_to_envelope` mask-pads
  a compiled graph up to a shape envelope (serving/binning.Envelope):
  extra domain slots get ``BIG`` cost and ``var_valid=False`` (the
  compile-time domain-padding discipline), extra variable rows are
  dead invalid rows, and extra bucket rows are zero-cost rows pointing
  at the sentinel variable (the PR-7 autopad pattern) — every kernel
  already masks all three, so a padded graph's real variables see
  bit-identical messages.  :func:`stack_to_envelope` pads a
  *different*-structure group to one envelope and stacks it for a
  single vmapped dispatch; ``run_stacked(envelope=...)`` reports
  honest per-lane ``envelope_waste`` next to ``pad_fraction``.

- **Lane packing.** :func:`run_lane_packed` routes a tiny-domain group
  through ops/maxsum_lane instead: the graphs are concatenated into
  one disjoint-union factor graph (factors on the lane axis, no
  per-member shape padding at all — the only mask waste is the shared
  domain rung), solved as one program, and sliced back per member.
"""

import contextlib
import functools
import time
from typing import (
    Any,
    Dict,
    List,
    NamedTuple,
    Optional,
    Sequence,
    Tuple,
)

import jax
import jax.numpy as jnp
import numpy as np

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.compile import (
    BIG,
    CompiledFactorGraph,
    FactorBucket,
    FactorGraphMeta,
    compile_dcop,
)
from pydcop_tpu.engine.runner import (
    DeviceRunResult,
    finish_jit_call,
    launch_jit_call,
    timed_jit_call,
)
from pydcop_tpu.observability import efficiency
from pydcop_tpu.observability.profiler import profiler
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.ops import maxsum as maxsum_ops

# Batch-size ladder used when a caller asks for bin padding without
# giving one: powers of two keep the compiled-program count per
# structure logarithmic in the largest batch.
DEFAULT_BIN_SIZES = (1, 2, 4, 8, 16, 32, 64)

# jit-cache warmth per (shape-signature, solver statics) — feeds the
# cold/warm split in timed_jit_call so serving dispatch latencies can
# separate compile stalls from steady-state batches.
_warm: set = set()


def stack_graphs(
    graphs: Sequence[CompiledFactorGraph],
) -> CompiledFactorGraph:
    """Stack same-shaped compiled graphs along a new leading axis."""
    shapes = [
        (g.var_costs.shape,) + tuple(b.costs.shape for b in g.buckets)
        for g in graphs
    ]
    if any(s != shapes[0] for s in shapes):
        raise ValueError(
            "Batched solving requires identical compiled shapes; got "
            f"{sorted(set(shapes))}"
        )
    return jax.tree.map(lambda *xs: jnp.stack(xs), *graphs)


# Pre-promotion private name, kept for external callers.
_stack_graphs = stack_graphs


def bin_size_for(n: int, bin_sizes: Sequence[int]) -> int:
    """Smallest ladder size >= n; n itself when the ladder tops out
    below it (an oversized dispatch compiles once for its exact size
    rather than failing)."""
    for b in sorted(bin_sizes):
        if b >= n:
            return b
    return n


def pad_to_bin(
    graphs: Sequence[CompiledFactorGraph],
    bin_sizes: Sequence[int] = DEFAULT_BIN_SIZES,
) -> Tuple[List[CompiledFactorGraph], int, float]:
    """Pad a graph list up to the next bin size by repeating the last
    instance.  Returns (padded_graphs, n_real, pad_fraction) — padded
    lanes solve a duplicate problem whose results the caller drops.
    """
    n_real = len(graphs)
    if n_real == 0:
        return [], 0, 0.0
    target = bin_size_for(n_real, bin_sizes)
    padded = list(graphs) + [graphs[-1]] * (target - n_real)
    return padded, n_real, (target - n_real) / target


def _array_cells(graph: CompiledFactorGraph) -> int:
    """Total var-table + bucket-hypercube elements (the waste unit).
    ONE definition, shared with the scheduler's cost model: the
    pack-vs-solo decision (serving/binning.pack_decision) and the
    reported ``envelope_waste`` must never drift apart."""
    from pydcop_tpu.serving.binning import graph_cells

    return graph_cells(graph)


def pad_graph_to_envelope(graph: CompiledFactorGraph,
                          env) -> CompiledFactorGraph:
    """Mask-pad a compiled graph up to a shape envelope
    (serving/binning.Envelope, duck-typed: ``v_env``/``d_env``/
    ``rows``).  Every padding element is inert by the same masking the
    compiler already emits, so the padded graph's real variables
    compute BIT-IDENTICAL messages (battery-asserted):

    - domain slots ``d..d_env``: ``BIG`` cost, ``var_valid=False`` —
      they never win a min-reduction, are excluded from the
      mean-normalization and convergence test, and are masked out of
      the final argmin;
    - variable rows ``v..v_env``: invalid rows no factor references
      (nothing scatters into them, their argmin result is dropped);
    - bucket rows ``F..rows_env``: zero-cost rows whose ``var_ids``
      all point at the sentinel row ``v_env`` (the PR-7 autopad
      pattern — their messages aggregate into the sentinel row, which
      every consumer drops).

    The envelope must COVER the graph (each dimension >= the real
    size, identical arity set) — a violated envelope raises instead of
    silently truncating.  Aggregation arrays are dropped (scatter
    path), matching the serving dispatch's compiled graphs.
    """
    v, d = graph.n_vars, graph.dmax
    by_arity = {b.arity: b for b in graph.buckets}
    env_rows = dict(env.rows)
    if env.v_env < v or env.d_env < d:
        raise ValueError(
            f"envelope (v={env.v_env}, d={env.d_env}) does not cover "
            f"graph (v={v}, d={d})")
    if set(env_rows) != set(by_arity):
        raise ValueError(
            f"envelope arities {sorted(env_rows)} != graph arities "
            f"{sorted(by_arity)}")
    for a, b in by_arity.items():
        if env_rows[a] < b.n_factors:
            raise ValueError(
                f"envelope rows {env_rows[a]} < {b.n_factors} factors "
                f"at arity {a}")
    if (env.v_env == v and env.d_env == d
            and all(env_rows[a] == b.n_factors
                    for a, b in by_arity.items())):
        # Exact fit: nothing to pad, but the drop-aggregation-arrays
        # contract still holds — an exact-fit member stacked next to
        # padded members (agg fields None) must have the same pytree
        # structure, and agg array shapes (e.g. ell's [V+1, K]) are
        # not envelope-determined.
        if all(a is None for a in (graph.agg_perm,
                                   graph.agg_sorted_seg,
                                   graph.agg_starts, graph.agg_ends,
                                   graph.agg_ell)):
            return graph
        return CompiledFactorGraph(
            var_costs=graph.var_costs, var_valid=graph.var_valid,
            buckets=graph.buckets,
        )

    ve, de = env.v_env, env.d_env
    dtype = graph.var_costs.dtype
    var_costs = np.full((ve + 1, de), BIG, dtype=dtype)
    var_costs[:v, :d] = np.asarray(graph.var_costs)[:v]
    var_valid = np.zeros((ve + 1, de), dtype=bool)
    var_valid[:v, :d] = np.asarray(graph.var_valid)[:v]

    buckets = []
    for a in sorted(env_rows):
        b = by_arity[a]
        n_facs = b.n_factors
        costs = np.zeros((env_rows[a],) + (de,) * a,
                         dtype=b.costs.dtype)
        if n_facs:
            block = np.full((n_facs,) + (de,) * a, BIG,
                            dtype=b.costs.dtype)
            block[(slice(None),) + (slice(0, d),) * a] = \
                np.asarray(b.costs)
            costs[:n_facs] = block
        ids = np.full((env_rows[a], a), ve, dtype=np.int32)
        real_ids = np.asarray(b.var_ids).copy()
        # Re-point the graph's own sentinel (index v) at the
        # envelope's (index ve) — compile-time padding rows must stay
        # masked after the variable table grows.
        real_ids[real_ids == v] = ve
        ids[:n_facs] = real_ids
        buckets.append(FactorBucket(costs=costs, var_ids=ids))
    return CompiledFactorGraph(
        var_costs=var_costs, var_valid=var_valid,
        buckets=tuple(buckets),
    )


def stack_to_envelope(
    graphs: Sequence[CompiledFactorGraph], env,
) -> Tuple[List[CompiledFactorGraph], List[float]]:
    """Pad a *different*-structure group up to one shape envelope so
    it stacks (``stack_graphs``) into a single vmapped dispatch.
    Returns ``(padded_graphs, envelope_waste)`` — per-member wasted
    fraction of the envelope's cells (``1 - real/envelope``), the
    honest-padding number ``run_stacked`` reports per dispatch."""
    padded = [pad_graph_to_envelope(g, env) for g in graphs]
    waste = [
        round(1.0 - _array_cells(g) / max(_array_cells(p), 1), 4)
        for g, p in zip(graphs, padded)
    ]
    return padded, waste


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_cycles", "damping", "damp_vars", "damp_factors",
        "stability", "prune",
    ),
)
def _batched_solve(stacked, *, max_cycles, damping, damp_vars,
                   damp_factors, stability, prune=False):
    """One jitted program per solver-parameter combination (jit's own
    cache keys on the static args), reused across calls — a fresh
    closure per call would retrace and recompile every time.

    ``prune`` threads branch-and-bound pruning into each lane.  Under
    vmap the per-lane phase predicates batch, so the dense/compacted
    alternation degrades toward evaluating both sides more often than
    the solo engine would — the decision consumed here
    (serving/service: prune="auto") was raced on the SOLO path, where
    the win is largest; results are identical either way."""

    def solve_one(graph):
        state, values = maxsum_ops.run_maxsum(
            graph, max_cycles,
            damping=damping,
            damp_vars=damp_vars,
            damp_factors=damp_factors,
            stability=stability,
            stop_on_convergence=False,
            prune=prune,
        )
        return values, state.cycle, state.stable

    return jax.vmap(solve_one)(stacked)


def _shape_signature(stacked: CompiledFactorGraph) -> tuple:
    return (
        (stacked.var_costs.shape,)
        + tuple(b.costs.shape for b in stacked.buckets)
    )


# The rollup's per-structure cell label (ONE definition, shared with
# the dynamic engine — observability/efficiency.py).
_structure_label = efficiency.structure_label


class _StackedPrep(NamedTuple):
    """Host-side assembly of one stacked dispatch — everything the
    decode/accounting tail needs, shared by the synchronous
    (:func:`run_stacked`) and pipelined (:func:`launch_stacked` /
    :func:`collect_stacked`) paths so the two cannot drift."""

    graphs: tuple
    stacked: CompiledFactorGraph
    statics: dict
    key: tuple
    n_real: int
    pad_fraction: float
    envelope_waste: Optional[List[float]]
    max_cycles: int
    t_pack: float


class PendingDispatch(NamedTuple):
    """A launched-but-uncollected device dispatch (JAX async
    dispatch): the device is executing while the host does other work.
    Produced by :func:`launch_stacked` / :func:`launch_lane_packed`,
    consumed exactly once by the matching ``collect_*``."""

    kind: str         # "stacked" | "lane"
    raw: Any          # launched device outputs (futures)
    prep: Any
    key: tuple
    t_launch: float


def _prepare_stacked(graphs, max_cycles, damping, damping_nodes,
                     stability, pad_to_bins, prune,
                     envelope) -> _StackedPrep:
    if not graphs:
        raise ValueError("run_stacked needs at least one graph")
    t_pack = time.perf_counter()
    envelope_waste: Optional[List[float]] = None
    if envelope is not None:
        graphs, envelope_waste = stack_to_envelope(graphs, envelope)
    n_real = len(graphs)
    pad_fraction = 0.0
    if pad_to_bins is not None:
        graphs, n_real, pad_fraction = pad_to_bin(graphs, pad_to_bins)
    stacked = stack_graphs(graphs)
    statics = dict(
        max_cycles=max_cycles,
        damping=damping,
        damp_vars=damping_nodes in ("vars", "both"),
        damp_factors=damping_nodes in ("factors", "both"),
        stability=stability,
        prune=prune,
    )
    key = (
        "maxsum_batch", len(graphs), _shape_signature(stacked),
        tuple(sorted(statics.items())),
    )
    return _StackedPrep(tuple(graphs), stacked, statics, key, n_real,
                        pad_fraction, envelope_waste, max_cycles,
                        t_pack)


def _finish_stacked(prep: _StackedPrep, values, cycles, stable,
                    elapsed: float, compile_s: float, run_s: float,
                    t0: float, pipelined: bool = False):
    """Decode + accounting tail shared by both dispatch paths: ONE
    coalesced ``device_get`` for the whole output pytree (one host
    sync per dispatch instead of three), then the DeviceRunResult
    metrics and the efficiency-plane dispatch sample."""
    values, cycles, stable = jax.device_get((values, cycles, stable))
    n_real = prep.n_real
    values = np.asarray(values)[:n_real]
    cycles = np.asarray(cycles)[:n_real]
    stable = np.asarray(stable)[:n_real]
    batch_result = DeviceRunResult(
        assignment={},
        cycles=int(cycles.max()) if cycles.size else 0,
        converged=bool(stable.all()) if stable.size else False,
        time_s=elapsed,
        compile_time_s=compile_s,
        metrics={
            "batch_size": len(prep.graphs),
            "n_real": n_real,
            "pad_fraction": prep.pad_fraction,
            "cold_start": compile_s > 0.0,
            "run_time_s": run_s,
            # Host-side batch assembly (envelope padding + stacking),
            # the ledger's ``prep`` share of this dispatch.
            "pack_host_s": t0 - prep.t_pack,
            # Per-request convergence verdicts (real lanes, dispatch
            # order): the serve plane folds lane i's flag into
            # request i's result.
            "converged_lanes": [bool(s) for s in stable],
            # Total device cells of the dispatched stack (padding
            # lanes included) and the jit program key: the
            # self-tuning pack planner regresses measured execute
            # walls on cells, and the speculative compiler matches
            # completed programs against its precompiled set.
            "cells_total": (_array_cells(prep.graphs[0])
                            * len(prep.graphs)),
            "program_key": str(prep.key),
        },
    )
    if pipelined:
        batch_result.metrics["pipelined"] = True
    if prep.envelope_waste is not None:
        envelope_waste = prep.envelope_waste
        batch_result.metrics["packing"] = "envelope"
        batch_result.metrics["envelope_waste_lanes"] = envelope_waste
        batch_result.metrics["envelope_waste"] = round(
            sum(envelope_waste) / len(envelope_waste), 4
        ) if envelope_waste else 0.0
    # Efficiency accounting: every batched dispatch is an attainment
    # sample — all lanes run the full max_cycles budget (no early
    # stop on the batched path), so the XLA per-iteration cost entry
    # scales by exactly max_cycles.  Everything (labels, backend
    # resolution) stays behind the enabled gate: PYDCOP_EFFICIENCY=0
    # must mean zero work, not discarded work.
    if efficiency.tracker.enabled:
        # Structure label AFTER envelope padding: a packed dispatch
        # runs ONE compiled envelope shape — labeling by whichever
        # member happened to be first would scatter the same program
        # across structure cells (the lane path labels its packed
        # union the same way).
        record = efficiency.tracker.record_dispatch(
            key=str(prep.key),
            structure=_structure_label(prep.graphs[0]),
            backend=efficiency.backend_name(),
            # The INNER device wall (sync-honest), not the outer
            # elapsed: the outer interval also holds the profiler's
            # one-off AOT capture on cold dispatches, which is host
            # work, not device attainment denominator.
            time_s=run_s, compile_s=compile_s, cycles=prep.max_cycles,
            n_real=n_real, batch_size=len(prep.graphs),
            pad_fraction=prep.pad_fraction,
            envelope_waste=batch_result.metrics.get(
                "envelope_waste", 0.0) or 0.0,
            packing=batch_result.metrics.get("packing") or (
                "batched" if n_real > 1 else "solo"),
            cost_entry=(profiler.get(prep.key)
                        if profiler.enabled else None),
        )
        if record is not None:
            batch_result.metrics["efficiency"] = record
    return values, cycles, batch_result


def launch_stacked(
    graphs: Sequence[CompiledFactorGraph],
    max_cycles: int = 200,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
    pad_to_bins: Optional[Sequence[int]] = None,
    prune: bool = False,
    envelope=None,
) -> Optional[PendingDispatch]:
    """Async-launch a stacked dispatch without waiting for results
    (the pipelined serving flush: dispatch k+1 launches while k's
    arrays are still in flight).  Returns ``None`` when the program
    is COLD — trace+compile must stay on the synchronous
    :func:`run_stacked` path where the profiler/aotcache cold-call
    attribution lives — and the caller falls back."""
    prep = _prepare_stacked(graphs, max_cycles, damping,
                            damping_nodes, stability, pad_to_bins,
                            prune, envelope)
    if prep.key not in _warm:
        return None
    t0 = time.perf_counter()
    raw = launch_jit_call(
        _warm, prep.key,
        functools.partial(_batched_solve, **prep.statics),
        prep.stacked)
    return PendingDispatch("stacked", raw, prep, prep.key, t0)


def collect_stacked(pending: PendingDispatch):
    """Force completion of a :func:`launch_stacked` dispatch and run
    the shared decode/accounting tail.  Returns the same
    ``(values, cycles, batch_result)`` triple as :func:`run_stacked`;
    ``run_time_s`` is the honest launch-to-completion device wall."""
    prep: _StackedPrep = pending.prep
    span = (tracer.span("engine_segment", "engine",
                        batch_size=len(prep.graphs),
                        n_real=prep.n_real, from_cycle=0,
                        extra_cycles=prep.max_cycles, pipelined=True)
            if tracer.active else None)
    with (span if span is not None else contextlib.nullcontext()):
        (values, cycles, stable), run_s = finish_jit_call(
            pending.key, pending.raw, pending.t_launch)
    elapsed = time.perf_counter() - pending.t_launch
    return _finish_stacked(prep, values, cycles, stable, elapsed,
                           0.0, run_s, pending.t_launch,
                           pipelined=True)


def run_stacked(
    graphs: Sequence[CompiledFactorGraph],
    max_cycles: int = 200,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
    pad_to_bins: Optional[Sequence[int]] = None,
    prune: bool = False,
    envelope=None,
) -> Tuple[np.ndarray, np.ndarray, DeviceRunResult]:
    """One device dispatch over a stack of same-shaped compiled graphs.

    The serving hot path: all instances run ``max_cycles`` cycles (no
    convergence stop — a data-dependent loop bound would serialize the
    batch; converged instances freeze via send suppression, so extra
    cycles don't change their assignment).  With ``pad_to_bins`` the
    stack is padded up the bin ladder first (see module docstring).

    Returns ``(values, cycles, batch_result)``: per-instance selected
    value indices / cycle counts for the first ``n_real`` lanes
    (padding lanes already dropped), plus a batch-level
    :class:`DeviceRunResult` whose ``metrics`` carry the dispatch
    accounting — ``batch_size``, ``n_real``, ``pad_fraction``,
    ``cold_start`` — and whose ``assignment`` is empty (a batch has no
    single assignment; decode per instance via each meta).

    ``envelope`` (a serving/binning.Envelope) lifts the same-shape
    contract: every graph is mask-padded to the envelope's shapes
    first (:func:`stack_to_envelope`), so *different*-structure
    problems share the dispatch with bit-identical per-instance
    results; the metrics then additionally carry ``envelope_waste``
    (mean padded-cell fraction over real lanes) and
    ``envelope_waste_lanes`` (per lane, dispatch order).
    """
    prep = _prepare_stacked(graphs, max_cycles, damping,
                            damping_nodes, stability, pad_to_bins,
                            prune, envelope)
    t0 = time.perf_counter()
    # A batched dispatch IS one engine segment (the whole solve in
    # one program): the span name matches the segmented loop's so
    # request-scoped trace queries see a uniform engine layer —
    # under a serve dispatch the thread-bound trace context stamps
    # the batch's trace_ids onto it.
    span = (tracer.span("engine_segment", "engine",
                        batch_size=len(prep.graphs),
                        n_real=prep.n_real,
                        from_cycle=0, extra_cycles=max_cycles)
            if tracer.active else None)
    with (span if span is not None else contextlib.nullcontext()):
        (values, cycles, stable), compile_s, run_s = timed_jit_call(
            _warm, prep.key,
            functools.partial(_batched_solve, **prep.statics),
            prep.stacked,
        )
    elapsed = time.perf_counter() - t0
    return _finish_stacked(prep, values, cycles, stable, elapsed,
                           compile_s, run_s, t0)


@functools.partial(
    jax.jit,
    static_argnames=(
        "max_cycles", "damping", "damp_vars", "damp_factors",
        "stability",
    ),
)
def _lane_packed_solve(lane, *, max_cycles, damping, damp_vars,
                       damp_factors, stability):
    """One jitted lane-major solve of a packed union (see
    ``run_lane_packed``); the suppression counters ride out so the
    host can recover per-member convergence verdicts."""
    from pydcop_tpu.ops import maxsum_lane as lane_ops

    state, values = lane_ops.run_maxsum(
        lane, max_cycles,
        damping=damping, damp_vars=damp_vars,
        damp_factors=damp_factors, stability=stability,
        stop_on_convergence=False,
    )
    return values, state.cycle, state.v2f_count, state.f2v_count


def run_lane_packed(
    graphs: Sequence[CompiledFactorGraph],
    max_cycles: int = 200,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
    d_env: Optional[int] = None,
    ladder=None,
) -> Tuple[List[np.ndarray], np.ndarray, DeviceRunResult]:
    """One device dispatch over a lane-packed DISJOINT UNION of
    different-structure graphs (ops/maxsum_lane.pack_graphs): members
    concatenate along the variable axis and each arity's factor/lane
    axis instead of padding to a common hypercube, so heterogeneous
    ``v_count``/factor counts carry no mask waste at all — only the
    shared domain rung ``d_env`` (default: the group's max) is padded.
    The tiny-domain route of the serving envelope tier
    (docs/serving.md "Envelope batching").

    ``ladder`` (a serving/binning.EnvelopeLadder) additionally rounds
    the union's variable/row counts up the ladder with masked sentinel
    rows, bounding the number of compiled union programs under
    changing group compositions.

    Returns ``(values, cycles, batch_result)`` like ``run_stacked``,
    with ``values`` a per-member list (members have different variable
    counts).  ``converged_lanes`` holds honest per-member verdicts
    recovered from the suppression counters
    (ops/maxsum_lane.converged_per_graph)."""
    prep = _prepare_lane(graphs, max_cycles, damping, damping_nodes,
                         stability, d_env, ladder)
    t0 = time.perf_counter()
    span = (tracer.span("engine_segment", "engine",
                        batch_size=len(graphs), n_real=len(graphs),
                        packing="lane", from_cycle=0,
                        extra_cycles=max_cycles)
            if tracer.active else None)
    with (span if span is not None else contextlib.nullcontext()):
        (values, cycle, v2f_count, f2v_count), compile_s, run_s = \
            timed_jit_call(
                _warm, prep.key,
                functools.partial(_lane_packed_solve, **prep.statics),
                prep.lane,
            )
    elapsed = time.perf_counter() - t0
    return _finish_lane(prep, values, cycle, v2f_count, f2v_count,
                        elapsed, compile_s, run_s, t0)


class _LanePrep(NamedTuple):
    """Host-side assembly of one lane-packed dispatch (see
    :class:`_StackedPrep`)."""

    graphs: tuple
    union: CompiledFactorGraph
    layout: Any
    lane: Any
    statics: dict
    key: tuple
    max_cycles: int
    t_pack: float


def _prepare_lane(graphs, max_cycles, damping, damping_nodes,
                  stability, d_env, ladder) -> _LanePrep:
    from pydcop_tpu.ops import maxsum_lane as lane_ops

    if not graphs:
        raise ValueError("run_lane_packed needs at least one graph")
    t_pack = time.perf_counter()
    union, layout = lane_ops.pack_graphs(graphs, d_env=d_env)
    if ladder is not None:
        from pydcop_tpu.serving.binning import envelope_key

        # Ladder-round the union's variable/row counts so group
        # compositions reuse compiled programs — but KEEP the exact
        # domain: the caller grouped by domain rung already, and
        # rounding d again would charge every member the rung's
        # hypercube blowup the lane pack exists to avoid.
        union = pad_graph_to_envelope(
            union,
            envelope_key(union, ladder)._replace(d_env=union.dmax))
    lane = lane_ops.to_lane_graph(union)
    statics = dict(
        max_cycles=max_cycles,
        damping=damping,
        damp_vars=damping_nodes in ("vars", "both"),
        damp_factors=damping_nodes in ("factors", "both"),
        stability=stability,
    )
    key = (
        "maxsum_lane_pack",
        (lane.var_costs.shape,)
        + tuple(b.costs.shape for b in lane.buckets),
        tuple(sorted(statics.items())),
    )
    return _LanePrep(tuple(graphs), union, layout, lane, statics,
                     key, max_cycles, t_pack)


def _finish_lane(prep: _LanePrep, values, cycle, v2f_count,
                 f2v_count, elapsed: float, compile_s: float,
                 run_s: float, t0: float, pipelined: bool = False):
    from pydcop_tpu.ops import maxsum_lane as lane_ops

    graphs = prep.graphs
    # ONE coalesced device_get for the whole output pytree (the old
    # path paid 4 separate host syncs per dispatch).
    values, cycle, v2f_count, f2v_count = jax.device_get(
        (values, cycle, v2f_count, f2v_count))
    values = np.asarray(values)
    per_values = [values[s:s + n] for s, n in prep.layout.var_slices]
    converged = lane_ops.converged_per_graph(
        v2f_count, f2v_count, prep.layout)
    n_cycles = int(cycle)
    cycles = np.full((len(graphs),), n_cycles, dtype=np.int32)
    # Honest waste accounting: members carry only domain-rung padding;
    # the union-level ladder rounding (sentinel rows) is shared
    # dispatch overhead, reported in the dispatch-level figure.
    from pydcop_tpu.serving.binning import lane_cells

    real_cells = [_array_cells(g) for g in graphs]
    union_cells = max(_array_cells(prep.union), 1)
    member_cells = [lane_cells(g, prep.lane.dmax) for g in graphs]
    lane_waste = [
        round(1.0 - r / max(m, 1), 4)
        for r, m in zip(real_cells, member_cells)
    ]
    batch_result = DeviceRunResult(
        assignment={},
        cycles=n_cycles,
        converged=all(converged),
        time_s=elapsed,
        compile_time_s=compile_s,
        metrics={
            "batch_size": len(graphs),
            "n_real": len(graphs),
            "pad_fraction": 0.0,
            "cold_start": compile_s > 0.0,
            "run_time_s": run_s,
            "pack_host_s": t0 - prep.t_pack,
            "packing": "lane",
            "converged_lanes": [bool(c) for c in converged],
            "envelope_waste_lanes": lane_waste,
            "envelope_waste": round(
                1.0 - sum(real_cells) / union_cells, 4),
            "cells_total": union_cells,
            "program_key": str(prep.key),
        },
    )
    if pipelined:
        batch_result.metrics["pipelined"] = True
    if efficiency.tracker.enabled:
        record = efficiency.tracker.record_dispatch(
            key=str(prep.key), structure=_structure_label(prep.union),
            backend=efficiency.backend_name(),
            time_s=run_s, compile_s=compile_s, cycles=prep.max_cycles,
            n_real=len(graphs), batch_size=len(graphs),
            pad_fraction=0.0,
            envelope_waste=batch_result.metrics["envelope_waste"],
            packing="lane",
            cost_entry=(profiler.get(prep.key)
                        if profiler.enabled else None),
        )
        if record is not None:
            batch_result.metrics["efficiency"] = record
    return per_values, cycles, batch_result


def launch_lane_packed(
    graphs: Sequence[CompiledFactorGraph],
    max_cycles: int = 200,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
    d_env: Optional[int] = None,
    ladder=None,
) -> Optional[PendingDispatch]:
    """Async-launch a lane-packed dispatch (see
    :func:`launch_stacked`); ``None`` when the union program is cold —
    compile stays on the synchronous path."""
    prep = _prepare_lane(graphs, max_cycles, damping, damping_nodes,
                         stability, d_env, ladder)
    if prep.key not in _warm:
        return None
    t0 = time.perf_counter()
    raw = launch_jit_call(
        _warm, prep.key,
        functools.partial(_lane_packed_solve, **prep.statics),
        prep.lane)
    return PendingDispatch("lane", raw, prep, prep.key, t0)


def collect_lane_packed(pending: PendingDispatch):
    """Force completion of a :func:`launch_lane_packed` dispatch and
    run the shared decode/accounting tail."""
    prep: _LanePrep = pending.prep
    span = (tracer.span("engine_segment", "engine",
                        batch_size=len(prep.graphs),
                        n_real=len(prep.graphs), packing="lane",
                        from_cycle=0, extra_cycles=prep.max_cycles,
                        pipelined=True)
            if tracer.active else None)
    with (span if span is not None else contextlib.nullcontext()):
        (values, cycle, v2f_count, f2v_count), run_s = \
            finish_jit_call(pending.key, pending.raw,
                            pending.t_launch)
    elapsed = time.perf_counter() - pending.t_launch
    return _finish_lane(prep, values, cycle, v2f_count, f2v_count,
                        elapsed, 0.0, run_s, pending.t_launch,
                        pipelined=True)


def solve_maxsum_batch(
    dcops: Sequence[DCOP],
    max_cycles: int = 200,
    noise_level: float = 0.01,
    damping: float = 0.5,
    damping_nodes: str = "both",
    stability: float = 0.1,
    pad_to_bins: Optional[Sequence[int]] = None,
) -> List[Dict]:
    """Solve a batch of same-shaped DCOPs in one vmapped program.

    Returns one dict per instance: assignment, cost (host-evaluated),
    cycles.  All instances run ``max_cycles`` cycles (no convergence
    stop: a data-dependent loop bound would serialize the batch).
    ``pad_to_bins`` pads the stack up a bin-size ladder so a sweep of
    ragged batch sizes reuses a bounded set of compiled programs; the
    shared dispatch accounting (incl. ``pad_fraction``) rides along in
    each result's ``batch`` key.
    """
    if not dcops:
        return []
    # Same-structured instances (same graph, different cost tables —
    # the repeated-traffic serving pattern) are exactly what the
    # structure-keyed compile cache serves: instance 1 builds the
    # layout/agg arrays, instances 2..N reuse them
    # (engine/compile.CompileCache), matching the device side where
    # vmap already made N solves cost barely more than one.
    compiled: List[Tuple[CompiledFactorGraph, FactorGraphMeta]] = [
        compile_dcop(d, noise_level=noise_level) for d in dcops
    ]
    graphs = [c[0] for c in compiled]
    metas = [c[1] for c in compiled]

    values, cycles, batch_result = run_stacked(
        graphs,
        max_cycles=max_cycles,
        damping=damping,
        damping_nodes=damping_nodes,
        stability=stability,
        pad_to_bins=pad_to_bins,
    )

    results = []
    for i, (dcop, meta) in enumerate(zip(dcops, metas)):
        assignment = meta.assignment_from_indices(values[i])
        cost, violations = dcop.solution_cost(assignment)
        results.append({
            "assignment": assignment,
            "cost": cost,
            "violations": violations,
            "cycles": int(cycles[i]),
            "batch": dict(batch_result.metrics),
        })
    return results

"""Scale acceptance: a SECP-style large factor graph solved sharded
over the 8-device virtual mesh, matching the unsharded solution.

SURVEY.md §7.6's acceptance shape (100k-factor SECP sharded over a
v5e-8), scaled down for CI wall-clock: the structure (many binary
factors, mesh-padded buckets, replicated variable tables, one
all-reduce per superstep) is identical; only the factor count differs.
"""

import numpy as np
import pytest

from pydcop_tpu.engine.compile import compile_factor_graph
from pydcop_tpu.engine.sharding import make_mesh, shard_graph
from pydcop_tpu.ops.maxsum import run_maxsum
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation

import jax


N_VARS = 2_000
N_FACTORS = 3_000
N_COLORS = 3


def _big_problem():
    rng = np.random.default_rng(7)
    domain = Domain("colors", "", list(range(N_COLORS)))
    variables = [Variable(f"v{i}", domain) for i in range(N_VARS)]
    eq_penalty = np.eye(N_COLORS, dtype=np.float64)
    constraints = []
    seen = set()
    k = 0
    while len(constraints) < N_FACTORS:
        i, j = rng.choice(N_VARS, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        constraints.append(NAryMatrixRelation(
            [variables[i], variables[j]], eq_penalty, f"c{k}"
        ))
        k += 1
    return variables, constraints


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)
def test_sharded_matches_unsharded():
    variables, constraints = _big_problem()
    mesh = make_mesh(8)

    # Tie-breaking noise (maxsum's `noise` param): without it the
    # fully-symmetric problem degenerates to everyone picking slot 0.
    graph1, meta = compile_factor_graph(
        variables, constraints, noise_level=0.01, noise_seed=1
    )
    state1, values1 = jax.jit(
        lambda g: run_maxsum(g, 120, stop_on_convergence=False)
    )(jax.device_put(graph1))

    graph8, _ = compile_factor_graph(
        variables, constraints, noise_level=0.01, noise_seed=1,
        pad_to=mesh.size,
    )
    graph8 = shard_graph(graph8, mesh)
    state8, values8 = jax.jit(
        lambda g: run_maxsum(g, 120, stop_on_convergence=False)
    )(graph8)

    values1 = np.asarray(values1)
    values8 = np.asarray(values8)

    def conflicts(values):
        n = 0
        for c in constraints:
            i, j = (int(v.name[1:]) for v in c.dimensions)
            n += int(values[i] == values[j])
        return n

    # Sharding must not change the computation: same message fixpoint,
    # same selected values.
    assert np.array_equal(values1, values8)
    # And the solution must be good: far fewer conflicts than random
    # (random 3-coloring conflicts ~ N_FACTORS / 3).
    assert conflicts(values1) < N_FACTORS / 30


@pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs the 8-device virtual mesh"
)
def test_sharded_bucket_autopad():
    """Regression (ISSUE 7 satellite): bucket rows not divisible by
    the mesh size used to raise a hard ValueError demanding
    ``pad_to=mesh.size`` at compile time; shard_graph now auto-pads
    with masked sentinel rows and the padded sharded run matches the
    unsharded one exactly."""
    variables, constraints = _big_problem()
    constraints = constraints[:1001]  # 1001 rows: not divisible by 8
    mesh = make_mesh(8)
    graph, _ = compile_factor_graph(
        variables, constraints, noise_level=0.01, noise_seed=1)
    assert graph.buckets[0].costs.shape[0] % mesh.size != 0
    placed = shard_graph(graph, mesh)
    assert placed.buckets[0].costs.shape[0] % mesh.size == 0
    # Padding rows carry zero cost and sentinel var ids.
    pad_rows = np.asarray(placed.buckets[0].var_ids)[1001:]
    assert (pad_rows == len(variables)).all()
    assert np.asarray(placed.buckets[0].costs)[1001:].sum() == 0.0

    state1, values1 = jax.jit(
        lambda g: run_maxsum(g, 40, stop_on_convergence=False)
    )(jax.device_put(graph))
    state8, values8 = jax.jit(
        lambda g: run_maxsum(g, 40, stop_on_convergence=False)
    )(placed)
    assert np.array_equal(np.asarray(values1), np.asarray(values8))
    assert int(state1.cycle) == int(state8.cycle)

"""CSV metrics writer: reference column schema (solve.py:386-443) and
append/header semantics."""

import csv

from pydcop_tpu.commands.metrics_io import COLUMNS, add_csvline


def test_reference_column_schema():
    assert COLUMNS == [
        "time", "cycle", "cost", "violation", "msg_count", "msg_size",
        "status",
    ]


def test_header_written_once_then_appends(tmp_path):
    p = tmp_path / "m.csv"
    add_csvline(str(p), "cycle_change",
                {"time": 0.5, "cycle": 1, "cost": 10.0, "violation": 0,
                 "msg_count": 4, "msg_size": 4, "status": "RUNNING"})
    add_csvline(str(p), "cycle_change",
                {"time": 1.0, "cycle": 2, "cost": 7.0, "violation": 0,
                 "msg_count": 8, "msg_size": 8, "status": "RUNNING"})
    rows = list(csv.reader(p.open()))
    assert rows[0] == COLUMNS
    assert len(rows) == 3
    assert rows[1][1] == "1" and rows[2][1] == "2"
    assert rows[2][2] == "7.0"


def test_missing_keys_become_empty_cells(tmp_path):
    p = tmp_path / "m.csv"
    add_csvline(str(p), "value_change", {"cycle": 3})
    rows = list(csv.reader(p.open()))
    assert rows[1][0] == "" and rows[1][1] == "3"

"""``pydcop solve``: one-shot local solve of a static DCOP.

Reference parity: pydcop/commands/solve.py (run_cmd :444, result JSON
keys :611-632: status/assignment/cost/violation/time/msg_count/msg_size/
cycle/agt_metrics).  Modes: ``--mode device`` (default — batched engine
on TPU/CPU), ``--mode thread`` / ``--mode process`` (agent runtime,
reference semantics).
"""

import argparse
import logging
import time

from pydcop_tpu.commands._utils import build_algo_def, emit_result

logger = logging.getLogger("pydcop.cli.solve")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "solve", help="solve a static DCOP")
    parser.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    parser.add_argument("-a", "--algo", required=True,
                        help="algorithm name, or 'auto' (device mode) "
                             "to race the whole-algorithm portfolio "
                             "on the compiled graph and solve with "
                             "the winner — decision cached by "
                             "structure signature "
                             "(docs/performance.md)")
    parser.add_argument("-p", "--algo_params", action="append",
                        help="algorithm parameter as name:value")
    parser.add_argument("-d", "--distribution", default="oneagent",
                        help="distribution method or file")
    parser.add_argument("-m", "--mode", default="device",
                        choices=["device", "thread", "process"],
                        help="execution mode")
    parser.add_argument("-c", "--cycles", type=int, default=1000,
                        help="max cycles (device/synchronous modes)")
    parser.add_argument("--n_devices", type=int, default=None,
                        help="replicated-variable sharding: row-shard "
                             "factor buckets over this many devices "
                             "(device mode, any algorithm)")
    parser.add_argument("--scenario", default=None, metavar="FILE",
                        help="dynamic DCOP: replay this scenario "
                             "yaml's events (dcop/scenario.py "
                             "vocabulary — change/add/remove factor, "
                             "add variable, agent placement) through "
                             "the incremental DynamicMaxSumEngine "
                             "after the initial solve converges — "
                             "warm-started between events, zero "
                             "recompiles while the shape survives "
                             "(device mode, maxsum family; "
                             "docs/sessions.md)")
    parser.add_argument("--scenario_event_cycles",
                        "--scenario-event-cycles",
                        type=int, default=None, metavar="CYCLES",
                        help="re-convergence cycle budget per "
                             "scenario event (default: --cycles)")
    parser.add_argument("--shards", type=int, default=None,
                        help="partitioned sharding (device mode, "
                             "maxsum family): min-edge-cut partition "
                             "of the factor graph, per-shard variable "
                             "slices, halo-only exchange — O(cut*D) "
                             "per-superstep communication instead of "
                             "O(V*D) (docs/sharding.md); mutually "
                             "exclusive with --n_devices")
    parser.add_argument("--collect_on", default="value_change",
                        choices=["value_change", "cycle_change", "period"])
    parser.add_argument("--period", type=float, default=1.0)
    parser.add_argument("--run_metrics", default=None,
                        help="csv file for run metrics")
    parser.add_argument("--end_metrics", default=None,
                        help="csv file for end metrics")
    parser.add_argument("--infinity", type=float, default=float("inf"))
    parser.add_argument("--uiport", type=int, default=None,
                        help="first websocket UI port (one per agent, "
                             "thread mode)")
    parser.add_argument("--trace", default=None,
                        help="trace file for the run; format chosen "
                             "by --trace_format (docs/observability"
                             ".md)")
    parser.add_argument("--trace_format", "--trace-format",
                        default="chrome",
                        choices=["chrome", "jsonl", "csv"],
                        help="chrome: trace_event JSON for "
                             "chrome://tracing / Perfetto; jsonl: one "
                             "event per line; csv: legacy per-step "
                             "rows (thread mode, infrastructure/"
                             "stats.py)")
    parser.add_argument("--metrics", default=None,
                        help="JSONL metrics-snapshot file; a "
                             "Prometheus text dump is written next to "
                             "it (<file>.prom)")
    parser.add_argument("--metrics_every", "--metrics-every",
                        type=int, default=100,
                        help="cycles between metrics snapshots (device "
                             "mode: also the engine chunk size)")
    parser.add_argument("--serve_metrics", "--serve-metrics",
                        type=int, default=None, metavar="PORT",
                        help="serve live telemetry over HTTP while "
                             "the solve runs: /metrics (Prometheus "
                             "text), /healthz, /events (SSE cycle/"
                             "cost stream); PORT 0 = OS-assigned, "
                             "printed on stderr "
                             "(docs/observability.md)")
    parser.add_argument("--flight_recorder_events",
                        "--flight-recorder-events",
                        type=int, default=None, metavar="N",
                        help="size of the always-on flight-recorder "
                             "ring (trace events kept for postmortem "
                             "bundles; 0 disables; default: "
                             "PYDCOP_FLIGHT_RECORDER or 2048 — "
                             "docs/observability.md)")
    parser.add_argument("--profile", default=None,
                        help="device mode: write a JAX profiler trace "
                             "of the solve to this directory (inspect "
                             "with TensorBoard / xprof)")
    parser.add_argument("--delay", type=float, default=None,
                        help="delay (s) between message deliveries — "
                             "for observing algorithms live, e.g. with "
                             "--uiport (thread/process modes; "
                             "reference solve --delay)")
    # Resilience knobs (docs/resilience.md).
    parser.add_argument("--checkpoint_dir", default=None,
                        help="device mode: snapshot solver state to "
                             "this directory between segments")
    parser.add_argument("--checkpoint_every", type=int, default=100,
                        help="cycles per checkpoint segment")
    parser.add_argument("--checkpoint_async",
                        action=argparse.BooleanOptionalAction,
                        default=True,
                        help="write snapshots on a background thread "
                             "overlapping device compute (default; "
                             "--no-checkpoint_async restores the "
                             "synchronous write between segments)")
    parser.add_argument("--checkpoint_keep", type=int, default=2,
                        help="keep-last-N checkpoint retention (the "
                             "newest valid snapshot is never pruned)")
    parser.add_argument("--resume", action="store_true",
                        help="device mode: continue from the newest "
                             "VALID checkpoint in --checkpoint_dir "
                             "(corrupt/truncated snapshots are "
                             "skipped with a warning)")
    # Self-healing knobs (docs/resilience.md).
    parser.add_argument("--recovery", action="store_true",
                        help="device mode: arm segment-boundary "
                             "guards (NaN/Inf scan) with rollback-"
                             "and-recover on a trip")
    parser.add_argument("--recovery_max_restarts", type=int, default=3,
                        help="restart budget before RecoveryExhausted")
    parser.add_argument("--recovery_noise", type=float, default=1e-3,
                        help="tie-break noise scale of the first "
                             "recovery escalation")
    parser.add_argument("--recovery_damping_bump", type=float,
                        default=0.2,
                        help="damping increase of the second recovery "
                             "escalation")
    parser.add_argument("--health", action="store_true",
                        help="thread mode: heartbeat failure "
                             "detection (phi-accrual suspicion, "
                             "bounded death verdicts feeding repair)")
    parser.add_argument("--health_interval", type=float, default=0.05,
                        help="seconds between agent heartbeats")
    parser.add_argument("--health_suspect_misses", type=float,
                        default=3.0,
                        help="missed intervals before an agent is "
                             "suspect")
    parser.add_argument("--health_dead_misses", type=float,
                        default=8.0,
                        help="missed intervals before an agent is "
                             "declared dead (the detection bound)")
    parser.add_argument("--fault_seed", type=int, default=0,
                        help="seed for deterministic fault injection "
                             "(thread mode)")
    parser.add_argument("--fault_drop", type=float, default=0.0,
                        help="per-message drop probability")
    parser.add_argument("--fault_dup", type=float, default=0.0,
                        help="per-message duplication probability")
    parser.add_argument("--fault_delay", type=float, default=0.0,
                        help="per-message delay probability")
    parser.add_argument("--fault_delay_time", type=float, default=0.05,
                        help="delay (s) applied to delayed messages")
    parser.add_argument("--fault_kill", action="append", default=None,
                        metavar="AGENT:CYCLE",
                        help="kill AGENT when the run reaches CYCLE "
                             "(repeatable; enables replication+repair)")
    parser.add_argument("--fault_replicas", type=int, default=2,
                        help="replicas placed before --fault_kill fires")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.api import solve
    from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

    if args.flight_recorder_events is not None:
        from pydcop_tpu.observability import flight

        flight.install(events=args.flight_recorder_events)

    # csv is the legacy per-step CSV (infrastructure/stats.py, thread
    # mode); chrome/jsonl route through the observability tracer via
    # api.solve's trace knob.
    trace_file = trace_format = None
    if args.trace and args.trace_format == "csv":
        from pydcop_tpu.infrastructure import stats

        stats.set_stats_file(args.trace)
    elif args.trace:
        trace_file, trace_format = args.trace, args.trace_format

    dcop = load_dcop_from_file(args.dcop_files)
    if args.algo == "auto":
        # api.solve resolves the portfolio (race or cached replay)
        # and builds the winner's AlgorithmDef itself.
        from pydcop_tpu.commands._utils import parse_algo_params

        if args.mode != "device":
            raise ValueError(
                "--algo auto races device kernels: use --mode device")
        algo_def = "auto"
        auto_params = parse_algo_params(args.algo_params)
    else:
        algo_def = build_algo_def(
            args.algo, args.algo_params, dcop.objective)
        auto_params = None

    if (args.checkpoint_dir or args.resume) and args.mode != "device":
        raise ValueError(
            "--checkpoint_dir/--resume segment the device engine's "
            "solve loop: use --mode device"
        )
    if args.scenario:
        return _run_scenario_cmd(args, dcop, algo_def)
    fault_plan = None
    if (args.fault_drop or args.fault_dup or args.fault_delay
            or args.fault_kill):
        from pydcop_tpu.resilience.faults import CrashEvent, FaultPlan

        if args.mode != "thread":
            raise ValueError(
                "--fault_* knobs need --mode thread (fault injection "
                "wraps in-process transports)"
            )
        fault_plan = FaultPlan(
            seed=args.fault_seed,
            drop=args.fault_drop,
            duplicate=args.fault_dup,
            delay=args.fault_delay,
            delay_time=args.fault_delay_time,
            crashes=tuple(
                CrashEvent.parse(s) for s in (args.fault_kill or [])
            ),
            replicas=args.fault_replicas,
        )
    health_config = None
    if args.health:
        from pydcop_tpu.resilience.health import HealthConfig

        if args.mode != "thread":
            raise ValueError(
                "--health needs --mode thread (heartbeats instrument "
                "in-process agents)"
            )
        health_config = HealthConfig(
            interval=args.health_interval,
            suspect_misses=args.health_suspect_misses,
            dead_misses=args.health_dead_misses,
        )
    recovery_policy = None
    if args.recovery:
        from pydcop_tpu.resilience.recovery import RecoveryPolicy

        if args.mode != "device":
            raise ValueError(
                "--recovery guards the device engine's segmented "
                "loop: use --mode device"
            )
        recovery_policy = RecoveryPolicy(
            max_restarts=args.recovery_max_restarts,
            noise_scale=args.recovery_noise,
            damping_bump=args.recovery_damping_bump,
        )

    t0 = time.perf_counter()
    if args.delay and args.mode == "device":
        logger.warning(
            "--delay only applies to agent modes (ignored in device "
            "mode)"
        )
    if args.mode == "device":
        import contextlib

        profile_ctx = contextlib.nullcontext()
        if args.profile:
            import jax

            profile_ctx = jax.profiler.trace(args.profile)
        with profile_ctx:
            res = solve(
                dcop, algo_def, backend="device",
                algo_params=auto_params,
                max_cycles=args.cycles, n_devices=args.n_devices,
                shards=args.shards,
                checkpoint_dir=args.checkpoint_dir,
                checkpoint_every=args.checkpoint_every,
                checkpoint_async=args.checkpoint_async,
                checkpoint_keep=args.checkpoint_keep,
                resume=args.resume,
                recovery=recovery_policy,
                trace=trace_file, trace_format=trace_format or "chrome",
                metrics_file=args.metrics,
                metrics_every=args.metrics_every,
                serve_metrics=args.serve_metrics,
            )
        result = {
            "status": res["status"],
            "assignment": res["assignment"],
            "cost": res["cost"],
            "violation": res["violations"],
            "time": res["time"],
            "msg_count": res["metrics"].get("msg_count", 0),
            "msg_size": res["metrics"].get("msg_size", 0),
            "cycle": res["cycles"],
            "compile_time": res["compile_time"],
            "backend": "device",
        }
        # Device-mode cycle metrics: the whole solve is one XLA
        # program, so per-cycle rows come from a cost-trace run
        # (MaxSumEngine.run_trace) written post-hoc with the same CSV
        # schema thread mode streams live.  Decimated solves have no
        # equivalent single trace (host-driven clamping rounds), so
        # they only get the final summary row.
        if (args.run_metrics and args.collect_on == "cycle_change"
                and not isinstance(algo_def, str)
                and algo_def.algo in ("maxsum", "amaxsum")
                and not algo_def.params.get("decimation")
                and not algo_def.params.get("decimation_margin")):
            from pydcop_tpu.algorithms.maxsum import build_engine
            from pydcop_tpu.commands.metrics_io import add_csvline

            trace_res = build_engine(
                dcop, algo_def.params, n_devices=args.n_devices,
                shards=args.shards,
            ).run_trace(max_cycles=max(res["cycles"], 1))
            for i, cost in enumerate(
                    trace_res.metrics["cost_trace"]):
                add_csvline(args.run_metrics, "cycle_change", {
                    "time": None,
                    "cycle": i + 1,
                    "cost": float(cost),
                    "violation": None,
                    "msg_count": None,
                    "msg_size": None,
                    "status": "RUNNING",
                })
    else:
        # Algorithms without a termination condition would run forever:
        # bound thread/process runs when no explicit timeout was given.
        timeout = args.timeout if args.timeout is not None else 15.0
        collector = None
        if args.run_metrics and args.mode == "thread":
            from pydcop_tpu.commands.metrics_io import add_csvline

            def collector(metrics):
                add_csvline(args.run_metrics, args.collect_on, metrics)

        res = solve(
            dcop, algo_def, distribution=args.distribution,
            backend=args.mode, timeout=timeout,
            max_cycles=args.cycles, ui_port=args.uiport,
            collector=collector, collect_moment=args.collect_on,
            collect_period=args.period, delay=args.delay,
            fault_plan=fault_plan, health=health_config,
            trace=trace_file, trace_format=trace_format or "chrome",
            metrics_file=args.metrics,
            metrics_every=args.metrics_every,
            serve_metrics=args.serve_metrics,
        )
        result = {
            "status": res["status"],
            "assignment": res["assignment"],
            "cost": res["cost"],
            "violation": res["violations"],
            "time": res.get("time", time.perf_counter() - t0),
            "msg_count": res.get("msg_count", 0),
            "msg_size": res.get("msg_size", 0),
            "cycle": res.get("cycles", 0),
            "agt_metrics": res.get("agt_metrics", {}),
            "backend": res.get("backend", args.mode),
        }
        if "fault_stats" in res:
            result["fault_stats"] = res["fault_stats"]
            result["killed_agents"] = res.get("killed_agents", [])
        if "health" in res:
            result["health"] = res["health"]

    if args.run_metrics or args.end_metrics:
        from pydcop_tpu.commands.metrics_io import add_csvline

        # Thread mode streams run metrics live through the collector;
        # the final summary row is always appended so the file exists
        # even when no collection event fired.
        for path in (args.run_metrics, args.end_metrics):
            if path:
                add_csvline(path, args.collect_on, result)

    emit_result(result, args.output)
    return 0


def _run_scenario_cmd(args, dcop, algo_def) -> int:
    """``pydcop solve --scenario FILE``: dynamic-DCOP replay through
    the incremental engine (reference CLI parity for scenario runs;
    generators/scenario_gen.py makes the inputs).  Events apply
    between warm-started engine segments — the same machinery the
    serve plane's stateful sessions use (docs/sessions.md)."""
    import time as _time

    from pydcop_tpu.dcop.yamldcop import load_scenario_from_file
    from pydcop_tpu.engine.dynamic import replay_scenario

    if args.mode != "device":
        raise ValueError(
            "--scenario replays events through the device engine: "
            "use --mode device")
    if isinstance(algo_def, str) or algo_def.algo not in (
            "maxsum", "maxsum_dynamic", "amaxsum"):
        raise ValueError(
            "--scenario needs a maxsum-family algorithm (the "
            "incremental engine is MaxSum); got "
            f"{algo_def if isinstance(algo_def, str) else algo_def.algo!r}")
    scenario = load_scenario_from_file(args.scenario)
    params = dict(algo_def.params)
    # maxsum's decimation_margin knob defaults to 0.0 == OFF (same
    # contract as decimation_plan_from_params: margin <= 0 disables),
    # so the falsy coercion here is the knob's documented semantics.
    margin = params.get("decimation_margin") or None
    t0 = _time.perf_counter()
    out = replay_scenario(
        dcop, scenario, params=params, max_cycles=args.cycles,
        event_cycles=args.scenario_event_cycles,
        decimation_margin=margin,
    )
    result = {
        "status": "FINISHED" if out["converged"] else "TIMEOUT",
        "assignment": out["assignment"],
        # Cost and violations both come from the MUTATED (live)
        # factor set — a hard constraint the scenario removed or
        # replaced no longer binds the solution, so the original
        # problem's tables are not consulted.
        "cost": out["cost"],
        "violation": out["violations"],
        "time": _time.perf_counter() - t0,
        "cycle": out["cycles"],
        "backend": "device",
        "scenario": {
            "file": args.scenario,
            "events_applied": out["event_count"],
            "recompiles": out["recompiles"],
            "clamped": out["clamped"],
            "orphaned_computations": out["orphaned"],
            "events": out["events"],
        },
    }
    emit_result(result, args.output)
    return 0

"""Battery over infrastructure/communication.Messaging — priority
ordering, FIFO-within-priority, park-and-retry, local/remote routing,
and the per-computation metrics counters (reference
test_infra_communication.py depth).

Messaging is driven directly with an InProcessCommunicationLayer and a
minimal in-memory discovery — no agents, no threads.
"""

import pytest

from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    MSG_MGT,
    MSG_VALUE,
    ComputationMessage,
    InProcessCommunicationLayer,
    Messaging,
)
from pydcop_tpu.infrastructure.computations import Message
from pydcop_tpu.infrastructure.discovery import Discovery


def make_messaging(agent="a1", delay=0):
    comm = InProcessCommunicationLayer()
    comm.discovery = Discovery(agent, comm)
    m = Messaging(agent, comm, delay=delay)
    return m, comm


def msg(content="x"):
    return Message("test", content)


class TestPriorities:
    def test_constants_order(self):
        assert MSG_MGT < MSG_VALUE < MSG_ALGO

    def test_mgt_before_algo(self):
        m, _ = make_messaging()
        m.register_computation("c1")
        m.post_msg("s", "c1", msg("algo"), prio=MSG_ALGO)
        m.post_msg("s", "c1", msg("mgt"), prio=MSG_MGT)
        assert m.next_msg().msg.content == "mgt"
        assert m.next_msg().msg.content == "algo"

    def test_fifo_within_priority(self):
        m, _ = make_messaging()
        m.register_computation("c1")
        for i in range(5):
            m.post_msg("s", "c1", msg(i), prio=MSG_ALGO)
        got = [m.next_msg().msg.content for _ in range(5)]
        assert got == [0, 1, 2, 3, 4]

    def test_empty_queue_returns_none(self):
        m, _ = make_messaging()
        assert m.next_msg(timeout=0.01) is None


class TestRouting:
    def test_local_delivery(self):
        m, _ = make_messaging()
        m.register_computation("c1")
        m.post_msg("src", "c1", msg("hello"))
        got = m.next_msg()
        assert got.src_comp == "src"
        assert got.dest_comp == "c1"
        assert got.msg.content == "hello"

    def test_unregistered_local_computation_is_remote(self):
        """After unregister, messages to the computation are parked
        (unknown destination), not delivered locally."""
        m, _ = make_messaging()
        m.register_computation("c1")
        m.unregister_computation("c1")
        m.post_msg("s", "c1", msg())
        assert m.next_msg(timeout=0.01) is None

    def test_remote_delivery_through_comm_layer(self):
        m1, comm1 = make_messaging("a1")
        m2, comm2 = make_messaging("a2")
        m2.register_computation("c2")
        # a1 learns that c2 lives on a2 (address = comm layer object,
        # InProcess convention).
        comm1.discovery.register_agent("a2", comm2)
        comm1.discovery.register_computation("c2", "a2", publish=False)
        m1.post_msg("c1", "c2", msg("over the wire"))
        got = m2.next_msg()
        assert got.msg.content == "over the wire"

    def test_park_and_retry_on_discovery(self):
        m1, comm1 = make_messaging("a1")
        m2, comm2 = make_messaging("a2")
        m2.register_computation("c2")
        m1.post_msg("c1", "c2", msg("early"))   # unknown yet: parked
        assert m2.next_msg(timeout=0.01) is None
        # Discovery now learns the computation: parked msg flushes.
        comm1.discovery.register_agent("a2", comm2)
        comm1.discovery._on_publish(
            "computation_added", "c2", ("a2", comm2))
        got = m2.next_msg()
        assert got is not None and got.msg.content == "early"

    def test_parked_message_order_preserved(self):
        m1, comm1 = make_messaging("a1")
        m2, comm2 = make_messaging("a2")
        m2.register_computation("c2")
        m1.post_msg("c1", "c2", msg(1))
        m1.post_msg("c1", "c2", msg(2))
        comm1.discovery.register_agent("a2", comm2)
        comm1.discovery._on_publish(
            "computation_added", "c2", ("a2", comm2))
        assert m2.next_msg().msg.content == 1
        assert m2.next_msg().msg.content == 2


class TestMetrics:
    def test_remote_counters_per_source(self):
        m1, comm1 = make_messaging("a1")
        m2, comm2 = make_messaging("a2")
        m2.register_computation("c2")
        comm1.discovery.register_agent("a2", comm2)
        comm1.discovery.register_computation("c2", "a2", publish=False)
        m1.post_msg("cA", "c2", msg())
        m1.post_msg("cA", "c2", msg())
        m1.post_msg("cB", "c2", msg())
        assert m1.count_ext_msg["cA"] == 2
        assert m1.count_ext_msg["cB"] == 1
        assert m1.size_ext_msg["cA"] >= 0

    def test_local_messages_not_counted_as_ext(self):
        m, _ = make_messaging()
        m.register_computation("c1")
        m.post_msg("cA", "c1", msg())
        assert "cA" not in m.count_ext_msg

    def test_queue_count_increments(self):
        m, _ = make_messaging()
        m.register_computation("c1")
        before = m.msg_queue_count
        m.post_msg("s", "c1", msg())
        m.post_msg("s", "c1", msg())
        assert m.msg_queue_count == before + 2


class TestComputationMessage:
    def test_fields(self):
        cm = ComputationMessage("a", "b", msg("m"), MSG_ALGO)
        assert cm.src_comp == "a"
        assert cm.dest_comp == "b"
        assert cm.msg_type == MSG_ALGO

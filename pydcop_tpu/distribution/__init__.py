"""Distribution layer: mapping computations onto agents.

Reference parity: pydcop/distribution/ — every method module exposes
``distribute(computation_graph, agentsdef, hints, computation_memory,
communication_load) -> Distribution`` and most expose
``distribution_cost(...)``.

TPU-native addition: distribution doubles as the shard-balancing pass for
the device engine (see pydcop_tpu.engine.sharding) — the same cost hooks
drive per-device shard assignment instead of per-agent placement.
"""

"""Engine-level telemetry for the jitted solvers.

The device engine's solve is one XLA program per segment — a host
callback per cycle would serialize the loop through the tunnel and
destroy the very rate being measured (engine/timing.py documents how
that tunnel also lies to ``block_until_ready``).  The probe therefore
piggybacks on ``MaxSumEngine.run_checkpointed``'s existing K-cycle
segmentation: each segment already ends with one honest ``sync`` (the
forced host fetch in ``timed_jit_call``), so the per-chunk wall time
handed to :meth:`EngineProbe.on_segment` is end-to-end honest, and the
probe adds NO host syncs inside the jitted loop — its only extra work
is one tiny jitted cost evaluation per chunk, on the chunk boundary
the engine already pays for.

Per chunk the probe emits: a ``chunk`` trace instant (cycle, cost,
converged, honest seconds), the monotone cycle counter + cost gauge
through a :class:`~pydcop_tpu.observability.metrics.CycleSnapshotter`
(JSONL snapshot per chunk when a metrics path is set), and a point on
the in-memory cost-vs-cycle curve that ``api.solve`` returns in
``metrics['cost_curve']``.  The cost computation mirrors
``run_maxsum_trace``'s exactly (constraint cost + noise-free variable
base costs, mode sign, constant term), so the curve's final point
equals the solver's reported cost — asserted in the battery.

**Convergence health** (the measured foundation the decimation /
message-pruning kernels need to decide *when* to prune, and the
oscillation signal an operator reads off a live solve): per segment
the probe also computes the **message residual** (mean |Δ| of the
f2v messages vs the previous segment's) and the **assignment flip
rate** (fraction of variables whose selected value changed) — both
evaluated ON DEVICE by one jitted comparison whose two scalars ride
the segment boundary's existing host fetch, zero syncs inside the
jitted loop.  They land in the ``pydcop_msg_residual`` /
``pydcop_flip_rate`` gauges, the per-chunk SSE ``/events`` payload
(``residual`` / ``flip_rate`` fields), the ``chunk`` trace instant,
and ``metrics['convergence_curve']`` on the result.
"""

import logging
from typing import Any, List, Optional, Tuple

logger = logging.getLogger("pydcop.observability.engine_probe")


class EngineProbe:
    """Per-chunk cost/convergence/timing recorder for a
    ``MaxSumEngine`` (edge layout; the lane layout's graph has no
    host-side cost tables, so its chunks record timing only)."""

    def __init__(self, engine, metrics_path: Optional[str] = None,
                 metrics_every: int = 1, registry=None):
        from pydcop_tpu.observability.metrics import CycleSnapshotter

        self.engine = engine
        self.snapshotter = CycleSnapshotter(
            metrics_path, every=metrics_every, reg=registry
        )
        reg = self.snapshotter.registry
        self._seg_seconds = reg.histogram(
            "pydcop_engine_segment_seconds",
            "Honest (sync-forced) wall seconds per engine chunk")
        self._compile_seconds = reg.counter(
            "pydcop_engine_compile_seconds_total",
            "Seconds spent jit-compiling engine programs")
        self._residual_g = reg.gauge(
            "pydcop_msg_residual",
            "Mean |delta| of f2v messages vs the previous segment "
            "(convergence health; 0 = message fixpoint)")
        self._flip_g = reg.gauge(
            "pydcop_flip_rate",
            "Fraction of variables whose selected value changed "
            "since the previous segment (oscillation signal)")
        # (cycle, cost, converged, seconds) per chunk.
        self.chunks: List[Tuple[int, Optional[float], bool, float]] = []
        # (cycle, residual, flip_rate) per chunk; None on the first
        # chunk (no previous segment to diff against).
        self.convergence: List[Tuple[int, Optional[float],
                                     Optional[float]]] = []
        self._cost_fn = None
        self._conv_fn = None
        self._prev_msgs = None
        self._prev_values = None

    def _build_cost_fn(self):
        import jax
        import jax.numpy as jnp

        from pydcop_tpu.ops import maxsum as maxsum_ops

        # The engine's own kernel namespace when it has one: the
        # partitioned engine's graph is a ShardedGraph whose cost
        # evaluation needs the halo-value exchange, and its ShardOps
        # exposes the same assignment_constraint_cost surface over a
        # GLOBAL [V] assignment.
        ops = getattr(self.engine, "_ops", maxsum_ops)
        constraint_cost = getattr(
            ops, "assignment_constraint_cost",
            maxsum_ops.assignment_constraint_cost)

        meta = self.engine.meta
        base = meta.var_base_costs
        base_arr = None if base is None else jnp.asarray(base)

        def cost_of(values):
            cost = constraint_cost(self.engine.graph, values)
            if base_arr is not None:
                cost = cost + jnp.sum(jnp.take_along_axis(
                    base_arr, values[:, None], axis=1))
            return cost

        return jax.jit(cost_of)

    def _chunk_cost(self, values) -> Optional[float]:
        if getattr(self.engine, "layout", "edge") != "edge":
            return None
        try:
            if self._cost_fn is None:
                self._cost_fn = self._build_cost_fn()
            raw = float(self._cost_fn(values))
        except Exception:
            logger.exception("Chunk cost evaluation failed")
            return None
        meta = self.engine.meta
        sign = 1.0 if meta.mode == "min" else -1.0
        return sign * raw + meta.constant_cost

    def _build_conv_fn(self):
        import jax
        import jax.numpy as jnp

        def conv(prev_msgs, msgs, prev_values, values):
            num = jnp.asarray(0.0, jnp.float32)
            den = 0
            for a, b in zip(jax.tree_util.tree_leaves(prev_msgs),
                            jax.tree_util.tree_leaves(msgs)):
                num = num + jnp.sum(jnp.abs(
                    b.astype(jnp.float32) - a.astype(jnp.float32)))
                den += a.size
            residual = num / max(den, 1)
            flips = jnp.mean(
                (values != prev_values).astype(jnp.float32))
            return residual, flips

        return jax.jit(conv)

    def _convergence(self, state, values
                     ) -> Tuple[Optional[float], Optional[float]]:
        """Residual/flip-rate vs the previous segment — one jitted
        device comparison, two scalars fetched at the boundary the
        host already pays for.  None/None on the first segment and
        for engines whose state carries no ``f2v`` messages."""
        import jax
        import jax.numpy as jnp

        msgs = getattr(state, "f2v", None)
        if msgs is None or values is None:
            return None, None
        residual = flips = None
        if self._prev_msgs is not None:
            try:
                if self._conv_fn is None:
                    self._conv_fn = self._build_conv_fn()
                r, f = jax.device_get(self._conv_fn(
                    self._prev_msgs, msgs,
                    self._prev_values, values))
                residual, flips = float(r), float(f)
            except Exception:
                logger.exception("convergence probe failed")
                self._prev_msgs = None
                self._prev_values = None
                return None, None
        # Retain copies for the next boundary: with buffer donation
        # the next segment consumes the state's buffers in place
        # (device-side copy, overlaps — no host sync); the values
        # output is not donated, so its reference stays valid.
        self._prev_msgs = jax.tree_util.tree_map(jnp.copy, msgs)
        self._prev_values = values
        return residual, flips

    def on_segment(self, state, values, seconds: float,
                   compile_s: float):
        """Record one completed chunk (called by ``run_checkpointed``
        on the chunk boundary, after its honest sync).

        A first call per program reports its whole elapsed time as
        BOTH compile and run (timed_jit_call's overlapping-fields
        convention — never sum them), so the run-only portion here is
        ``seconds - compile_s``: compile time goes to its own counter,
        not into the segment-seconds series.
        """
        from pydcop_tpu.observability.trace import tracer

        cycle = int(state.cycle)
        converged = bool(state.stable)
        cost = self._chunk_cost(values)
        residual, flips = self._convergence(state, values)
        run_s = max(float(seconds) - float(compile_s), 0.0)
        self.chunks.append((cycle, cost, converged, run_s))
        self.convergence.append((cycle, residual, flips))
        self._seg_seconds.observe(run_s)
        if compile_s:
            self._compile_seconds.inc(float(compile_s))
        if residual is not None:
            self._residual_g.set(residual)
        if flips is not None:
            self._flip_g.set(flips)
        self.snapshotter(cycle, cost, residual=residual,
                         flip_rate=flips)
        if tracer.active:
            tracer.instant(
                "chunk", "engine", cycle=cycle, cost=cost,
                converged=converged, seconds=run_s,
                compile_s=float(compile_s),
                residual=residual, flip_rate=flips,
            )

    def cost_curve(self) -> List[Tuple[int, float]]:
        """(cycle, cost) points for chunks where cost was computable."""
        return [(cycle, cost) for cycle, cost, _, _ in self.chunks
                if cost is not None]

    def convergence_curve(self) -> List[Tuple[int, float, float]]:
        """(cycle, residual, flip_rate) points where both signals
        were computable (segment 2 onward)."""
        return [(cycle, residual, flips)
                for cycle, residual, flips in self.convergence
                if residual is not None and flips is not None]

    def summary(self) -> dict:
        run_s = sum(s for _, _, _, s in self.chunks)
        return {
            "chunks": len(self.chunks),
            "chunk_seconds": run_s,
            "cost_curve": self.cost_curve(),
        }


def attach_result_metrics(result: Any, probe: "EngineProbe"):
    """Fold the probe's curve into a ``DeviceRunResult``/dict metrics
    mapping (shared by api.solve's probed paths)."""
    metrics = (result.metrics if hasattr(result, "metrics")
               else result.setdefault("metrics", {}))
    metrics["cost_curve"] = probe.cost_curve()
    metrics["probe_chunks"] = len(probe.chunks)
    metrics["convergence_curve"] = probe.convergence_curve()
    return result

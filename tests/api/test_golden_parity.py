"""Golden-parity: device-engine solves vs brute-force optimum on the
reference's own fixture files.

This is the CPU-vs-TPU / framework-vs-reference equivalence layer the
survey calls for (SURVEY.md §4): identical problems, identical optimal
costs.  Exact algorithms (dpop, syncbb) must hit the brute-force
optimum on every tractable fixture; approximate ones (maxsum) must
match it on the small fixtures they are documented to solve.
"""

import glob
import itertools
import os

import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.yamldcop import load_dcop_from_file

REF_INSTANCES = "/root/reference/tests/instances"
MAX_BRUTE_FORCE = 50_000


def _fixtures():
    for path in sorted(glob.glob(os.path.join(REF_INSTANCES, "*.y*ml"))):
        yield path


def _brute_force_cost(dcop):
    """Optimal cost by enumeration; None when the space is too big."""
    variables = list(dcop.variables.values())
    space = 1
    for v in variables:
        space *= len(v.domain)
        if space > MAX_BRUTE_FORCE:
            return None
    best = None
    for values in itertools.product(*(v.domain for v in variables)):
        assignment = {
            v.name: val for v, val in zip(variables, values)
        }
        cost, _ = dcop.solution_cost(assignment)
        if best is None:
            best = cost
        elif dcop.objective == "min":
            best = min(best, cost)
        else:
            best = max(best, cost)
    return best


TRACTABLE = [
    p for p in _fixtures()
    if _brute_force_cost(load_dcop_from_file([p])) is not None
]


@pytest.mark.parametrize(
    "path", TRACTABLE, ids=[os.path.basename(p) for p in TRACTABLE]
)
def test_dpop_matches_brute_force(path):
    dcop = load_dcop_from_file([path])
    expected = _brute_force_cost(dcop)
    res = solve(dcop, "dpop")
    assert res["cost"] == pytest.approx(expected, abs=1e-5), path


@pytest.mark.parametrize(
    "path", TRACTABLE, ids=[os.path.basename(p) for p in TRACTABLE]
)
def test_syncbb_matches_brute_force(path):
    dcop = load_dcop_from_file([path])
    if dcop.objective == "max":
        pytest.skip("syncbb is a minimizer (reference parity)")
    expected = _brute_force_cost(dcop)
    res = solve(dcop, "syncbb")
    assert res["cost"] == pytest.approx(expected, abs=1e-5), path


@pytest.mark.parametrize("fixture,expected", [
    ("graph_coloring1.yaml", -0.1),
    ("graph_coloring1_func.yaml", -0.1),
    ("graph_coloring_eq.yaml", -0.3),
    ("graph_coloring_tuto.yaml", 12.0),
])
def test_maxsum_reaches_optimum(fixture, expected):
    """Small colorings where maxsum reliably reaches the brute-force
    optimum (expected values verified by enumeration)."""
    dcop = load_dcop_from_file(
        [os.path.join(REF_INSTANCES, fixture)]
    )
    res = solve(dcop, "maxsum", max_cycles=200)
    assert res["cost"] == pytest.approx(expected, abs=1e-5)


def test_secp_fixture_solves():
    dcop = load_dcop_from_file(
        [os.path.join(REF_INSTANCES, "secp_simple1.yaml")]
    )
    expected = _brute_force_cost(dcop)
    res = solve(dcop, "dpop")
    assert res["cost"] == pytest.approx(expected, abs=1e-5)

"""Entry-point helpers for agent-mode runs.

Reference parity: pydcop/infrastructure/run.py (solve :52,
run_local_thread_dcop :145, run_local_process_dcop :225).
"""

import importlib
import logging
from typing import Dict, Optional

from pydcop_tpu.algorithms import AlgorithmDef, load_algorithm_module
from pydcop_tpu.computations_graph import load_graph_module
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.infrastructure.communication import (
    InProcessCommunicationLayer,
)
from pydcop_tpu.infrastructure.orchestratedagents import OrchestratedAgent
from pydcop_tpu.infrastructure.orchestrator import Orchestrator

logger = logging.getLogger("pydcop.run")


def _build_distribution(dcop: DCOP, cg, algo_module,
                        distribution: str) -> Distribution:
    if distribution.endswith((".yaml", ".yml")):
        from pydcop_tpu.dcop.yamldcop import load_dist_from_file

        return load_dist_from_file(distribution)
    dist_module = importlib.import_module(
        f"pydcop_tpu.distribution.{distribution}"
    )
    return dist_module.distribute(
        cg, dcop.agents.values(), hints=dcop.dist_hints,
        computation_memory=getattr(
            algo_module, "computation_memory", None),
        communication_load=getattr(
            algo_module, "communication_load", None),
    )


def run_local_thread_dcop(algo: AlgorithmDef, cg, distribution, dcop,
                          infinity=float("inf"), delay=None,
                          replication: bool = False,
                          ) -> Orchestrator:
    """One OrchestratedAgent thread per AgentDef + an orchestrator, all
    with in-process transports (reference run.py:145).  With
    ``replication=True`` agents are resilient: they host a
    replica-placement computation for dynamic-DCOP repair."""
    comm = InProcessCommunicationLayer()
    orchestrator = Orchestrator(
        algo, cg, distribution, comm, dcop, infinity
    )
    orchestrator.start()
    hosting = {
        a for a in distribution.agents
        if distribution.computations_hosted(a)
    }
    for agent_def in dcop.agents.values():
        if agent_def.name not in hosting and not replication:
            continue
        agent_comm = InProcessCommunicationLayer()
        agent = OrchestratedAgent(
            agent_def, agent_comm, orchestrator.address, delay=delay,
            replication=replication,
        )
        agent.start()
    return orchestrator


def solve(dcop: DCOP, algo_def, distribution="oneagent",
          timeout: Optional[float] = 5, delay=None) -> Dict:
    """One-call solve with the threaded runtime; returns the assignment
    (reference run.py:52)."""
    if isinstance(algo_def, str):
        algo_def = AlgorithmDef.build_with_default_param(
            algo_def, mode=dcop.objective
        )
    algo_module = load_algorithm_module(algo_def.algo)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    if isinstance(distribution, str):
        distribution = _build_distribution(
            dcop, cg, algo_module, distribution)
    orchestrator = run_local_thread_dcop(
        algo_def, cg, distribution, dcop, delay=delay
    )
    try:
        orchestrator.deploy_computations()
        orchestrator.run(timeout=timeout)
        assignment = orchestrator.end_metrics()["assignment"]
        return assignment
    finally:
        orchestrator.stop_agents(5)
        orchestrator.stop()


def solve_with_agents(dcop: DCOP, algo_def, distribution="oneagent",
                      timeout: Optional[float] = 5,
                      max_cycles: int = 0) -> Dict:
    """Full-metrics variant used by the api/CLI thread backend."""
    if isinstance(algo_def, str):
        algo_def = AlgorithmDef.build_with_default_param(
            algo_def, mode=dcop.objective
        )
    algo_module = load_algorithm_module(algo_def.algo)
    # Fail in the caller, not on an agent thread during deployment:
    # only the dynamic maxsum computations subscribe to external
    # (read-only) variables; other algorithms would silently treat them
    # as free optimization variables.
    if dcop.external_variables and algo_def.algo != "maxsum_dynamic":
        raise ValueError(
            f"DCOP has external variable(s) "
            f"{sorted(dcop.external_variables)} but algorithm "
            f"{algo_def.algo!r} does not support them: use "
            "'maxsum_dynamic'"
        )
    # Map max_cycles onto the algorithm's stop_cycle parameter when it
    # has one and none was given, so the -c CLI bound takes effect.
    if max_cycles:
        param_names = {p.name for p in algo_module.algo_params}
        if ("stop_cycle" in param_names
                and not algo_def.params.get("stop_cycle")):
            params = algo_def.params
            params["stop_cycle"] = max_cycles
            algo_def = AlgorithmDef(algo_def.algo, params, algo_def.mode)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    if isinstance(distribution, str):
        distribution = _build_distribution(
            dcop, cg, algo_module, distribution)
    orchestrator = run_local_thread_dcop(algo_def, cg, distribution, dcop)
    stopped = False
    try:
        if not orchestrator.wait_ready(10):
            raise RuntimeError("Agents did not become ready in time")
        orchestrator.deploy_computations()
        orchestrator.run(timeout=timeout)
        # Stop agents first: final metrics arrive with AgentStopped.
        orchestrator.stop_agents(5)
        stopped = True
        metrics = orchestrator.end_metrics()
        return {
            "status": orchestrator.status,
            "assignment": {
                k: v for k, v in metrics["assignment"].items()
                if k in dcop.variables
            },
            "cost": metrics["cost"],
            "violations": metrics["violation"],
            "cycles": metrics["cycle"],
            "time": metrics["time"],
            "msg_count": metrics["msg_count"],
            "msg_size": metrics["msg_size"],
            "agt_metrics": metrics["agt_metrics"],
            "backend": "thread",
        }
    finally:
        if not stopped:
            orchestrator.stop_agents(5)
        orchestrator.stop()

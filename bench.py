"""Benchmark: MaxSum on 10k-variable graph coloring (the north-star
config from BASELINE.json), device engine vs reference-style python loop.

Prints ONE json line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The baseline is a faithful dict-based reimplementation of the reference's
per-computation hot loop (factor_costs_for_var maxsum.py:382 +
costs_for_factor :623: python dicts, per-assignment enumeration), timed
on the same problem for a few cycles — the reference itself cannot run
in this image (py3.12-incompatible imports, missing pulp).
"""

import json
import time

import numpy as np

N_VARS = 10_000
N_COLORS = 3
DEVICE_CYCLES = 200
BASELINE_CYCLES = 2


def build_problem(seed: int = 0):
    rng = np.random.default_rng(seed)
    eq = np.eye(N_COLORS, dtype=np.float32)
    edges = []
    seen = set()
    for _ in range(int(N_VARS * 1.5)):
        i, j = rng.choice(N_VARS, size=2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        edges.append(key)
    return edges, eq


def bench_device(edges):
    from pydcop_tpu.engine.compile import CompiledFactorGraph, FactorBucket
    from pydcop_tpu.engine.runner import MaxSumEngine
    from pydcop_tpu.engine.compile import FactorGraphMeta

    n_f = len(edges)
    costs = np.broadcast_to(
        np.eye(N_COLORS, dtype=np.float32), (n_f, N_COLORS, N_COLORS)
    ).copy()
    var_ids = np.array(edges, dtype=np.int32)
    var_costs = np.zeros((N_VARS + 1, N_COLORS), dtype=np.float32)
    rng = np.random.default_rng(42)
    var_costs[:N_VARS] = rng.random((N_VARS, N_COLORS)) * 0.01
    var_costs[N_VARS] = 1e9
    var_valid = np.ones((N_VARS + 1, N_COLORS), dtype=bool)
    var_valid[N_VARS] = False
    graph = CompiledFactorGraph(
        var_costs=var_costs,
        var_valid=var_valid,
        buckets=(FactorBucket(costs, var_ids),),
    )
    meta = FactorGraphMeta(
        var_names=tuple(f"v{i}" for i in range(N_VARS)),
        domains=tuple(tuple(range(N_COLORS)) for _ in range(N_VARS)),
        factor_names=tuple(f"c{k}" for k in range(n_f)),
        bucket_sizes=(n_f,),
        mode="min",
    )
    engine = MaxSumEngine(graph, meta)
    # Warmup with the same program key so the timed run is compile-free:
    engine.run(max_cycles=DEVICE_CYCLES, stop_on_convergence=False)
    res = engine.run(max_cycles=DEVICE_CYCLES, stop_on_convergence=False)
    elapsed = res.time_s
    cps = DEVICE_CYCLES / elapsed
    # Solution quality: conflicts at selected assignment.
    vals = np.array(
        [res.assignment[f"v{i}"] for i in range(N_VARS)], dtype=np.int64
    )
    conflicts = int(np.sum(vals[var_ids[:, 0]] == vals[var_ids[:, 1]]))
    return cps, elapsed, conflicts


def bench_python_reference_style(edges, var_costs_arr):
    """Reference-semantics hot loop: dicts of dicts, python enumeration."""
    dom = list(range(N_COLORS))
    f2v = {}  # (f, side) -> {val: cost}
    v2f = {}
    var_factors = {}
    for f, (i, j) in enumerate(edges):
        var_factors.setdefault(i, []).append((f, 0))
        var_factors.setdefault(j, []).append((f, 1))

    t0 = time.perf_counter()
    for _cycle in range(BASELINE_CYCLES):
        # factor -> var (factor_costs_for_var semantics)
        for f, (i, j) in enumerate(edges):
            for side, (tgt, other) in enumerate(((i, j), (j, i))):
                recv = v2f.get((f, 1 - side))
                costs = {}
                for d in dom:
                    best = float("inf")
                    for d2 in dom:
                        val = 1.0 if d == d2 else 0.0
                        if recv is not None:
                            val += recv[d2]
                        best = min(best, val)
                    costs[d] = best
                f2v[(f, side)] = costs
        # var -> factor (costs_for_factor semantics, mean-normalized)
        for v, incident in var_factors.items():
            for f, side in incident:
                msg = {d: var_costs_arr[v][d] for d in dom}
                sum_cost = 0.0
                for f2, side2 in incident:
                    if (f2, side2) == (f, side):
                        continue
                    c2 = f2v.get((f2, side2))
                    if c2 is None:
                        continue
                    for d in dom:
                        msg[d] += c2[d]
                        sum_cost += c2[d]
                avg = sum_cost / len(dom)
                v2f[(f, side)] = {d: msg[d] - avg for d in dom}
    elapsed = time.perf_counter() - t0
    return BASELINE_CYCLES / elapsed


def _ensure_live_backend():
    """Guard against a wedged TPU tunnel: probe backend init in a
    subprocess with a timeout; on hang/failure, re-exec this script on
    the CPU backend so the bench always emits its JSON line."""
    import os
    import subprocess
    import sys

    if os.environ.get("PYDCOP_BENCH_NO_PROBE"):
        return
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=120, check=True,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        )
        return
    except (subprocess.TimeoutExpired, subprocess.CalledProcessError):
        print(
            "bench: accelerator backend unresponsive; falling back "
            "to CPU", file=sys.stderr,
        )
    from pydcop_tpu.utils.cleanenv import scrubbed_cpu_env

    env = scrubbed_cpu_env()
    env["PYDCOP_BENCH_NO_PROBE"] = "1"
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def main():
    _ensure_live_backend()
    edges, _ = build_problem()
    device_cps, elapsed, conflicts = bench_device(edges)

    rng = np.random.default_rng(42)
    var_costs_arr = rng.random((N_VARS, N_COLORS)) * 0.01
    python_cps = bench_python_reference_style(edges, var_costs_arr)

    print(json.dumps({
        "metric": "maxsum_cycles_per_sec_10kvar_graphcoloring",
        "value": round(device_cps, 2),
        "unit": "cycles/s",
        "vs_baseline": round(device_cps / python_cps, 1),
    }))


if __name__ == "__main__":
    main()

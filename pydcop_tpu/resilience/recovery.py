"""Rollback-and-recover for guarded engine segments.

A NaN/Inf inside the jitted solve loop — a poisoned cost table, an
overflow in a long bfloat16 run, a flipped bit on flaky hardware —
silently corrupts every later cycle: the solve "finishes" with a
garbage assignment and nothing ever noticed.  ``run_checkpointed``
already pauses at every K-cycle segment boundary (the host is syncing
the cycle counter there anyway), so that boundary becomes a *guard*:
a device-side validation (NaN/Inf scan over the state pytree + an
optional cost-divergence window) whose verdict travels back in the
same host fetch — zero extra syncs inside the jitted loop.

On a tripped guard the :class:`RecoveryPolicy` rolls the solve back to
the last *validated* in-memory snapshot (bit-identical restore,
assertable) and re-runs the segment with **escalating intervention**:

1. reseeded tie-break noise on the message arrays — the same lever
   decimation-style MaxSum interventions use to leave a bad basin
   (Improving Max-Sum through Decimation, arXiv:1706.02209): a tiny
   deterministic perturbation re-orders argmin ties and the re-run
   walks a different trajectory;
2. a damping bump — heavier smoothing suppresses the oscillation that
   diverged (the engine's segment jit re-keys on damping, so the bump
   compiles a fresh program rather than silently reusing the old one);
3. both, with a fresh noise seed, until the restart budget
   (``max_restarts``) is spent — then :class:`RecoveryExhausted`
   aborts the solve *carrying the partial trajectory* (last valid
   assignment + cycle), so the caller still gets the best known state
   instead of garbage.

Every trip and every attempt is a trace instant/span and a registry
counter, so a recovered run is reconstructable from its trace file
(PR-2 observability).  With no guard trips the guarded path is
bit-identical to the unguarded one — guards only *read* state (tier-1
asserted).
"""

import logging
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from pydcop_tpu.observability import flight
from pydcop_tpu.observability.metrics import registry as metrics_registry
from pydcop_tpu.observability.trace import tracer

logger = logging.getLogger("pydcop.resilience.recovery")


class GuardViolation(NamedTuple):
    """One tripped segment guard.  ``shard`` is set only for
    ``shard_loss`` trips (the lost device's mesh position)."""

    kind: str      # "nonfinite" | "divergence" | "injected" |
    #                "shard_loss"
    cycle: int     # end cycle of the segment that tripped
    detail: str
    shard: Optional[int] = None

    def as_dict(self) -> Dict[str, Any]:
        out = {"kind": self.kind, "cycle": int(self.cycle),
               "detail": self.detail}
        if self.shard is not None:
            out["shard"] = int(self.shard)
        return out


class NoSurvivingDevices(RuntimeError):
    """A shard loss left the mesh empty: there is nothing left to
    re-partition onto.  Raised by the engine's shard-loss hook and
    converted to :class:`RecoveryExhausted` (with the partial
    trajectory) by the recovery run."""


class RecoveryExhausted(RuntimeError):
    """The restart budget is spent: the solve cannot self-heal.

    Carries the partial trajectory — ``partial`` holds the last
    VALIDATED assignment/cycle (``assignment`` may be None when the
    guard tripped before any segment validated) — plus the full
    violation history and attempt count, so callers can surface a
    best-effort answer and a diagnosis instead of a bare stack trace.
    """

    def __init__(self, message: str, *,
                 violations: List[GuardViolation],
                 attempts: int,
                 partial: Dict[str, Any]):
        super().__init__(message)
        self.violations = list(violations)
        self.attempts = attempts
        self.partial = dict(partial)


@dataclass
class RecoveryPolicy:
    """Guard thresholds + the escalation ladder of ``run_checkpointed``
    (docs/resilience.md "Failure detection & recovery").

    The NaN/Inf guard is always on.  The cost-divergence guard is
    opt-in (``divergence_window > 0``): it trips when every cost in
    the last ``divergence_window`` segment boundaries exceeds
    ``divergence_factor * |best cost seen| + divergence_slack`` — set
    ``divergence_slack`` for problems whose optimum cost is 0.

    ``trip_cycles`` injects guard trips (chaos soak / tests): the
    first segment ending at-or-past each listed cycle trips once with
    kind ``"injected"``.

    ``trip_shard`` injects DEVICE LOSSES on a partitioned sharded
    engine (``(cycle, shard)`` pairs — the first segment ending
    at-or-past ``cycle`` loses mesh position ``shard``).  A shard
    loss does not walk the escalation ladder and does not consume the
    restart budget: the engine rolls back to the last validated
    snapshot, RE-PARTITIONS the factor graph onto the surviving mesh
    (``ShardedMaxSumEngine.repartition_after_loss`` — the partitioner
    memoizes by structure key + shard count, so a repeated loss
    pattern repartitions from cache), remaps the snapshot onto the
    new layout and resumes; only when NO devices remain does the run
    abort with :class:`RecoveryExhausted` carrying the partial
    trajectory.

    ``verify_restore`` (default True) asserts every rollback restored
    the snapshot bit-identically before intervening — a host fetch of
    the state, paid only on the (rare) rollback path.
    """

    max_restarts: int = 3
    noise_scale: float = 1e-3
    noise_seed: int = 0
    damping_bump: float = 0.2
    damping_cap: float = 0.95
    divergence_window: int = 0
    divergence_factor: float = 3.0
    divergence_slack: float = 0.0
    trip_cycles: Tuple[int, ...] = field(default_factory=tuple)
    trip_shard: Tuple[Tuple[int, int], ...] = field(
        default_factory=tuple)
    verify_restore: bool = True

    def __post_init__(self):
        if self.max_restarts < 0:
            raise ValueError(
                f"max_restarts must be >= 0: {self.max_restarts}")
        if self.noise_scale < 0:
            raise ValueError(
                f"noise_scale must be >= 0: {self.noise_scale}")
        for entry in self.trip_shard:
            if len(tuple(entry)) != 2:
                raise ValueError(
                    "trip_shard entries are (cycle, shard) pairs: "
                    f"{entry!r}")

    def action_for(self, attempt: int) -> str:
        """The escalation ladder: attempt 1 reseeds tie-break noise,
        attempt 2 bumps damping, later attempts do both with a fresh
        seed."""
        if attempt <= 1:
            return "reseed_noise"
        if attempt == 2:
            return "damping_bump"
        return "reseed_noise+damping_bump"


def perturb_state(state, scale: float, seed: int):
    """Deterministic tie-break noise: add uniform(-scale, +scale)
    noise (seeded jax PRNG, folded per leaf) to every floating-point
    leaf of the state pytree, and clear a ``stable`` flag when the
    state carries one (the perturbed messages must re-converge, not
    inherit the snapshot's convergence verdict).  Same (seed, scale,
    structure) -> same perturbation — recovery stays replayable."""
    import jax
    import jax.numpy as jnp

    key = jax.random.PRNGKey(seed)
    leaves, treedef = jax.tree_util.tree_flatten(state)
    out = []
    for i, leaf in enumerate(leaves):
        if jnp.issubdtype(leaf.dtype, jnp.inexact) and leaf.ndim >= 1:
            noise = jax.random.uniform(
                jax.random.fold_in(key, i), leaf.shape,
                dtype=leaf.dtype, minval=-scale, maxval=scale,
            )
            out.append(leaf + noise)
        else:
            out.append(leaf)
    perturbed = jax.tree_util.tree_unflatten(treedef, out)
    if hasattr(perturbed, "_replace") and hasattr(perturbed, "stable"):
        perturbed = perturbed._replace(stable=jnp.asarray(False))
    return perturbed


def _assert_bit_identical(restored, snapshot):
    """The rollback contract: the restored state IS the snapshot, byte
    for byte.  A mismatch means donation aliasing or a buggy copy —
    corrupting the recovery path itself — so fail loudly."""
    import jax

    r_leaves = jax.tree_util.tree_leaves(restored)
    s_leaves = jax.tree_util.tree_leaves(snapshot)
    assert len(r_leaves) == len(s_leaves)
    for i, (r, s) in enumerate(zip(
            jax.device_get(r_leaves), jax.device_get(s_leaves))):
        r, s = np.asarray(r), np.asarray(s)
        if r.tobytes() != s.tobytes():
            raise AssertionError(
                f"rollback restore not bit-identical at leaf {i} "
                f"(dtype {r.dtype}, shape {r.shape})"
            )


class RecoveryRun:
    """Mutable guard/recovery state for ONE ``run_checkpointed`` call.

    The engine owns the loop; this object owns the verdicts: `check`
    scores each segment's guard outputs, `retain` snapshots a
    validated state (a device-side copy when the engine donates
    buffers), `rollback` restores + intervenes or raises
    :class:`RecoveryExhausted` once the budget is spent.
    """

    def __init__(self, policy: RecoveryPolicy, engine):
        self.policy = policy
        self.engine = engine
        self.attempts = 0
        self.trips: List[GuardViolation] = []
        self.actions: List[str] = []
        self.best_cost: Optional[float] = None
        self._window = deque(
            maxlen=max(policy.divergence_window, 1))
        # Kept sorted, duplicates preserved: (c, c, c) arms three
        # consecutive trips at cycle c — how tests force a run through
        # the whole escalation ladder into RecoveryExhausted.
        self._pending_injections = sorted(policy.trip_cycles)
        # (cycle, shard) device-loss injections, sorted by cycle —
        # ((10, 1), (20, 0)) loses shard 1 at ~cycle 10 and then
        # shard 0 of the ALREADY-SHRUNK mesh at ~cycle 20.
        self._pending_shard_trips = sorted(
            tuple(t) for t in policy.trip_shard)
        self.shard_losses = 0
        self._snap_state = None
        self._snap_values = None
        self._m_trips = metrics_registry.counter(
            "pydcop_guard_trips_total",
            "Engine segment guard trips")
        self._m_attempts = metrics_registry.counter(
            "pydcop_recovery_attempts_total",
            "Recovery rollback attempts by escalation action")

    # -- snapshots ------------------------------------------------------ #

    def retain(self, state, values) -> None:
        """Snapshot a VALIDATED state as the rollback target.  With
        buffer donation the next segment consumes ``state``'s buffers,
        so the snapshot is a device-side copy (an on-device program —
        it overlaps, no host sync); without donation the reference
        stays valid as-is."""
        import jax
        import jax.numpy as jnp

        self._snap_state = (
            jax.tree_util.tree_map(jnp.copy, state)
            if self.engine.donate else state
        )
        self._snap_values = values

    @property
    def snapshot_state(self):
        """The retained (donation-safe) copy of the last validated
        state.  Read-only sharing is safe: rollback copies OUT of it,
        so a checkpoint writer fetching from the same buffers never
        races a mutation — run_checkpointed reuses it instead of
        making a second per-segment device copy."""
        return self._snap_state

    @property
    def snapshot_cycle(self) -> Optional[int]:
        if self._snap_state is None:
            return None
        return int(self._snap_state.cycle)

    # -- guard verdicts ------------------------------------------------- #

    def check(self, end_cycle: int, finite: bool,
              cost: float) -> Optional[GuardViolation]:
        """Score one segment's guard outputs; None means valid."""
        if self._pending_shard_trips \
                and end_cycle >= self._pending_shard_trips[0][0]:
            at, shard = self._pending_shard_trips.pop(0)
            return GuardViolation(
                "shard_loss", end_cycle,
                f"injected loss of shard {shard} armed at cycle "
                f"{at}", shard=int(shard))
        if self._pending_injections \
                and end_cycle >= self._pending_injections[0]:
            at = self._pending_injections.pop(0)
            return GuardViolation(
                "injected", end_cycle, f"injected trip armed at "
                f"cycle {at}")
        if not finite:
            return GuardViolation(
                "nonfinite", end_cycle, "NaN/Inf in solver state")
        if self.policy.divergence_window > 0:
            if self.best_cost is None or cost < self.best_cost:
                self.best_cost = cost
            self._window.append(cost)
            threshold = (
                self.policy.divergence_factor * abs(self.best_cost)
                + self.policy.divergence_slack
            )
            if len(self._window) == self._window.maxlen \
                    and min(self._window) > threshold:
                return GuardViolation(
                    "divergence", end_cycle,
                    f"cost window min {min(self._window):.6g} > "
                    f"threshold {threshold:.6g} "
                    f"(best {self.best_cost:.6g})")
        return None

    # -- rollback + escalation ----------------------------------------- #

    def _partial(self) -> Dict[str, Any]:
        """The best-known state for a RecoveryExhausted carrier."""
        import jax

        partial: Dict[str, Any] = {
            "assignment": None,
            "cycle": self.snapshot_cycle,
            "converged": False,
        }
        if self._snap_values is not None:
            partial["assignment"] = (
                self.engine.meta.assignment_from_indices(
                    np.asarray(jax.device_get(self._snap_values)))
            )
        return partial

    def rollback(self, violation: GuardViolation):
        """Restore the last valid snapshot and intervene; returns the
        (state, values) to continue from.  Raises RecoveryExhausted
        past the restart budget.  ``shard_loss`` violations take the
        repartition path instead of the escalation ladder."""
        import jax
        import jax.numpy as jnp

        if violation.kind == "shard_loss":
            return self._rollback_shard_loss(violation)
        self.trips.append(violation)
        self._m_trips.inc(kind=violation.kind)
        if tracer.active:
            tracer.instant("guard_trip", "resilience",
                           kind=violation.kind,
                           cycle=int(violation.cycle),
                           detail=violation.detail)
        self.attempts += 1
        # Flight-recorder anomaly: the guard-trip escalation is
        # black-box evidence whether or not the run survives it.
        flight.trigger("guard_trip", trip_kind=violation.kind,
                       cycle=int(violation.cycle),
                       attempt=self.attempts,
                       detail=violation.detail)
        if self.attempts > self.policy.max_restarts:
            partial = self._partial()
            flight.trigger(
                "recovery_exhausted", force=True,
                trip_kind=violation.kind,
                cycle=int(violation.cycle),
                attempts=self.attempts,
                last_valid_cycle=self.snapshot_cycle)
            raise RecoveryExhausted(
                f"recovery budget exhausted after "
                f"{self.policy.max_restarts} restarts; last trip: "
                f"{violation.kind} at cycle {violation.cycle}",
                violations=self.trips, attempts=self.attempts,
                partial=partial,
            )
        action = self.policy.action_for(self.attempts)
        self.actions.append(action)
        self._m_attempts.inc(action=action)
        logger.warning(
            "Guard trip (%s at cycle %d): rollback to cycle %s, "
            "attempt %d/%d, action=%s",
            violation.kind, violation.cycle, self.snapshot_cycle,
            self.attempts, self.policy.max_restarts, action,
        )
        with tracer.span("recovery_rollback", "resilience",
                         attempt=self.attempts, action=action,
                         kind=violation.kind,
                         to_cycle=self.snapshot_cycle):
            # Copy out of the snapshot — the continuing loop will
            # donate (or perturb) what we return, and a LATER trip
            # must be able to roll back to this same snapshot again.
            restored = jax.tree_util.tree_map(
                jnp.copy, self._snap_state)
            if self.policy.verify_restore:
                _assert_bit_identical(restored, self._snap_state)
            if "reseed_noise" in action and self.policy.noise_scale:
                restored = perturb_state(
                    restored, self.policy.noise_scale,
                    self.policy.noise_seed + self.attempts,
                )
            if "damping_bump" in action:
                engine = self.engine
                bumped = min(
                    engine.damping + self.policy.damping_bump,
                    self.policy.damping_cap,
                )
                logger.warning(
                    "Recovery damping bump: %.3f -> %.3f",
                    engine.damping, bumped)
                engine.damping = bumped
        # The diverged branch's costs must not poison the next
        # window's verdict.
        self._window.clear()
        return restored, self._snap_values

    def _rollback_shard_loss(self, violation: GuardViolation):
        """Shard-loss recovery: roll back to the last validated
        snapshot AND re-partition onto the surviving mesh.

        Distinct from the escalation ladder on purpose — losing a
        device says nothing about the numerics, so no noise/damping
        intervention is applied and the restart budget is not
        consumed (a solve can survive as many device losses as it has
        devices).  The engine hook does the heavy lifting: new mesh
        from the survivors, memoized re-partition, snapshot remapped
        onto the new layout.  :class:`NoSurvivingDevices` becomes
        :class:`RecoveryExhausted` carrying the partial trajectory.
        """
        self.trips.append(violation)
        self._m_trips.inc(kind="shard_loss")
        if tracer.active:
            tracer.instant("guard_trip", "resilience",
                           kind="shard_loss",
                           cycle=int(violation.cycle),
                           shard=violation.shard,
                           detail=violation.detail)
        flight.trigger("shard_loss", shard=violation.shard,
                       cycle=int(violation.cycle),
                       detail=violation.detail)
        hook = getattr(self.engine, "repartition_after_loss", None)
        if hook is None:
            raise ValueError(
                "trip_shard requires a partitioned sharded engine "
                "(solve with shards=N); this engine has no "
                "repartition_after_loss hook")
        self.shard_losses += 1
        self.actions.append("repartition")
        self._m_attempts.inc(action="repartition")
        logger.warning(
            "Shard loss (shard %s at cycle %d): rollback to cycle "
            "%s and re-partition onto the surviving mesh",
            violation.shard, violation.cycle, self.snapshot_cycle,
        )
        with tracer.span("recovery_rollback", "resilience",
                         attempt=self.attempts, action="repartition",
                         kind="shard_loss",
                         to_cycle=self.snapshot_cycle,
                         lost_shard=violation.shard):
            try:
                state = hook(violation.shard, self._snap_state)
            except NoSurvivingDevices as exc:
                flight.trigger(
                    "recovery_exhausted", force=True,
                    trip_kind="shard_loss", shard=violation.shard,
                    cycle=int(violation.cycle))
                raise RecoveryExhausted(
                    f"no surviving devices after loss of shard "
                    f"{violation.shard} at cycle {violation.cycle}",
                    violations=self.trips, attempts=self.attempts,
                    partial=self._partial(),
                ) from exc
        # The old snapshot's layout died with the lost shard: the
        # remapped state IS the new rollback target (retain copies it
        # when the engine donates, so the continuing loop cannot
        # invalidate it).
        self.retain(state, self._snap_values)
        self._window.clear()
        return state, self._snap_values

    def metrics(self) -> Dict[str, Any]:
        return {
            "guard_trips": len(self.trips),
            "recovery_attempts": self.attempts,
            "recovery_actions": list(self.actions),
            "shard_losses": self.shard_losses,
            "guard_violations": [v.as_dict() for v in self.trips],
        }

"""HTTP transport retry / on_error / departed-agent tests (VERDICT #8:
"HTTP retry/on_error modes" were untested; ADVICE round-1 item on
retry-queue purging).

Two real HttpCommunicationLayer servers on localhost with a stub
discovery; delivery, retry-until-reachable, fail-fast, purge-on-removal
and stale-namesake behavior are all observable through recorded
receive_msg calls.
"""

import threading
import time

import pytest

from pydcop_tpu.infrastructure.communication import (
    MSG_ALGO,
    ComputationMessage,
    HttpCommunicationLayer,
    UnreachableAgent,
)
from pydcop_tpu.infrastructure.computations import Message

PORTS = iter(range(19410, 19470))


class StubDiscovery:
    def __init__(self):
        self.addresses = {}

    def agent_address(self, name):
        return self.addresses[name]


def _msg(content="x"):
    return ComputationMessage(
        "c_src", "c_dst", Message("test", content), MSG_ALGO)


@pytest.fixture()
def layers():
    created = []

    def make(name, discovery):
        port = next(PORTS)
        layer = HttpCommunicationLayer(("127.0.0.1", port))
        layer.discovery = discovery
        layer.RETRY_WINDOW = 5.0
        layer.RETRY_INTERVAL = 0.1
        received = []
        done = threading.Event()

        def record(src_agent, dest_agent, cmsg):
            received.append((src_agent, dest_agent, cmsg))
            done.set()

        layer.receive_msg = record
        created.append(layer)
        return layer, received, done

    yield make
    for layer in created:
        layer.shutdown()


def test_delivery_roundtrip(layers):
    disco = StubDiscovery()
    a, _, _ = layers("a", disco)
    b, received, done = layers("b", disco)
    disco.addresses["b"] = b.address
    a.send_msg("a", "b", _msg("hello"))
    assert done.wait(5)
    src, dest, cmsg = received[0]
    assert (src, dest) == ("a", "b")
    assert cmsg.msg.content == "hello"
    assert cmsg.dest_comp == "c_dst"


def test_on_error_fail_raises_for_unknown_agent(layers):
    disco = StubDiscovery()
    a, _, _ = layers("a", disco)
    with pytest.raises(UnreachableAgent):
        a.send_msg("a", "ghost", _msg(), on_error="fail")


def test_retry_delivers_once_agent_becomes_known(layers):
    """An undeliverable message parks in the retry queue and arrives
    after discovery learns the destination (agents starting before
    their orchestrator)."""
    disco = StubDiscovery()
    a, _, _ = layers("a", disco)
    a.send_msg("a", "late", _msg("queued"))  # unknown -> queued
    b, received, done = layers("b", disco)
    disco.addresses["late"] = b.address
    assert done.wait(5), "retry loop should deliver within the window"
    assert received[0][2].msg.content == "queued"


def test_removed_agent_purges_queue_and_drops_new_sends(layers):
    disco = StubDiscovery()
    a, _, _ = layers("a", disco)
    a.send_msg("a", "gone", _msg())
    assert a._retry_queue or a._retry_thread is not None
    a.on_agent_change("agent_removed", "gone")
    assert not a._retry_queue
    # New sends to the departed agent are dropped immediately.
    a.send_msg("a", "gone", _msg())
    assert not a._retry_queue


def test_readded_namesake_does_not_get_stale_messages(layers):
    """Messages enqueued before an agent's removal must not reach a
    re-added agent reusing the name."""
    disco = StubDiscovery()
    a, _, _ = layers("a", disco)
    a.send_msg("a", "phoenix", _msg("stale"))
    a.on_agent_change("agent_removed", "phoenix")
    a.on_agent_change("agent_added", "phoenix")
    b, received, done = layers("b", disco)
    disco.addresses["phoenix"] = b.address
    # Fresh message sent after the re-add is delivered...
    a.send_msg("a", "phoenix", _msg("fresh"))
    assert done.wait(5)
    time.sleep(0.5)  # give the retry loop a chance to misbehave
    contents = [c.msg.content for _, _, c in received]
    assert "fresh" in contents
    # ...but the pre-removal message was purged, not re-delivered.
    assert "stale" not in contents


def test_messages_to_unreachable_address_retry_then_drop(layers):
    """A known address that never answers keeps retrying and is
    dropped after RETRY_WINDOW without raising."""
    disco = StubDiscovery()
    a, _, _ = layers("a", disco)
    a.RETRY_WINDOW = 0.4
    disco.addresses["dead"] = ("127.0.0.1", 1)  # nothing listens
    a.send_msg("a", "dead", _msg())
    deadline = time.monotonic() + 5
    while a._retry_queue and time.monotonic() < deadline:
        time.sleep(0.1)
    assert not a._retry_queue
"""Pallas binary-factor kernel tests (interpret mode: validates the
lane-major layout and the unrolled min-plus on any backend).  The
oracle is the XLA path (ops.maxsum.factor_to_var) on the same bucket.
"""

import numpy as np
import pytest

from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.compile import compile_factor_graph
from pydcop_tpu.ops import maxsum as ops
from pydcop_tpu.ops.pallas_maxsum import binary_factor_update


def _bucket(n_factors: int, d: int, seed: int):
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    n_vars = max(4, n_factors // 2)
    vs = [Variable(f"v{i}", dom) for i in range(n_vars)]
    cs = []
    for k in range(n_factors):
        i, j = rng.choice(n_vars, size=2, replace=False)
        table = rng.normal(size=(d, d))
        cs.append(NAryMatrixRelation([vs[i], vs[j]], table, f"c{k}"))
    graph, _ = compile_factor_graph(vs, cs)
    assert len(graph.buckets) == 1 and graph.buckets[0].arity == 2
    msgs = rng.normal(size=(graph.buckets[0].n_factors, 2, d)).astype(
        np.float32)
    return graph, msgs


@pytest.mark.parametrize("n_factors,d,seed", [
    (7, 3, 0),        # smaller than one lane block
    (128, 3, 1),      # exactly one block
    (300, 5, 2),      # multiple blocks + padding remainder
    (50, 8, 3),       # largest SECP-style domain
])
def test_matches_xla_factor_to_var(n_factors, d, seed):
    graph, msgs = _bucket(n_factors, d, seed)
    xla = np.asarray(ops.factor_to_var(graph, (msgs,))[0])
    pallas = np.asarray(binary_factor_update(
        graph.buckets[0].costs, msgs, interpret=True))
    np.testing.assert_allclose(pallas, xla, rtol=1e-6, atol=1e-6)


def test_zero_messages_give_row_minima():
    """With no incoming messages the update is the plain table min —
    an independently checkable closed form."""
    graph, msgs = _bucket(20, 4, 5)
    zeros = np.zeros_like(msgs)
    out = np.asarray(binary_factor_update(
        graph.buckets[0].costs, zeros, interpret=True))
    costs = np.asarray(graph.buckets[0].costs)
    np.testing.assert_allclose(
        out[:, 0, :], costs.min(axis=2), rtol=1e-6)
    np.testing.assert_allclose(
        out[:, 1, :], costs.min(axis=1), rtol=1e-6)
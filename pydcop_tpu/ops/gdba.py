"""GDBA (Generalized Distributed Breakout) step kernel.

Reference parity: pydcop/algorithms/gdba.py:189-654 (Okamoto et al.
generalized breakout).  Unlike DBA, GDBA works on *optimization*
problems: each variable keeps, for every incident constraint, a
*modifier hypercube* the same shape as the constraint's cost table
(reference `__constraints_modifiers__`, gdba.py:277-279 — a dict keyed
by assignment; here a dense tensor).  The effective cost of an entry is
``base + modifier`` (modifier mode A) or ``base * modifier`` (mode M)
(_eff_cost, gdba.py:574-597).

One lockstep cycle (ok + improve phases, gdba.py:352-540):

- candidate evaluation uses effective costs with neighbors at
  previous-cycle values, plus unary variable costs (compute_eval_value
  :428 — the reference re-adds unary costs once per constraint due to
  an accumulation quirk; we add them exactly once);
- a variable moves iff its improvement is positive and largest in its
  neighborhood, lexically-smallest name winning ties (break_ties
  :657 picks the sorted-first name);
- when nobody in the neighborhood can improve (max improve == 0), each
  variable increases modifiers of its *violated* incident constraints
  (_increase_cost :627); violation is judged on base costs at the
  current assignment per `violation` mode (gdba.py:552-571):
  NZ: cost != 0, NM: cost != constraint minimum, MX: cost == maximum;
- which modifier entries increase depends on `increase_mode`
  (gdba.py:627-654): E: the current-assignment entry; R: all values of
  the own variable, others fixed; C: own value fixed, all assignments
  of the others (the reference keys C-entries with out-of-scope
  variables so they are never read back — we use the documented
  intent); T: every entry.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import CompiledFactorGraph
from pydcop_tpu.ops.localsearch import (
    _fix_other_axes,
    assignment_cost,
    factor_current_costs,
    factor_max_over_valid,
    factor_min_over_valid,
    factor_valid_masks,
    neighborhood_winners,
    positional_sum,
    random_initial_values,
)


class GdbaState(NamedTuple):
    values: jnp.ndarray                 # [V+1] int32
    modifiers: Tuple[jnp.ndarray, ...]  # per bucket [F, arity, D^arity]
    key: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph, modifier: str = "A",
               seed: int = 0) -> GdbaState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    base = 0.0 if modifier == "A" else 1.0  # gdba.py:247
    modifiers = tuple(
        jnp.full(
            (b.n_factors, b.arity) + b.costs.shape[1:], base,
            dtype=jnp.float32,
        )
        for b in graph.buckets
    )
    return GdbaState(
        values=random_initial_values(k0, graph),
        modifiers=modifiers,
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def factor_min_max(graph: CompiledFactorGraph
                   ) -> Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...]:
    """Per bucket: (min [F], max [F]) of each factor's base costs over
    the *valid* region (padded domain slots hold BIG and must not win
    the max) — reference records these at init (gdba.py:252-273)."""
    return tuple(
        (factor_min_over_valid(bucket, valid),
         factor_max_over_valid(bucket, valid))
        for bucket, valid in zip(graph.buckets, factor_valid_masks(graph))
    )


def _candidate_eff_costs(graph: CompiledFactorGraph,
                         modifiers: Tuple[jnp.ndarray, ...],
                         values: jnp.ndarray,
                         modifier_mode: str) -> jnp.ndarray:
    """[V+1, D]: effective cost per variable and candidate value, others
    at `values` (compute_eval_value + _eff_cost, gdba.py:428-461)."""
    per_bucket = []
    for bucket, mods in zip(graph.buckets, modifiers):
        arity = bucket.var_ids.shape[1]
        cols = []
        for p in range(arity):
            if modifier_mode == "A":
                eff = bucket.costs + mods[:, p]
            else:
                eff = bucket.costs * mods[:, p]
            cols.append(
                _fix_other_axes(eff, bucket.var_ids, values, p))
        per_bucket.append(jnp.stack(cols, axis=1))
    return positional_sum(graph, per_bucket, graph.var_costs)


def _increase_delta(bucket, values: jnp.ndarray, mask: jnp.ndarray,
                    p: int, increase_mode: str) -> jnp.ndarray:
    """[F, D^arity] one-increment tensor for position p's modifier:
    outer product over axes of one-hot(current value) or ones, gated by
    `mask` (gdba.py:627-654)."""
    arity = bucket.var_ids.shape[1]
    dmax = bucket.costs.shape[1]
    out = mask.astype(jnp.float32)  # [F]
    for q in range(arity):
        if increase_mode == "T":
            hot = False
        elif increase_mode == "E":
            hot = True
        elif increase_mode == "R":
            hot = q != p     # own axis free, others at current
        else:  # "C"
            hot = q == p     # own axis at current, others free
        if hot:
            wq = jax.nn.one_hot(
                values[bucket.var_ids[:, q]], dmax, dtype=jnp.float32
            )
        else:
            wq = jnp.ones((bucket.n_factors, dmax), dtype=jnp.float32)
        shape = (bucket.n_factors,) + (1,) * q + (dmax,)
        out = out[..., None] * wq.reshape(shape)
    return out


def gdba_step(state: GdbaState, graph: CompiledFactorGraph, *,
              modifier_mode: str, violation_mode: str, increase_mode: str,
              minmax: Tuple[Tuple[jnp.ndarray, jnp.ndarray], ...],
              lexic_ranks: jnp.ndarray) -> GdbaState:
    """One lockstep GDBA cycle (ok + improve phases)."""
    key, k_choice = jax.random.split(state.key)
    values = state.values

    cand = _candidate_eff_costs(
        graph, state.modifiers, values, modifier_mode
    )
    improve, proposed, nmax, wins = neighborhood_winners(
        graph, cand, values, k_choice, lexic_ranks
    )
    can_move = (improve > 0) & wins
    # Breakout condition: nobody in the neighborhood can improve
    # (gdba.py:529 `elif maxi == 0`; improvements are non-negative).
    stuck = (improve <= 0) & (nmax <= 0)

    # Violation on *base* costs at the current assignment (gdba.py:552).
    cur_costs = factor_current_costs(graph, values)
    new_modifiers = []
    for bucket, mods, cur, (fmin, fmax) in zip(
        graph.buckets, state.modifiers, cur_costs, minmax
    ):
        if violation_mode == "NZ":
            violated = cur != 0
        elif violation_mode == "NM":
            violated = cur != fmin
        else:  # "MX"
            violated = cur == fmax
        arity = bucket.var_ids.shape[1]
        deltas = []
        for p in range(arity):
            mask = stuck[bucket.var_ids[:, p]] & violated
            deltas.append(
                _increase_delta(bucket, values, mask, p, increase_mode)
            )
        new_modifiers.append(mods + jnp.stack(deltas, axis=1))

    values = jnp.where(can_move, proposed, values)
    return GdbaState(
        values=values,
        modifiers=tuple(new_modifiers),
        key=key,
        cycle=state.cycle + 1,
    )


def run_gdba(graph: CompiledFactorGraph, max_cycles: int, *,
             modifier_mode: str = "A", violation_mode: str = "NZ",
             increase_mode: str = "E", lexic_ranks: jnp.ndarray,
             seed: int = 0,
             ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full GDBA run in one XLA program.

    Returns (values [V], final *base* assignment cost, cycles) — the
    modifiers only steer the search; solution quality is judged on real
    costs."""
    state = init_state(graph, modifier=modifier_mode, seed=seed)
    minmax = factor_min_max(graph)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: gdba_step(
            s, graph,
            modifier_mode=modifier_mode,
            violation_mode=violation_mode,
            increase_mode=increase_mode,
            minmax=minmax,
            lexic_ranks=lexic_ranks,
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle

"""DPOP: Dynamic Programming Optimization Protocol (exact).

Reference parity: pydcop/algorithms/dpop.py (:115-441) — two-phase sweep
over the DFS pseudo-tree: UTIL messages flow leaves→root (each node joins
its assigned constraints with its children's UTIL tables and projects
itself out, :313-386), then VALUE assignments flow root→leaves (each node
slices its joined table on the received separator assignment and picks
its first-optimal value, :389-439).

Execution model here (two paths, selected by the ``engine`` param):

- ``jit`` (default): level-batched tensor sweep — all nodes of a tree
  level with the same table signature are joined + projected by ONE
  jitted XLA kernel on stacked hypercubes (pydcop_tpu/ops/dpop.py).
- ``numpy``: per-node host sweep using the dense relation algebra
  (pydcop_tpu.dcop.relations.join/projection) — the fallback when jax
  is unavailable, and the reference execution to diff against.

UTIL width is exponential in separator size; oversized tables raise
MemoryError in both paths (footprint accounting mirror:
computation_memory below, reference dpop.py:80-85).

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'dpop')
    >>> round(res['cost'], 3), sorted(res['assignment'].items())
    (0.0, [('x', 0), ('y', 1)])
"""

from typing import Dict, Optional

from pydcop_tpu.algorithms import AlgoParameterDef, AlgorithmDef
from pydcop_tpu.computations_graph import pseudotree as pt
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.relations import (
    NAryMatrixRelation,
    find_arg_optimal,
    join,
    projection,
)
from pydcop_tpu.engine.runner import DeviceRunResult
from pydcop_tpu.ops.dpop import UtilTooLargeError

GRAPH_TYPE = "pseudotree"

algo_params = [
    AlgoParameterDef("engine", "str", ["auto", "jit", "numpy"], "auto"),
    # Cross-edge consistency preprocessing (ops/dpop.cec_survivors):
    # prunes soft-dominated domain values before the UTIL tables are
    # built.  Bit-identical assignments either way; "on" shrinks the
    # hypercubes (raising the width ceiling), "off" skips the host-side
    # dominance pass on problems already far under the cap.
    AlgoParameterDef("cec", "str", ["on", "off"], "on"),
]


def computation_memory(node) -> float:
    return pt.computation_memory(node)


def communication_load(src, target: str) -> float:
    return pt.communication_load(src, target)


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("dpop", comp_def)


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 0, mesh=None,
                    n_devices: Optional[int] = None,
                    **_) -> DeviceRunResult:
    """Exact solve via level-scheduled UTIL/VALUE sweeps."""
    import time

    requested = "auto"
    cec = True
    if algo_def is not None and algo_def.params:
        requested = algo_def.params.get("engine", "auto")
        cec = algo_def.params.get("cec", "on") != "off"
    engine = requested
    t0 = time.perf_counter()
    graph = pt.build_computation_graph(dcop)
    mode = dcop.objective

    if engine == "auto":
        # Batching pays when levels are wide (many nodes per kernel
        # call); deep narrow trees are dispatch-overhead-bound and run
        # faster through the per-node numpy sweep.
        depth = pt.node_depths(graph)
        levels = max(depth.values(), default=0) + 1
        mean_width = len(depth) / levels
        engine = "jit" if mean_width >= 16 else "numpy"

    if engine == "jit":
        try:
            # The engine tier (engine/dpop.DpopEngine) routes every
            # kernel dispatch through timed_jit_call, so exact solves
            # show up in tracing, metrics and the efficiency ledgers
            # exactly like the iterative engines.
            from pydcop_tpu.engine.dpop import DpopEngine

            res = DpopEngine(graph, mode=mode, cec=cec).run()
            elapsed = time.perf_counter() - t0
            cost, _ = dcop.solution_cost(res.assignment)
            stats = dict(res.metrics)
            stats["device_cost"] = cost
            stats["engine"] = "jit"
            return DeviceRunResult(
                assignment=res.assignment,
                cycles=res.cycles,
                converged=True,
                time_s=elapsed,
                compile_time_s=res.compile_time_s,
                metrics=stats,
            )
        except (ImportError, UtilTooLargeError) as e:
            if requested == "jit":
                raise
            # No jax, or a UTIL table beyond the device cap (the host
            # sweep can still stream it): fall back, audibly.
            import logging

            logging.getLogger("pydcop.algo.dpop").warning(
                "jit sweep unavailable (%s); using numpy sweep", e
            )

    assignment, stats = _solve_numpy(graph, mode)
    elapsed = time.perf_counter() - t0
    cost, _ = dcop.solution_cost(assignment)
    return DeviceRunResult(
        assignment=assignment,
        cycles=stats.pop("levels"),
        converged=True,
        time_s=elapsed,
        compile_time_s=0.0,
        metrics={**stats, "device_cost": cost, "engine": "numpy",
                 "optimal": True},
    )


def _solve_numpy(graph, mode: str):
    """Host-side per-node sweep (dense numpy relation algebra)."""
    nodes = {n.name: n for n in graph.nodes}

    # Order nodes deepest-first for the UTIL sweep.
    depth = pt.node_depths(graph)
    util_order = sorted(nodes, key=lambda n: -depth[n])

    # UTIL phase: joined[n] = join(own constraints, children UTILs);
    # util_to_parent[n] = project(joined[n], n).
    joined: Dict[str, NAryMatrixRelation] = {}
    util_msgs: Dict[str, NAryMatrixRelation] = {}
    msg_count, msg_size = 0, 0
    for name in util_order:
        node = nodes[name]
        # Seed with the variable's own unary costs so problems modeled
        # with variable cost functions (not only constraints) stay exact.
        acc = NAryMatrixRelation(
            [node.variable], node.variable.cost_vector(),
            name=f"util_{name}",
        )
        for c in node.constraints:
            acc = join(acc, NAryMatrixRelation.from_func_relation(c))
        for child in node.children:
            acc = join(acc, util_msgs[child])
        joined[name] = acc
        if node.parent is not None:
            util_msgs[name] = projection(acc, node.variable, mode)
            msg_count += 1
            msg_size += util_msgs[name].matrix.size

    # VALUE phase: roots pick their optimum, then each child slices its
    # joined table on the separator assignment received from above.
    assignment: Dict[str, object] = {}
    value_order = sorted(nodes, key=lambda n: depth[n])
    for name in value_order:
        node = nodes[name]
        rel = joined[name]
        known = {
            v: assignment[v] for v in rel.scope_names
            if v != name and v in assignment
        }
        if known:
            rel = rel.slice(known)
        values, _ = find_arg_optimal(node.variable, rel, mode)
        assignment[name] = values[0]
        if node.children:
            msg_count += len(node.children)

    stats = {
        "msg_count": msg_count,
        "msg_size": msg_size,
        "levels": max(depth.values(), default=0) + 1,
    }
    return assignment, stats

"""MGM2 step kernel — coordinated 2-opt local search.

Reference parity: pydcop/algorithms/mgm2.py:399-1050 (Maheswaran et al.
2004, 5-phase protocol: value / offer / answer? / gain / go?).  One
lockstep cycle here performs all five phases with neighbor values from
the previous cycle:

1. **value**: every variable computes its unilateral best response and
   gain (mgm2.py:742-779); with probability `threshold` it becomes an
   *offerer* and picks a random partner among its neighbors (:755-758).
2. **offer**: an offerer sends its partner all joint (my_value,
   partner_value) moves that strictly improve its own local view,
   tagged with its local gain (_compute_offers_to_send :520).
3. **answer**: a non-offerer picks, among incoming offers, the joint
   move with the best *global* gain (own delta + partner delta with
   shared constraints counted once, _find_best_offer :552) and commits
   to it if that gain beats (or per `favor`, ties with) its unilateral
   gain (:808-827).  Offerers reject offers they receive (:790).
4. **gain**: everyone announces its potential gain — the joint gain
   for committed pairs, the unilateral gain otherwise (:880).
5. **go**: a committed pair moves iff *both* sides' joint gain beats
   every other neighbor's announced gain (:889-903 + :941-955);
   an uncommitted variable moves alone iff its gain is the strict
   neighborhood max, lexically-smallest name winning ties (:907-935).

Device-form notes (documented divergences, all distribution-level, not
cost-level):

- partners are drawn uniformly over incident (factor, position) edges
  rather than distinct neighbor variables — identical unless two
  variables share several constraints or a constraint has arity > 2;
- the joint gain counts shared constraints exactly once *for the
  chosen edge's factor*; additional constraints shared by the same
  pair are treated as fixed-context (the reference excludes them all;
  exact for the common one-constraint-per-pair case).  The reference
  additionally inflates the global gain by the shared constraints'
  current cost (mgm2.py:577 uses the full current cost while the new
  cost excludes shared relations); we compute the true joint gain
  instead.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import CompiledFactorGraph
from pydcop_tpu.ops.localsearch import (
    assignment_cost,
    best_candidates,
    candidate_costs,
    neighbor_max,
    neighbor_min_rank_where,
    random_best_choice,
    random_initial_values,
)

NEG = -jnp.inf


class Mgm2State(NamedTuple):
    values: jnp.ndarray  # [V+1] int32
    key: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph, seed: int = 0) -> Mgm2State:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return Mgm2State(
        values=random_initial_values(k0, graph),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def _fix_two_axes(costs: jnp.ndarray, var_ids: jnp.ndarray,
                  values: jnp.ndarray, p: int, q: int) -> jnp.ndarray:
    """Reduce a bucket cost tensor [F, D^arity] to [F, Dp, Dq] by fixing
    every axis except p and q at its variable's current value."""
    arity = var_ids.shape[1]
    out = costs
    for a in range(arity - 1, -1, -1):
        if a in (p, q):
            continue
        va = values[var_ids[:, a]]
        idx = va.reshape((-1,) + (1,) * (out.ndim - 1))
        out = jnp.squeeze(
            jnp.take_along_axis(out, idx, axis=a + 1), axis=a + 1
        )
    if p > q:  # remaining axes are in original order (q before p)
        out = jnp.swapaxes(out, 1, 2)
    return out


def _families(graph: CompiledFactorGraph):
    """All ordered (bucket, p, q) position pairs — the directed edge
    families of the interaction graph."""
    for bucket in graph.buckets:
        arity = bucket.var_ids.shape[1]
        for p in range(arity):
            for q in range(arity):
                if p != q:
                    yield bucket, p, q


def mgm2_step(state: Mgm2State, graph: CompiledFactorGraph, *,
              threshold: float, favor: str,
              lexic_ranks: jnp.ndarray) -> Mgm2State:
    """One lockstep MGM2 cycle (all 5 phases)."""
    values = state.values
    n_seg = graph.var_costs.shape[0]
    sentinel = n_seg - 1
    dmax = graph.dmax
    key, k_uni, k_offer, k_coin, k_fam = jax.random.split(state.key, 5)

    # ---- phase 1: unilateral best response ----------------------------
    cand = candidate_costs(graph, values)                  # [V+1, D]
    cur = jnp.take_along_axis(cand, values[:, None], axis=1).squeeze(1)
    best, is_best = best_candidates(graph, cand)
    uni_gain = cur - best                                  # >= 0
    uni_prop = random_best_choice(k_uni, is_best)
    uni_value = jnp.where(uni_gain > 0, uni_prop, values)
    g_delta = cur[:, None] - cand                          # [V+1, D]

    is_offerer = (
        jax.random.uniform(k_offer, (n_seg,)) < threshold
    ).at[sentinel].set(False)

    # ---- partner selection: random incident edge per offerer ---------
    fams = list(_families(graph))
    fam_keys = [jax.random.fold_in(k_fam, i) for i in range(len(fams))]
    scores = []
    score_max = jnp.full((n_seg,), NEG)
    for (bucket, p, q), fk in zip(fams, fam_keys):
        src, dst = bucket.var_ids[:, p], bucket.var_ids[:, q]
        real = (src != sentinel) & (dst != sentinel)
        s = jnp.where(
            real & is_offerer[src],
            jax.random.uniform(jax.random.fold_in(fk, 0),
                               (bucket.n_factors,)),
            NEG,
        )
        scores.append(s)
        score_max = jnp.maximum(score_max, jax.ops.segment_max(
            s, src, num_segments=n_seg
        ))

    # ---- phases 2-3: offers, global gains, acceptance ----------------
    # Collected per family, then reduced per acceptor variable.
    acc_best = jnp.full((n_seg,), NEG)      # best incoming global gain
    fam_results = []
    for (bucket, p, q), fk, s in zip(fams, fam_keys, scores):
        src, dst = bucket.var_ids[:, p], bucket.var_ids[:, q]
        chosen = jnp.isfinite(s) & (s == score_max[src])
        T = _fix_two_axes(bucket.costs, bucket.var_ids, values, p, q)
        a_cur, b_cur = values[src], values[dst]
        t_a = jnp.take_along_axis(T, b_cur[:, None, None].repeat(
            dmax, axis=1), axis=2).squeeze(2)     # [F, D] T(da, b_cur)
        t_b = jnp.take_along_axis(T, a_cur[:, None, None].repeat(
            dmax, axis=2), axis=1).squeeze(1)     # [F, D] T(a_cur, db)
        t_cur = jnp.take_along_axis(
            t_a, a_cur[:, None], axis=1
        ).squeeze(1)                              # [F] T(a_cur, b_cur)
        # True joint gain (see module docstring):
        # G(da,db) = gA(da) + gB(db) + T(da,b) + T(a,db) - T(a,b) - T(da,db)
        G = (
            g_delta[src][:, :, None] + g_delta[dst][:, None, :]
            + t_a[:, :, None] + t_b[:, None, :]
            - t_cur[:, None, None] - T
        )
        # Offer condition: the offerer's own local view strictly
        # improves (mgm2.py:544-549).
        local_a = cand[src][:, :, None] - t_a[:, :, None] + T
        offer_ok = local_a < cur[src][:, None, None]
        valid = (
            graph.var_valid[src][:, :, None]
            & graph.var_valid[dst][:, None, :]
        )
        G = jnp.where(offer_ok & valid, G, NEG)
        bestG = jnp.max(G.reshape(bucket.n_factors, -1), axis=1)
        # Random choice among tied best joint moves (mgm2.py:822).
        u = jax.random.uniform(
            jax.random.fold_in(fk, 1), (bucket.n_factors, dmax * dmax)
        )
        flat_pick = jnp.argmax(jnp.where(
            G.reshape(bucket.n_factors, -1) == bestG[:, None], u, -1.0
        ), axis=1)
        da, db = flat_pick // dmax, flat_pick % dmax
        # An offer reaches the acceptor only if the target is not
        # itself an offerer (offerers reject, mgm2.py:790-797).
        offered = chosen & ~is_offerer[dst] & (bestG > 0)
        bestG = jnp.where(offered, bestG, NEG)
        acc_best = jnp.maximum(acc_best, jax.ops.segment_max(
            bestG, dst, num_segments=n_seg
        ))
        fam_results.append((src, dst, offered, bestG, da, db))

    # Acceptor commit decision (mgm2.py:808-827).
    has_offer = jnp.isfinite(acc_best)
    if favor == "coordinated":
        tie_ok = jnp.ones((n_seg,), dtype=bool)
    elif favor == "no":
        tie_ok = jax.random.uniform(k_coin, (n_seg,)) > 0.5
    else:  # "unilateral"
        tie_ok = jnp.zeros((n_seg,), dtype=bool)
    acc_commit = has_offer & (
        (acc_best > uni_gain) | ((acc_best == uni_gain) & tie_ok)
    )

    # Pick ONE accepted edge per committed acceptor (random among
    # gain-ties), then scatter pair state to both endpoints.
    partner = jnp.full((n_seg,), -1, dtype=jnp.int32)
    pair_gain = jnp.full((n_seg,), NEG)
    pair_val = jnp.zeros((n_seg,), dtype=jnp.int32)
    committed = jnp.zeros((n_seg,), dtype=bool)
    win_max = jnp.full((n_seg,), NEG)
    fam_w = []
    for i, (src, dst, offered, bestG, da, db) in enumerate(fam_results):
        w = jnp.where(
            offered & (bestG == acc_best[dst]) & acc_commit[dst],
            jax.random.uniform(jax.random.fold_in(k_fam, 10_000 + i),
                               (src.shape[0],)),
            NEG,
        )
        fam_w.append(w)
        win_max = jnp.maximum(win_max, jax.ops.segment_max(
            w, dst, num_segments=n_seg
        ))
    for (src, dst, offered, bestG, da, db), w in zip(fam_results, fam_w):
        accepted = jnp.isfinite(w) & (w == win_max[dst])
        idx_s = jnp.where(accepted, src, n_seg)
        idx_d = jnp.where(accepted, dst, n_seg)
        partner = partner.at[idx_s].set(dst, mode="drop")
        partner = partner.at[idx_d].set(src, mode="drop")
        pair_gain = pair_gain.at[idx_s].set(bestG, mode="drop")
        pair_gain = pair_gain.at[idx_d].set(bestG, mode="drop")
        pair_val = pair_val.at[idx_s].set(
            da.astype(jnp.int32), mode="drop")
        pair_val = pair_val.at[idx_d].set(
            db.astype(jnp.int32), mode="drop")
        committed = committed.at[idx_s].set(True, mode="drop")
        committed = committed.at[idx_d].set(True, mode="drop")

    # ---- phase 4: gain exchange --------------------------------------
    g = jnp.where(committed, pair_gain, uni_gain)

    # Max neighbor gain excluding the partner (mgm2.py:889-893).
    nmax_excl = jnp.full((n_seg,), NEG)
    for bucket, p, q in _families(graph):
        src, dst = bucket.var_ids[:, p], bucket.var_ids[:, q]
        contrib = jnp.where(dst != partner[src], g[dst], NEG)
        nmax_excl = jnp.maximum(nmax_excl, jax.ops.segment_max(
            contrib, src, num_segments=n_seg
        ))

    # ---- phase 5: moves ----------------------------------------------
    can_move = committed & (g > nmax_excl)
    new_values = values
    for (src, dst, offered, bestG, da, db), w in zip(fam_results, fam_w):
        accepted = jnp.isfinite(w) & (w == win_max[dst])
        go = accepted & can_move[src] & can_move[dst]
        idx_s = jnp.where(go, src, n_seg)
        idx_d = jnp.where(go, dst, n_seg)
        new_values = new_values.at[idx_s].set(
            da.astype(jnp.int32), mode="drop")
        new_values = new_values.at[idx_d].set(
            db.astype(jnp.int32), mode="drop")

    # Uncommitted unilateral winners (mgm2.py:907-935).
    nmax_all = neighbor_max(graph, g)
    nrank = neighbor_min_rank_where(graph, g, g, lexic_ranks)
    uni_win = (
        ~committed & (uni_gain > 0)
        & ((uni_gain > nmax_all)
           | ((uni_gain == nmax_all) & (lexic_ranks < nrank)))
    )
    new_values = jnp.where(uni_win, uni_value, new_values)

    return Mgm2State(
        values=new_values, key=key, cycle=state.cycle + 1
    )


def run_mgm2(graph: CompiledFactorGraph, max_cycles: int, *,
             threshold: float = 0.5, favor: str = "unilateral",
             lexic_ranks: jnp.ndarray, seed: int = 0,
             ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full MGM2 run in one XLA program.

    Returns (values [V], final cost, cycles)."""
    state = init_state(graph, seed)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: mgm2_step(
            s, graph, threshold=threshold, favor=favor,
            lexic_ranks=lexic_ranks,
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle

"""Battery over the Agent runtime loop (infrastructure/agents.py)
beyond lifecycle/metrics basics: periodic-action scheduling, message
routing resilience, run() selection, and shutdown (reference
test_infra_agents depth)."""

import threading
import time

from pydcop_tpu.infrastructure.agents import Agent
from pydcop_tpu.infrastructure.communication import (
    InProcessCommunicationLayer,
)
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
    message_type,
    register,
)

NoteMessage = message_type("note", ["n"])


class Recorder(MessagePassingComputation):
    def __init__(self, name):
        super().__init__(name)
        self.seen = []
        self.started = threading.Event()

    def on_start(self):
        self.started.set()

    @register("note")
    def _on_note(self, sender, msg, t):
        self.seen.append((sender, msg.n))


class Exploder(MessagePassingComputation):
    @register("note")
    def _on_note(self, sender, msg, t):
        raise RuntimeError("boom")


def make_agent(name="a1"):
    return Agent(name, InProcessCommunicationLayer())


def wait_for(predicate, timeout=5.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return False


class TestRuntimeLoop:
    def test_message_delivery_on_agent_thread(self):
        agent = make_agent()
        comp = Recorder("c1")
        agent.add_computation(comp)
        agent.start()
        try:
            agent.run()
            agent.messaging.post_msg("ext", "c1", NoteMessage(1))
            assert wait_for(lambda: comp.seen == [("ext", 1)])
        finally:
            agent.clean_shutdown(2)

    def test_handler_exception_does_not_kill_loop(self):
        agent = make_agent()
        bad, good = Exploder("bad"), Recorder("good")
        agent.add_computation(bad)
        agent.add_computation(good)
        agent.start()
        try:
            agent.run()
            agent.messaging.post_msg("ext", "bad", NoteMessage(1))
            agent.messaging.post_msg("ext", "good", NoteMessage(2))
            assert wait_for(lambda: good.seen == [("ext", 2)])
        finally:
            agent.clean_shutdown(2)

    def test_unknown_computation_message_logged_not_fatal(self):
        agent = make_agent()
        comp = Recorder("c1")
        agent.add_computation(comp)
        agent.start()
        try:
            agent.run()
            agent.messaging.register_computation("ghost")
            agent.messaging.post_msg("ext", "ghost", NoteMessage(0))
            agent.messaging.post_msg("ext", "c1", NoteMessage(1))
            assert wait_for(lambda: comp.seen == [("ext", 1)])
        finally:
            agent.clean_shutdown(2)


class TestPeriodicActions:
    def test_periodic_fires_repeatedly(self):
        agent = make_agent()
        hits = []
        agent.set_periodic_action(0.05, lambda: hits.append(1))
        agent.start()
        try:
            assert wait_for(lambda: len(hits) >= 3, timeout=3)
        finally:
            agent.clean_shutdown(2)

    def test_remove_periodic_action(self):
        agent = make_agent()
        hits = []

        def tick():
            hits.append(1)

        agent.set_periodic_action(0.05, tick)
        agent.start()
        try:
            assert wait_for(lambda: len(hits) >= 1, timeout=3)
            agent.remove_periodic_action(tick)
            time.sleep(0.15)
            count = len(hits)
            time.sleep(0.2)
            assert len(hits) == count   # no longer firing
        finally:
            agent.clean_shutdown(2)

    def test_periodic_exception_does_not_kill_loop(self):
        agent = make_agent()
        hits = []

        def bad():
            raise RuntimeError("tick boom")

        agent.set_periodic_action(0.05, bad)
        agent.set_periodic_action(0.05, lambda: hits.append(1))
        agent.start()
        try:
            assert wait_for(lambda: len(hits) >= 2, timeout=3)
        finally:
            agent.clean_shutdown(2)


class TestLifecycleGuards:
    def test_clean_shutdown_stops_thread_and_computations(self):
        agent = make_agent()
        comp = Recorder("c1")
        agent.add_computation(comp)
        agent.start()
        agent.run()
        assert wait_for(lambda: comp.started.is_set())
        agent.clean_shutdown(2)
        assert not agent._thread.is_alive()
        assert not comp.is_running

    def test_clean_shutdown_idempotent(self):
        agent = make_agent()
        agent.start()
        agent.clean_shutdown(2)
        agent.clean_shutdown(2)   # second call must not raise

"""Opt-in per-step computation trace, written as CSV.

Reference parity: pydcop/infrastructure/stats.py (column schema
:49-64, set_stats_file :71, trace_computation :81 — off by default).

Columns: timestamp, computation, step duration, messages in/out,
message sizes in/out, current value.
"""

import csv
import threading
import time
from typing import Optional

COLUMNS = [
    "time",
    "computation",
    "duration",
    "msg_in_count",
    "msg_in_size",
    "msg_out_count",
    "msg_out_size",
    "value",
]

_lock = threading.Lock()
_stats_file = None
_writer = None


def set_stats_file(path: Optional[str]):
    """Enable (or disable with None) step tracing to a CSV file."""
    global _stats_file, _writer
    with _lock:
        if _stats_file is not None:
            _stats_file.close()
            _stats_file = None
            _writer = None
        if path is not None:
            _stats_file = open(path, "w", newline="",
                               encoding="utf-8")
            _writer = csv.writer(_stats_file)
            _writer.writerow(COLUMNS)


def tracing_enabled() -> bool:
    return _stats_file is not None


def trace_computation(computation: str, duration: float,
                      msg_in_count: int = 0, msg_in_size: int = 0,
                      msg_out_count: int = 0, msg_out_size: int = 0,
                      value=None):
    """Append one step row (no-op unless set_stats_file was called)."""
    with _lock:
        if _writer is None:
            return
        _writer.writerow([
            f"{time.time():.6f}", computation, f"{duration:.6f}",
            msg_in_count, msg_in_size, msg_out_count, msg_out_size,
            "" if value is None else value,
        ])
        _stats_file.flush()

"""Distribution-file IO.

Reference parity: pydcop/distribution/yamlformat.py
(load_dist_from_file :44) — delegates to the yaml layer.
"""

from pydcop_tpu.dcop.yamldcop import (  # noqa: F401
    load_dist,
    load_dist_from_file,
    yaml_dist,
)

"""Device/engine algorithm tests.

Exact algorithms (dpop, syncbb) are checked against brute-force optima
on random problems; local search (dsa, mgm) against quality invariants
(mgm monotonicity is structural: never worse than random init).
"""

import itertools

import numpy as np
import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable, VariableWithCostDict
from pydcop_tpu.dcop.relations import NAryMatrixRelation, constraint_from_str


def brute_force(dcop):
    best, best_asst = np.inf, None
    names = list(dcop.variables)
    domains = [list(dcop.variables[n].domain) for n in names]
    sign = 1 if dcop.objective == "min" else -1
    for combo in itertools.product(*domains):
        asst = dict(zip(names, combo))
        cost, _ = dcop.solution_cost(asst)
        if sign * cost < best:
            best, best_asst = sign * cost, asst
    return sign * best, best_asst


def random_dcop(n_vars=8, n_constraints=12, d=3, seed=0, objective="min",
                with_var_costs=False, arity3=False):
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP("rand", objective=objective)
    variables = []
    for i in range(n_vars):
        if with_var_costs:
            costs = {v: float(rng.random()) for v in dom}
            variables.append(
                VariableWithCostDict(f"v{i}", dom, costs))
        else:
            variables.append(Variable(f"v{i}", dom))
    for k in range(n_constraints):
        arity = 3 if (arity3 and k % 4 == 0) else 2
        idx = rng.choice(n_vars, size=arity, replace=False)
        table = rng.integers(0, 10, size=(d,) * arity).astype(float)
        dcop.add_constraint(NAryMatrixRelation(
            [variables[i] for i in idx], table, f"c{k}"))
    return dcop


class TestDpop:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_optimal_vs_bruteforce(self, seed):
        dcop = random_dcop(seed=seed)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_optimal_with_var_costs(self):
        dcop = random_dcop(seed=3, with_var_costs=True)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_optimal_arity3(self):
        dcop = random_dcop(seed=4, arity3=True)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_max_mode(self):
        dcop = random_dcop(seed=5, objective="max")
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "dpop")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_disconnected_components(self):
        dom = Domain("d", "", [0, 1])
        a, b, c, e = (Variable(n, dom) for n in "abce")
        dcop = DCOP("disc")
        dcop.add_constraint(constraint_from_str("c1", "a + b", [a, b]))
        dcop.add_constraint(constraint_from_str("c2", "2 - c - e", [c, e]))
        res = solve(dcop, "dpop")
        assert res["cost"] == 0
        assert res["assignment"] == {"a": 0, "b": 0, "c": 1, "e": 1}


class TestSyncBB:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    def test_optimal_vs_bruteforce(self, seed):
        dcop = random_dcop(seed=seed)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "syncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_optimal_with_var_costs_and_arity3(self):
        dcop = random_dcop(seed=6, with_var_costs=True, arity3=True)
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "syncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_max_mode(self):
        dcop = random_dcop(seed=7, objective="max")
        expected_cost, _ = brute_force(dcop)
        res = solve(dcop, "syncbb")
        assert res["cost"] == pytest.approx(expected_cost)

    def test_agrees_with_dpop(self):
        dcop = random_dcop(seed=8, n_vars=10, n_constraints=18)
        r1 = solve(dcop, "dpop")
        r2 = solve(dcop, "syncbb")
        assert r1["cost"] == pytest.approx(r2["cost"])


class TestLocalSearch:
    def test_dsa_reaches_reasonable_quality(self):
        dcop = random_dcop(seed=9, n_vars=20, n_constraints=30)
        optimal, _ = brute_force_sample(dcop)
        res = solve(dcop, "dsa", max_cycles=100)
        assert res["violations"] == 0
        # Local search should land within 2x of a sampled-good cost.
        assert res["cost"] <= optimal * 2 + 10

    def test_dsa_variants_and_params(self):
        dcop = random_dcop(seed=10)
        for variant in ("A", "B", "C"):
            res = solve(dcop, "dsa", max_cycles=30,
                        algo_params={"variant": variant})
            assert res["assignment"]
        res = solve(dcop, "dsa", max_cycles=30,
                    algo_params={"p_mode": "arity"})
        assert res["assignment"]

    def test_dsa_deterministic_given_seed(self):
        dcop = random_dcop(seed=11)
        r1 = solve(dcop, "dsa", max_cycles=40, algo_params={"seed": 5})
        r2 = solve(dcop, "dsa", max_cycles=40, algo_params={"seed": 5})
        assert r1["assignment"] == r2["assignment"]

    def test_mgm_monotone_quality(self):
        dcop = random_dcop(seed=12, n_vars=15, n_constraints=25)
        r_short = solve(dcop, "mgm", max_cycles=5)
        r_long = solve(dcop, "mgm", max_cycles=60)
        assert r_long["cost"] <= r_short["cost"] + 1e-6

    def test_mgm_break_modes(self):
        dcop = random_dcop(seed=13)
        for mode in ("lexic", "random"):
            res = solve(dcop, "mgm", max_cycles=30,
                        algo_params={"break_mode": mode})
            assert res["assignment"]

    def test_device_cost_matches_host_cost(self):
        """The on-device cost accumulator must agree with the host
        solution_cost evaluation (cross-validates the compiled arrays)."""
        dcop = random_dcop(seed=14, arity3=True, with_var_costs=True)
        for algo in ("dsa", "mgm"):
            res = solve(dcop, algo, max_cycles=30)
            assert res["metrics"]["device_cost"] == pytest.approx(
                res["cost"], rel=1e-5
            )


def brute_force_sample(dcop, n=2000, seed=0):
    """Sampled best cost (cheap stand-in for brute force on larger
    problems)."""
    rng = np.random.default_rng(seed)
    names = list(dcop.variables)
    domains = [list(dcop.variables[v].domain) for v in names]
    best, best_asst = np.inf, None
    for _ in range(n):
        asst = {
            v: d[rng.integers(len(d))] for v, d in zip(names, domains)
        }
        cost, _ = dcop.solution_cost(asst)
        if cost < best:
            best, best_asst = cost, asst
    return best, best_asst

"""Scrubbed-environment helper for JAX backend selection.

This image's sitecustomize registers the axon TPU PJRT plugin in every
python interpreter (gated on ``PALLAS_AXON_POOL_IPS``); once registered,
a wedged tunnel hangs backend init and no in-process ``jax.config``
update can recover. Every entry point that needs a guaranteed-live CPU
backend (tests, bench fallback, multichip dryrun) builds its child env
through this one helper so the scrub recipe cannot drift between copies.

No jax import here — this module must be importable before any backend
is initialized.
"""

import json
import os
import re
import time

_COUNT_FLAG = "--xla_force_host_platform_device_count"

# Probe-diagnostic event log, carried across re-execs in the env so the
# final JSON line can prove WHAT the guard saw (round-3 verdict: three
# rounds of silent CPU fallbacks left no evidence of the wedge).
DIAG_ENV = "PYDCOP_BENCH_DIAG"
# Original accelerator plugin setting, saved before scrubbing so a CPU
# fallback child can still probe (and revive into) the TPU backend.
SAVED_AXON_ENV = "PYDCOP_SAVED_AXON"
# Probe timeout override (seconds): one env var governs every probe —
# startup retries AND the revival probe — so a slow-but-alive tunnel
# can be given more rope without editing two call sites.
PROBE_TIMEOUT_ENV = "PYDCOP_BENCH_PROBE_TIMEOUT"

# On-disk accelerator-probe history (the committed
# BENCH_TPU_PROBELOG.jsonl format: one record_diag-shaped JSON object
# per line — {"unix": ..., "event": ..., ...}).  The in-env DIAG log
# only covers THIS process tree; the probelog is the cross-run
# history tools/onchip_autopilot.py appends, which is what a
# postmortem needs to say what backend the anomalous run actually
# executed on.  PYDCOP_PROBELOG points elsewhere.
PROBELOG_ENV = "PYDCOP_PROBELOG"
PROBELOG_DEFAULT = "BENCH_TPU_PROBELOG.jsonl"


def default_probe_timeout(default=120.0):
    """The probe timeout in seconds: ``PYDCOP_BENCH_PROBE_TIMEOUT``
    when set (and parseable, and positive), else ``default``."""
    raw = os.environ.get(PROBE_TIMEOUT_ENV)
    if raw is None:
        return default
    try:
        value = float(raw)
    except ValueError:
        return default
    return value if value > 0 else default


def _probe_failure_reason(error):
    """Short label for the failure-counter: 'timeout' vs 'init_error'
    (non-zero exit / import crash)."""
    if error and str(error).startswith("timeout"):
        return "timeout"
    return "init_error"


def is_probe_failure(event):
    """Whether a diagnostic event (a ``diag_events()`` row, or a
    (kind, details) pair flattened into one dict) records a probe /
    supervision FAILURE.  The single classification shared by the
    metrics mirror below and the ``/healthz`` accelerator_probe body
    (observability/server.py) — one predicate, so a new failure kind
    can never be counted in one place and missing from the other."""
    kind = str(event.get("event", ""))
    return (
        kind in ("cpu_fallback", "child_timeout", "child_failed")
        or (kind.endswith("probe") and event.get("ok") is False)
    )


def _observe_probe_event(kind, details):
    """Mirror a diagnostic event into the observability plane: failed
    probes and fallbacks count in
    ``pydcop_bench_probe_failures_total{reason}`` and every event is a
    ``bench_probe`` trace instant while tracing is on.  Import is
    deferred and failure-swallowed — diagnostics must work in the
    most broken environments (that is their job).

    Deliberately NOT gated on ``registry.active``: CLI probes fire
    BEFORE api.solve opens its ObservabilitySession, so gating would
    hide exactly the failures a later live scrape exists to surface.
    The label set is bounded (timeout / init_error / fallback kinds),
    and probe trouble is process-level state, not per-solve detail —
    a .prom dump that includes it is attributing correctly."""
    try:
        from pydcop_tpu.observability.metrics import registry
        from pydcop_tpu.observability.trace import tracer
    except Exception:  # noqa: BLE001
        return
    failed = is_probe_failure({"event": kind, **details})
    if failed:
        reason = (kind if not kind.endswith("probe")
                  else _probe_failure_reason(details.get("error")))
        registry.counter(
            "pydcop_bench_probe_failures_total",
            "Accelerator probe / bench supervision failures by reason",
        ).inc(reason=reason)
    if tracer.enabled:
        tracer.instant("bench_probe", "bench", kind=kind, **details)


def diag_events():
    """Accumulated probe/fallback events ([] when none)."""
    try:
        events = json.loads(os.environ.get(DIAG_ENV, "[]"))
        return events if isinstance(events, list) else []
    except (ValueError, TypeError):
        return []


def record_diag(kind, **details):
    """Append an event to the in-env diagnostic log and return the
    full log.  Timestamps are unix seconds.  Each event is also
    mirrored into the metrics registry / tracer
    (``pydcop_bench_probe_failures_total{reason}`` + ``bench_probe``
    instants) so probe trouble is visible to a live scrape, not only
    in the post-hoc JSON line."""
    events = diag_events()
    events.append({"unix": round(time.time(), 1), "event": kind,
                   **details})
    os.environ[DIAG_ENV] = json.dumps(events)
    _observe_probe_event(kind, details)
    return events


def probelog_path():
    """The accelerator-probe history file: ``PYDCOP_PROBELOG`` when
    set, else ``BENCH_TPU_PROBELOG.jsonl`` in the current directory
    (where serve/bench processes run from the repo root).  Returns
    the path whether or not it exists."""
    return os.environ.get(PROBELOG_ENV, PROBELOG_DEFAULT)


def probelog_tail(n=20, path=None):
    """The last ``n`` rows of the on-disk probe history (the
    ``BENCH_TPU_PROBELOG.jsonl`` / ``record_diag`` event shape).
    Unparsable lines are skipped, a missing file is an empty list —
    this feeds postmortem bundles, which must never gain a second
    failure from their own evidence gathering."""
    path = path or probelog_path()
    rows = []
    try:
        with open(path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    row = json.loads(line)
                except ValueError:
                    continue
                if isinstance(row, dict):
                    rows.append(row)
    except OSError:
        return []
    return rows[-max(int(n), 0):]


def probe_backend(timeout=120, env=None):
    """One subprocess probe of jax backend init.

    Returns (ok, error, seconds): error is None on success, else a
    short string ("timeout after Ns" / "exit <rc>: <stderr tail>")."""
    import subprocess
    import sys

    t0 = time.time()
    try:
        proc = subprocess.run(
            [sys.executable, "-c",
             "import jax; print(jax.devices()[0].platform)"],
            timeout=timeout, capture_output=True, text=True, env=env,
        )
    except subprocess.TimeoutExpired:
        return False, f"timeout after {timeout}s", time.time() - t0
    dt = time.time() - t0
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-1:]
        return False, f"exit {proc.returncode}: {' '.join(tail)[:200]}", dt
    return True, None, dt


def scrubbed_cpu_env(n_devices=None, base=None):
    """Return an env dict that forces a clean CPU JAX backend.

    - drops ``PALLAS_AXON_POOL_IPS`` so sitecustomize skips plugin
      registration entirely in the child interpreter;
    - sets ``JAX_PLATFORMS=cpu``;
    - when ``n_devices`` is given, forces exactly that virtual host
      device count in ``XLA_FLAGS`` (replacing any inherited value —
      an inherited smaller count would make sharded code fail even
      though it is healthy).
    """
    env = dict(os.environ if base is None else base)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    if n_devices is not None:
        flags = re.sub(
            _COUNT_FLAG + r"=\d+", "", env.get("XLA_FLAGS", "")
        ).strip()
        env["XLA_FLAGS"] = (
            flags + f" {_COUNT_FLAG}={n_devices}"
        ).strip()
    return env


def ensure_live_backend(tag="bench", retries=1, probe_timeout=None,
                        backoff=10.0):
    """Guard a benchmark entry point against a wedged TPU tunnel.

    Probes jax backend init in a subprocess (a wedged axon tunnel hangs
    `jax.devices()` forever, even under JAX_PLATFORMS=cpu, because the
    plugin blocks at registration).  After ``retries`` failed probes
    with ``backoff`` seconds between them (the wedge is frequently
    transient, so callers may ask for several) the current script is
    re-exec'd into a scrubbed CPU env so it always emits its result
    line.  No-op in the re-exec'd child (PYDCOP_BENCH_NO_PROBE marker).

    Every probe outcome is recorded in the DIAG_ENV event log, which
    survives the re-exec — benchmarks embed it in their JSON so a CPU
    fallback is always accompanied by evidence of the wedge.
    """
    if os.environ.get("PYDCOP_BENCH_NO_PROBE"):
        return
    if not probe_with_retries(tag, retries, probe_timeout, backoff):
        cpu_fallback_exec(tag)


def probe_with_retries(tag, retries, probe_timeout=None, backoff=10.0):
    """Probe the backend up to ``retries`` times with ``backoff``
    seconds between failures (none after the last), recording every
    attempt in the diagnostic log.  Returns True when a probe
    succeeds.  ``probe_timeout=None`` resolves through
    ``PYDCOP_BENCH_PROBE_TIMEOUT`` (default 120 s)."""
    import sys

    if probe_timeout is None:
        probe_timeout = default_probe_timeout()
    for attempt in range(retries):
        ok, error, dt = probe_backend(probe_timeout)
        record_diag(
            "probe", tag=tag, attempt=attempt + 1, of=retries,
            ok=ok, error=error, seconds=round(dt, 1),
        )
        if ok:
            return True
        print(
            f"{tag}: accelerator probe {attempt + 1}/{retries} "
            f"failed ({error})", file=sys.stderr,
        )
        if attempt < retries - 1:
            time.sleep(backoff)
    return False


def cpu_fallback_exec(tag):
    """Re-exec the current script into a scrubbed CPU env (the one
    shared fallback recipe — every benchmark guard must go through
    here so the scrub cannot drift between copies).  Preserves the
    diagnostic log and the original plugin setting so the child can
    report the history and probe for a revived tunnel."""
    import sys

    print(
        f"{tag}: accelerator backend unresponsive; falling back to "
        "CPU", file=sys.stderr,
    )
    record_diag("cpu_fallback", tag=tag)
    env = scrubbed_cpu_env()
    env["PYDCOP_BENCH_NO_PROBE"] = "1"
    env[DIAG_ENV] = os.environ.get(DIAG_ENV, "[]")
    saved = os.environ.get("PALLAS_AXON_POOL_IPS")
    if saved is not None:
        env[SAVED_AXON_ENV] = saved
    os.execve(sys.executable, [sys.executable] + sys.argv, env)


def tpu_env():
    """Reconstruct an env dict that re-enables the accelerator plugin
    from inside a scrubbed CPU child (None when never scrubbed or no
    plugin setting was saved)."""
    saved = os.environ.get(SAVED_AXON_ENV)
    if saved is None:
        return None
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = saved
    env.pop("JAX_PLATFORMS", None)
    env.pop("PYDCOP_BENCH_NO_PROBE", None)
    return env

"""The orchestrator: central bootstrap, monitoring and control.

Reference parity: pydcop/infrastructure/orchestrator.py (Orchestrator
:62 — own agent + directory :128, deploy_computations :203, run :245,
stop_agents :291, wait_ready :318; AgentsMgt :535 — metrics aggregation
:802-900, global_metrics :1215).
"""

import logging
import threading
import time
from typing import Any, Dict, List, Optional

from pydcop_tpu.algorithms import AlgorithmDef, ComputationDef
from pydcop_tpu.computations_graph.objects import ComputationGraph
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.distribution.objects import Distribution
from pydcop_tpu.infrastructure.agents import Agent
from pydcop_tpu.infrastructure.communication import (
    CommunicationLayer,
    MSG_MGT,
)
from pydcop_tpu.infrastructure.computations import (
    MessagePassingComputation,
    register,
)
from pydcop_tpu.infrastructure.discovery import Directory
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.infrastructure.orchestratedagents import (
    AgentReadyMessage,
    AgentStoppedMessage,
    ComputationFinishedMessage,
    CycleChangeMessage,
    DeployMessage,
    ORCHESTRATOR_AGENT,
    ORCHESTRATOR_MGT,
    PauseMessage,
    ResumeMessage,
    RunAgentMessage,
    StopAgentMessage,
    ValueChangeMessage,
)

logger = logging.getLogger("pydcop.orchestrator")


class AgentsMgt(MessagePassingComputation):
    """Orchestrator-side management computation: aggregates value/cycle
    reports into a global view, tracks completion."""

    def __init__(self, orchestrator: "Orchestrator"):
        super().__init__(ORCHESTRATOR_MGT)
        self.orchestrator = orchestrator
        self.assignment: Dict[str, Any] = {}
        self.cycles: Dict[str, int] = {}
        self.agent_metrics: Dict[str, Dict] = {}
        self.finished_computations: set = set()
        self.ready_agents: set = set()
        self.start_time: Optional[float] = None
        self.last_stop_time: Optional[float] = None
        # Resilience bookkeeping: replica placement + repair progress.
        self.replica_hosts: Dict[str, List[str]] = {}
        self.replication_done_agents: set = set()
        self.repaired_computations: set = set()
        self.repair_event_count: int = 0
        # Per-activation acks from hosts: comp -> agent that confirmed
        # (or refused) activating its replica.
        self.repair_acked: Dict[str, str] = {}
        self.repair_failed: Dict[str, str] = {}
        # Temporarily-hosted computations (distributed repair rounds):
        # while a round runs its names sit in active_transients (their
        # reports are recorded but excluded from metrics collection);
        # when the round ends they move to retired_transients and any
        # in-flight message still queued for them is dropped on
        # arrival — otherwise a late message would re-insert a purged
        # repair variable into assignment/cycles/finished permanently.
        self.active_transients: set = set()
        self.retired_transients: set = set()

    def purge_computations(self, names) -> None:
        """Forget all bookkeeping for the given computation names."""
        self.finished_computations -= set(names)
        for n in names:
            self.assignment.pop(n, None)
            self.cycles.pop(n, None)

    @register("agent_ready")
    def _on_agent_ready(self, sender, msg, t):
        self.ready_agents.add(msg.agent)
        self.orchestrator._ready_evt.set()

    @register("value_change")
    def _on_value_change(self, sender, msg, t):
        if msg.computation in self.retired_transients:
            return
        self.assignment[msg.computation] = msg.value
        self.cycles[msg.computation] = max(
            self.cycles.get(msg.computation, 0), msg.cycle
        )
        if msg.computation in self.active_transients:
            return  # repair-internal: keep out of the metrics stream
        self.orchestrator._on_progress()
        self.orchestrator._collect("value_change")
        self.orchestrator._note_cycle()

    @register("cycle_change")
    def _on_cycle_change(self, sender, msg, t):
        if msg.computation in self.retired_transients:
            return
        self.cycles[msg.computation] = max(
            self.cycles.get(msg.computation, 0), msg.cycle
        )
        if msg.computation in self.active_transients:
            return
        self.orchestrator._collect("cycle_change")
        self.orchestrator._note_cycle()

    @register("computation_finished")
    def _on_comp_finished(self, sender, msg, t):
        if msg.computation in self.retired_transients:
            return
        self.finished_computations.add(msg.computation)
        self.orchestrator._check_all_finished()

    @register("replication_done")
    def _on_replication_done(self, sender, msg, t):
        for comp, hosts in msg.replica_hosts.items():
            self.replica_hosts[comp] = list(hosts)
        self.replication_done_agents.add(msg.agent)
        self.orchestrator._replication_evt.set()

    @register("repair_done")
    def _on_repair_done(self, sender, msg, t):
        for comp in msg.computations:
            # Duplicate re-acks (host-side activation dedupe) must not
            # inflate the event counter.
            if self.repair_acked.get(comp) != msg.agent:
                self.repair_event_count += 1
            self.repair_acked[comp] = msg.agent
            self.repaired_computations.add(comp)
        self.orchestrator._repair_evt.set()

    @register("repair_failed")
    def _on_repair_failed(self, sender, msg, t):
        for comp in msg.computations:
            self.repair_failed[comp] = msg.agent
        self.orchestrator._repair_evt.set()

    @register("agent_stopped")
    def _on_agent_stopped(self, sender, msg, t):
        self.agent_metrics[msg.agent] = msg.metrics
        self.last_stop_time = time.monotonic()
        self.orchestrator._on_agent_stopped(msg.agent)

    def global_metrics(self, status: str) -> Dict:
        """Reference-shaped result dict (orchestrator.py:1215-1274)."""
        dcop = self.orchestrator.dcop
        dcop_assignment = {
            k: v for k, v in self.assignment.items()
            if k in dcop.variables
        }
        try:
            cost, violation = dcop.solution_cost(
                dcop_assignment, self.orchestrator.infinity
            )
        except ValueError:
            cost, violation = None, None
        msg_count, msg_size = 0, 0
        for metrics in self.agent_metrics.values():
            # Registry-sourced totals (Agent.metrics msg_count /
            # msg_size) are bumped at the same call site as the
            # per-computation count_ext_msg dicts, so the two views
            # agree; the dict sum stays as fallback for pre-upgrade
            # metrics payloads (process agents on an older build).
            count = metrics.get("msg_count")
            size = metrics.get("msg_size")
            msg_count += int(
                count if count is not None
                else sum(metrics.get("count_ext_msg", {}).values())
            )
            msg_size += int(
                size if size is not None
                else sum(metrics.get("size_ext_msg", {}).values())
            )
        total_time = (
            time.monotonic() - self.start_time
            if self.start_time else 0
        )
        return {
            "status": status,
            "assignment": self.assignment,
            "cost": cost,
            "violation": violation,
            "time": total_time,
            "msg_count": msg_count,
            "msg_size": msg_size,
            "cycle": max(self.cycles.values(), default=0),
            "agt_metrics": self.agent_metrics,
        }


class Orchestrator:
    """Bootstraps a distributed run: deploys computations onto agents,
    starts them, monitors progress and stops everything."""

    def __init__(self, algo: AlgorithmDef,
                 cg: ComputationGraph,
                 agent_mapping: Distribution,
                 comm: CommunicationLayer,
                 dcop: DCOP,
                 infinity: float = float("inf"),
                 collector=None,
                 collect_moment: str = "value_change",
                 collect_period: float = 1.0,
                 repair_mode: str = "device"):
        self.algo = algo
        self.cg = cg
        self.distribution = agent_mapping
        self.dcop = dcop
        self.infinity = infinity
        self.status = "INIT"
        # How the repair DCOP is solved on agent departure:
        # "device" (default) solves it centrally on the device engine
        # (TPU-first); "distributed" deploys the repair computations
        # onto the candidate agents themselves and runs a bounded
        # synchronous search among them — the reference's architecture
        # (repair hosted in RepairComputation on candidate agents,
        # pydcop/infrastructure/agents.py:1384, orchestrator.py:
        # 1039-1178).
        self.repair_mode = repair_mode
        # Run-metrics collection (reference solve.py:386-443): the
        # collector callable receives a metrics dict at each
        # value_change / cycle_change event or every collect_period
        # seconds.
        self.collector = collector
        self.collect_moment = collect_moment
        self.collect_period = collect_period
        self._collect_timer: Optional[threading.Timer] = None
        self._collecting = False
        # Optional observability.metrics.CycleSnapshotter (set by the
        # runner when the caller asked for --metrics): invoked on
        # every cycle/value report with the global cycle count; its
        # own cadence check rate-limits the snapshot writes.
        self.metrics_snapshotter = None
        # Optional resilience.health.HealthMonitor (set by
        # attach_health when the runner enabled heartbeat failure
        # detection); its death verdicts call report_agent_failure.
        self.health_monitor = None

        self._agent = Agent(ORCHESTRATOR_AGENT, comm)
        self.directory = Directory(self._agent.discovery)
        # Failure detection: transports (and the fault monitor) mark a
        # dead agent by unregistering it from discovery; the directory
        # mirrors the removal here and this hook routes it into the
        # reparation path.  Scenario-driven removals and kill
        # injections also land in _handle_agent_failure — the
        # _failure_lock + _removed_agents latch make the two paths
        # race-safe and idempotent.
        self._failure_lock = threading.Lock()
        self._agent.discovery.agent_change_hooks.append(
            self._on_discovery_agent_change
        )
        # Thread-mode runners register their in-process Agent objects
        # here (name -> Agent) so crash injection can hard-stop them;
        # empty for process/multi-machine runs.
        self.local_agents: Dict[str, Agent] = {}
        self._agent.add_computation(self.directory.directory_computation)
        self._agent.discovery.use_directory(
            ORCHESTRATOR_AGENT, comm.address
        )
        self.mgt = AgentsMgt(self)
        self._agent.add_computation(self.mgt)

        # External (read-only/sensor) variables are published by
        # computations hosted on the orchestrator's agent: dynamic
        # factors subscribe to them by name and receive value changes
        # (reference computations.py:1093 ExternalVariableComputation).
        self._external_computations = []
        for ev in dcop.external_variables.values():
            from pydcop_tpu.infrastructure.computations import (
                ExternalVariableComputation,
            )

            comp = ExternalVariableComputation(ev)
            self._agent.add_computation(comp)
            self._external_computations.append(comp)

        self._ready_evt = threading.Event()
        self._finished_evt = threading.Event()
        self._replication_evt = threading.Event()
        self._repair_evt = threading.Event()
        self._stopped_agents: set = set()
        self._all_stopped_evt = threading.Event()
        self._expected_computations = [
            n.name for n in cg.nodes
        ]
        # Set by the runner (run_local_thread_dcop): called with an
        # AgentDef to create + start a new agent for add_agent
        # scenario events.
        self.agent_factory = None
        # Readiness window for scenario-added agents; runners override
        # (process agents pay a spawn + import before registering).
        self.agent_ready_timeout: float = 10.0
        self._removed_agents: set = set()
        # Last requested replica count; scenario events re-trigger
        # replication with it to heal replica counts after
        # membership changes.
        self.replication_k: Optional[int] = None

    @property
    def address(self):
        return self._agent.address

    # -- lifecycle ----------------------------------------------------- #

    def start(self):
        self._agent.start()
        self.directory.directory_computation.start()
        self.mgt.start()
        for comp in self._external_computations:
            comp.start()

    def stop(self):
        # Disarm BEFORE cancel: a timer callback racing the cancel
        # re-checks this flag before re-arming, so no new timer can be
        # created after stop.
        self._collecting = False
        if self._collect_timer is not None:
            self._collect_timer.cancel()
            self._collect_timer = None
        self._agent.clean_shutdown()

    # -- run-metrics collection ---------------------------------------- #

    def _collect(self, moment: str):
        if self.collector is None or self.collect_moment != moment:
            return
        try:
            self.collector(self.mgt.global_metrics(self.status))
        except Exception:
            logger.exception("Metrics collector failed")

    def _note_cycle(self):
        """Feed the global cycle view into the metrics snapshotter
        (no-op without one; cost is only evaluated when a snapshot
        actually fires — see CycleSnapshotter)."""
        snapshotter = self.metrics_snapshotter
        if snapshotter is None:
            return
        try:
            snapshotter(max(self.mgt.cycles.values(), default=0))
        except Exception:
            logger.exception("Metrics snapshotter failed")

    def _schedule_periodic_collect(self):
        if not self._collecting or self.status != "RUNNING":
            return
        self._collect("period")
        if not self._collecting:
            return
        self._collect_timer = threading.Timer(
            self.collect_period, self._schedule_periodic_collect
        )
        self._collect_timer.daemon = True
        self._collect_timer.start()

    def wait_ready(self, timeout: Optional[float] = None) -> bool:
        """Wait until every agent of the distribution has reported in."""
        expected = {
            a for a in self.distribution.agents
            if self.distribution.computations_hosted(a)
        }
        deadline = (
            time.monotonic() + timeout if timeout is not None else None
        )
        while not expected <= self.mgt.ready_agents:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
            self._ready_evt.clear()
            self._ready_evt.wait(
                min(0.1, remaining) if remaining else 0.1
            )
        return True

    def deploy_computations(self):
        """Send each computation's definition to its hosting agent
        (reference :203 → DeployMessage per computation :1197-1209)."""
        # Once-per-run path: tracer.span is its own no-op when off.
        with tracer.span("deploy_computations", "orchestrator",
                         computations=len(self._expected_computations)):
            for comp_name in self._expected_computations:
                agent = self.distribution.agent_for(comp_name)
                node = self.cg.computation(comp_name)
                comp_def = ComputationDef(node, self.algo)
                self.mgt.post_msg(
                    f"_mgt_{agent}", DeployMessage(comp_def), MSG_MGT
                )

    def run(self, scenario=None, timeout: Optional[float] = None):
        """Start all computations; block until finished or timeout."""
        self.status = "RUNNING"
        self.mgt.start_time = time.monotonic()
        if self.collector is not None and \
                self.collect_moment == "period":
            self._collecting = True
            self._schedule_periodic_collect()
        for agent in self.distribution.agents:
            if self.distribution.computations_hosted(agent):
                self.mgt.post_msg(
                    f"_mgt_{agent}", RunAgentMessage([]), MSG_MGT
                )
        if scenario is not None:
            self._run_scenario(scenario)
        finished = self._finished_evt.wait(timeout)
        if finished:
            self.status = "FINISHED"
        else:
            self.status = "TIMEOUT"

    def _run_scenario(self, scenario):
        from pydcop_tpu.infrastructure.events_handler import (
            run_scenario_events,
        )

        threading.Thread(
            target=run_scenario_events, args=(self, scenario),
            daemon=True, name="scenario",
        ).start()

    # -- resilience: replication + repair ------------------------------- #

    def start_replication(self, k: int, timeout: float = 30):
        """Ask every hosting agent to place k replicas of each of its
        computations (reference orchestrator.py:223), then collect the
        resulting replica distribution."""
        from pydcop_tpu.replication.dist_ucs_hostingcosts import (
            ReplicateRequestMessage,
            replication_computation_name,
        )
        from pydcop_tpu.replication.objects import ReplicaDistribution

        # Every agent that registered a replication computation can
        # host replicas; only agents with computations run a search.
        prefix = replication_computation_name("")
        resilient = sorted(
            c[len(prefix):]
            for c in self._agent.discovery.computations()
            if c.startswith(prefix)
            and c[len(prefix):] not in self._removed_agents
        )
        self.replication_k = k
        expected = sorted(
            a for a in resilient
            if self.distribution.computations_hosted(a)
        )
        self.mgt.replication_done_agents = set()
        # Everyone gets the trigger (it carries the resilient-agent
        # set used to bound the search graph); agents hosting nothing
        # answer done immediately.
        for agent in resilient:
            self.mgt.post_msg(
                replication_computation_name(agent),
                ReplicateRequestMessage(k, resilient),
                MSG_MGT,
            )
        deadline = time.monotonic() + timeout
        while not set(expected) <= self.mgt.replication_done_agents:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                logger.warning(
                    "Replication timed out; done agents: %s",
                    sorted(self.mgt.replication_done_agents),
                )
                break
            self._replication_evt.clear()
            self._replication_evt.wait(min(0.1, remaining))
        return ReplicaDistribution(self.mgt.replica_hosts)

    def add_agent(self, agent_def, timeout: Optional[float] = None):
        """Scenario-driven agent arrival: spin up a new (empty) agent
        that can host replicas and repaired computations (reference
        scenario add_agent action, dcop/scenario.py:37).

        Blocks until the new agent has registered with the directory
        and reported ready, so a subsequent replication heal can see
        it (registration is asynchronous message traffic).  The default
        window is ``self.agent_ready_timeout`` — the runner sets it
        (process-mode agents need a spawn + package import before they
        can register)."""
        if timeout is None:
            timeout = self.agent_ready_timeout
        if self.agent_factory is None:
            logger.warning(
                "No agent factory: cannot add agent %s", agent_def.name
            )
            return
        # A departed agent can come back under the same name.
        self._removed_agents.discard(agent_def.name)
        self.dcop.add_agents([agent_def])
        self.agent_factory(agent_def)
        self.distribution.host_on_agent(agent_def.name, [])
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if agent_def.name in self.mgt.ready_agents:
                break
            time.sleep(0.05)
        else:
            logger.warning(
                "Agent %s did not report ready within %.0fs",
                agent_def.name, timeout,
            )
        logger.info("Agent %s added", agent_def.name)

    def remove_agent(self, agent: str):
        """Scenario-driven agent removal: stop the agent, then migrate
        its orphaned computations onto agents holding their replicas by
        solving the repair DCOP (reference orchestrator.py:955-1178)."""
        self.mgt.post_msg(f"_mgt_{agent}", StopAgentMessage(), MSG_MGT)
        self._handle_agent_failure(agent)

    def report_agent_failure(self, agent: str):
        """External failure report (fault monitor, health checks): the
        agent is already dead — no stop message — so unregister it from
        the directory (stopping messaging toward it and purging
        transport retry queues) and run the reparation path."""
        try:
            self._agent.discovery.unregister_agent(agent)
        except Exception:
            logger.exception("Unregistering failed agent %s", agent)
        self._handle_agent_failure(agent)

    def _on_discovery_agent_change(self, event: str, agent: str):
        """Discovery hook: an agent_removed publication during a run is
        a detected death (transports mark dead agents by unregistering
        them, communication.py _mark_agent_dead).  Repair runs on its
        own thread — this hook fires on the orchestrator agent thread,
        which must stay free to process the repair round's own
        messages."""
        if event != "agent_removed" or agent == ORCHESTRATOR_AGENT:
            return
        if self.status != "RUNNING" or agent in self._removed_agents:
            return
        threading.Thread(
            target=self._handle_agent_failure, args=(agent,),
            name=f"repair_{agent}", daemon=True,
        ).start()

    def _handle_agent_failure(self, agent: str):
        """Shared failure path: forget the agent, then repair.  Safe
        under concurrent detection (scenario removal + transport mark +
        fault monitor can all fire for the same death): the first
        caller wins the latch, the rest return.

        The lock spans the REPAIR too, not just the bookkeeping: two
        nearby deaths handled concurrently would otherwise interleave
        — failure B rebuilds ``self.distribution`` from a snapshot
        taken before failure A's repair committed its re-hosted
        placements (``host_on_agent`` mutates the OLD object), erasing
        them.  Repair waits on acks delivered by the orchestrator
        agent thread, which never takes this lock, so serializing here
        cannot deadlock — the second failure simply repairs after the
        first."""
        with self._failure_lock:
            if agent in self._removed_agents:
                return
            self._removed_agents.add(agent)
            if self.health_monitor is not None:
                # Removed through another detector (scenario event,
                # transport mark): stop scoring it so the silence that
                # FOLLOWS the removal cannot yield a second, spurious
                # death verdict.  A monitor-declared death keeps its
                # record.
                self.health_monitor.forget_removed(agent)
            tracer.instant("agent_failure", "orchestrator", agent=agent)
            orphaned = self.distribution.computations_hosted(agent)
            mapping = self.distribution.mapping
            mapping.pop(agent, None)
            self.distribution = Distribution(mapping)
            # Replicas hosted on the departed agent are gone with it.
            for hosts in self.mgt.replica_hosts.values():
                if agent in hosts:
                    hosts.remove(agent)
            logger.warning(
                "Agent %s removed; orphaned computations: %s",
                agent, orphaned,
            )
            if orphaned:
                with tracer.span("repair", "orchestrator",
                                 departed=agent,
                                 orphaned=len(orphaned)):
                    self.repair(orphaned, departed=[agent])

    def repair(self, orphaned: List[str], departed: List[str],
               timeout: float = 10):
        """Re-host orphaned computations on live replica holders.

        The repair problem is built as a DCOP (reparation builders) and
        solved per ``repair_mode``: centrally on the device engine (the
        TPU-native default), or distributed among the candidate agents
        themselves (``repair_mode="distributed"``, the reference's
        architecture — repair computations hosted on candidates,
        pydcop/infrastructure/agents.py:1384).  Falls back to a greedy
        assignment when the solve violates hard constraints.
        """
        from pydcop_tpu.replication.dist_ucs_hostingcosts import (
            ActivateReplicaMessage,
            replication_computation_name,
        )
        from pydcop_tpu.replication.objects import ReplicaDistribution
        from pydcop_tpu.reparation.removal import (
            candidate_agents,
            unrepairable_computations,
        )

        replicas = ReplicaDistribution(self.mgt.replica_hosts)
        candidates = candidate_agents(orphaned, replicas, departed)
        lost = unrepairable_computations(candidates)
        if lost:
            logger.error(
                "Computations lost (no live replica): %s", lost
            )
        repairable = [c for c in orphaned if c not in lost]
        if not repairable:
            return {}
        placement = self._solve_repair_dcop(repairable, candidates)
        # Activation is two-phase: distribution / replica bookkeeping is
        # only committed once the host *acknowledges* promoting its
        # replica.  A nacked activation (no replica on the host) fails
        # over to the next candidate; an unacked one (lost message) is
        # re-sent to the same host — activation is idempotent on the
        # host side, so redelivery is safe — until the deadline.
        committed: Dict[str, str] = {}
        tried: Dict[str, set] = {c: set() for c in placement}
        pending = dict(placement)
        # Acks are cumulative across scenario events; a previous
        # event's ack for the same (comp, host) pair must not satisfy
        # this round's activation.
        for comp in placement:
            self.mgt.repair_acked.pop(comp, None)
            self.mgt.repair_failed.pop(comp, None)
        deadline = time.monotonic() + timeout
        while pending:
            for comp, host in pending.items():
                tried[comp].add(host)
                self.mgt.post_msg(
                    replication_computation_name(host),
                    ActivateReplicaMessage(
                        comp,
                        [
                            h
                            for h in self.mgt.replica_hosts.get(comp, [])
                            if h != host
                        ],
                    ),
                    MSG_MGT,
                )
            # Wait one round for acks / nacks.
            round_deadline = min(deadline, time.monotonic() + 2.0)
            while True:
                acked = {
                    c for c in pending
                    if self.mgt.repair_acked.get(c) == pending[c]
                }
                failed = {
                    c for c in pending
                    if c not in acked
                    and self.mgt.repair_failed.get(c) == pending[c]
                }
                if acked | failed == set(pending):
                    break
                remaining = round_deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._repair_evt.clear()
                self._repair_evt.wait(min(0.1, remaining))
            for comp in acked:
                host = pending.pop(comp)
                committed[comp] = host
                self.distribution.host_on_agent(host, [comp])
                # The activated replica is consumed.
                if host in self.mgt.replica_hosts.get(comp, []):
                    self.mgt.replica_hosts[comp].remove(host)
            if time.monotonic() >= deadline:
                if pending:
                    logger.warning(
                        "Repair timed out; unrepaired: %s",
                        sorted(pending),
                    )
                break
            retry: Dict[str, str] = {}
            for comp, host in pending.items():
                if comp not in failed and \
                        host not in self._removed_agents:
                    # Unacked: lost request or delayed ack — re-send to
                    # the same host next round.
                    retry[comp] = host
                    continue
                # Nacked, or the host itself departed mid-repair (it
                # will never answer): fail over to the next candidate.
                self.mgt.repair_failed.pop(comp, None)
                if host in self.mgt.replica_hosts.get(comp, []):
                    # The host refused, so its replica record is stale.
                    self.mgt.replica_hosts[comp].remove(host)
                untried = [
                    a for a in candidates.get(comp, [])
                    if a not in tried[comp]
                ]
                if untried:
                    retry[comp] = untried[0]
                else:
                    logger.error(
                        "Repair of %s failed: all candidates refused",
                        comp,
                    )
            pending = retry
        logger.info("Repair placement: %s", committed)
        return committed

    def _solve_repair_dcop(self, orphaned: List[str],
                           candidates: Dict[str, List[str]]
                           ) -> Dict[str, str]:
        """Build + solve the repair DCOP; returns comp -> agent."""
        from pydcop_tpu.reparation import (
            create_agent_capacity_constraint,
            create_agent_comp_comm_constraint,
            create_agent_hosting_constraint,
            create_computation_hosted_constraint,
            create_binary_variables_for,
        )

        agent_defs = self.dcop.agents
        # Round-unique variable names: stale messages from a previous
        # (timed-out) distributed round target names that no longer
        # exist, so they can never be misread as this round's result.
        self._repair_round = getattr(self, "_repair_round", 0) + 1
        variables = create_binary_variables_for(
            orphaned, candidates, suffix=f"__r{self._repair_round}")
        repair = DCOP("_repair", objective="min")
        for var in variables.values():
            repair.add_variable(var)
        by_agent: Dict[str, Dict[str, Any]] = {}
        for (comp, agt), var in variables.items():
            by_agent.setdefault(agt, {})[comp] = var
        for comp in orphaned:
            repair.add_constraint(create_computation_hosted_constraint(
                comp, [variables[(comp, a)] for a in candidates[comp]]
            ))
        for agt, agt_vars in by_agent.items():
            agent_def = agent_defs.get(agt)
            capacity = (
                agent_def.capacity if agent_def is not None else None
            )
            if capacity is not None:
                repair.add_constraint(create_agent_capacity_constraint(
                    agt, self._remaining_capacity(agt),
                    {
                        c: self._effective_repair_footprint(c, agt)
                        for c in agt_vars
                    },
                    agt_vars,
                ))
            hosting_costs = {
                c: (agent_def.hosting_cost(c)
                    if agent_def is not None else 0.0)
                for c in agt_vars
            }
            if any(hosting_costs.values()):
                repair.add_constraint(create_agent_hosting_constraint(
                    agt, hosting_costs, agt_vars
                ))
            # Soft communication costs: route to each neighbor
            # computation's current host (orphaned neighbors skipped —
            # their future host is part of the same repair problem).
            for comp, var in agt_vars.items():
                neighbor_agents = {}
                try:
                    node = self.cg.computation(comp)
                    for neighbor in node.neighbors:
                        if neighbor in orphaned:
                            continue
                        try:
                            neighbor_agents[neighbor] = \
                                self.distribution.agent_for(neighbor)
                        except KeyError:
                            pass
                except Exception:
                    pass
                if neighbor_agents and agent_def is not None:
                    repair.add_constraint(
                        create_agent_comp_comm_constraint(
                            agt, comp, neighbor_agents,
                            lambda a, b: agent_defs[a].route(b)
                            if a in agent_defs else 1.0,
                            self._comm_load,
                            var,
                        ))
        placement = self._assign_from_repair_solve(
            repair, variables, orphaned, candidates
        )
        return placement

    def _remaining_capacity(self, agent: str) -> float:
        """Capacity minus active computations and known replicas."""
        agent_def = self.dcop.agents.get(agent)
        if agent_def is None or agent_def.capacity is None:
            return float("inf")
        used = sum(
            self._footprint(c)
            for c in self.distribution.computations_hosted(agent)
        )
        used += sum(
            self._footprint(c)
            for c, hosts in self.mgt.replica_hosts.items()
            if agent in hosts
        )
        return agent_def.capacity - used

    def _effective_repair_footprint(self, comp: str, agent: str) -> float:
        """Extra capacity needed to host ``comp`` on ``agent`` during
        repair.  ``_remaining_capacity`` already charges the agent for
        every replica it holds; promoting one of *its own* replicas to
        live converts that charge in place, so the net cost is zero —
        charging the footprint again would falsely reject near-capacity
        replica holders."""
        if agent in self.mgt.replica_hosts.get(comp, []):
            return 0.0
        return self._footprint(comp)

    def _comm_load(self, computation: str, neighbor: str) -> float:
        from pydcop_tpu.algorithms import load_algorithm_module

        try:
            module = load_algorithm_module(self.algo.algo)
            return float(module.communication_load(
                self.cg.computation(computation), neighbor
            ))
        except Exception:
            return 1.0

    def _solve_repair_distributed(self, repair: DCOP, variables
                                  ) -> Optional[Dict[str, Any]]:
        """Solve the repair DCOP *among the candidate agents*: each
        binary decision variable x_(comp, agent) is deployed on
        `agent` itself, the group runs a bounded synchronous search,
        and the orchestrator only collects the final values (reference
        architecture: repair computations hosted on candidate agents,
        pydcop/infrastructure/agents.py:1384)."""
        from pydcop_tpu.algorithms import AlgorithmDef, ComputationDef
        from pydcop_tpu.computations_graph import (
            constraints_hypergraph as chg_mod,
        )
        from pydcop_tpu.infrastructure.orchestratedagents import (
            RemoveComputationsMessage,
        )

        per_agent: Dict[str, List[str]] = {}
        names = {var.name for var in variables.values()}
        # Active transients: reported values/cycles are recorded (the
        # round's result) but excluded from metrics collection and
        # progress events while the round runs.
        self.mgt.active_transients |= names
        try:
            repair_cg = chg_mod.build_computation_graph(repair)
            repair_algo = AlgorithmDef.build_with_default_param(
                "dsa", {"stop_cycle": 30, "variant": "B"}, mode="min",
            )
            for (comp, agt), var in variables.items():
                per_agent.setdefault(agt, []).append(var.name)
                node = repair_cg.computation(var.name)
                self.mgt.post_msg(
                    f"_mgt_{agt}",
                    DeployMessage(ComputationDef(node, repair_algo)),
                    MSG_MGT,
                )
            for agt, comps in per_agent.items():
                self.mgt.post_msg(
                    f"_mgt_{agt}", RunAgentMessage(comps), MSG_MGT
                )
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                if names <= self.mgt.finished_computations:
                    break
                time.sleep(0.05)
            assignment = {
                n: self.mgt.assignment.get(n) for n in names
            }
            missing = [n for n, v in assignment.items() if v is None]
            if missing:
                logger.warning(
                    "Distributed repair incomplete (no value for %s)",
                    missing,
                )
                assignment = None
            return assignment
        finally:
            for agt, comps in per_agent.items():
                self.mgt.post_msg(
                    f"_mgt_{agt}",
                    RemoveComputationsMessage(comps), MSG_MGT,
                )
            # Retire the names (straggler messages are dropped on
            # arrival — with round-unique names a later round can never
            # collide with them) and purge the round's bookkeeping so
            # final metrics never see the temporary computations.
            self.mgt.active_transients -= names
            self.mgt.retired_transients |= names
            self.mgt.purge_computations(names)

    def _assign_from_repair_solve(self, repair: DCOP, variables,
                                  orphaned, candidates
                                  ) -> Dict[str, str]:
        assignment = None
        if self.repair_mode == "distributed":
            try:
                assignment = self._solve_repair_distributed(
                    repair, variables)
            except Exception:
                logger.exception(
                    "Distributed repair failed; using greedy"
                )
        else:
            try:
                from pydcop_tpu.api import solve as api_solve

                res = api_solve(
                    repair, "maxsum", backend="device", max_cycles=60,
                )
                assignment = res["assignment"]
            except Exception:
                logger.exception(
                    "Device solve of repair DCOP failed; using greedy"
                )
        placement: Dict[str, str] = {}
        if assignment is not None:
            for comp in orphaned:
                chosen = [
                    a for a in candidates[comp]
                    if assignment.get(
                        variables[(comp, a)].name, 0
                    ) == 1
                ]
                if len(chosen) == 1:
                    placement[comp] = chosen[0]
                else:
                    placement = {}
                    break
        if placement:
            # The device solve is approximate: a one-host-per-comp
            # solution can still violate the capacity hard constraint.
            # Verify before accepting, else fall back to greedy.
            load: Dict[str, float] = {}
            for comp, agt in placement.items():
                load[agt] = load.get(agt, 0.0) + \
                    self._effective_repair_footprint(comp, agt)
            for agt, used in load.items():
                if used > self._remaining_capacity(agt):
                    logger.warning(
                        "Repair solve oversubscribes %s; using greedy",
                        agt,
                    )
                    placement = {}
                    break
        if not placement:
            # Greedy fallback: cheapest (hosting cost, load) candidate
            # with enough remaining capacity (capacity-less agents are
            # always eligible); if no candidate fits, least-loaded
            # wins — better oversubscribed than lost.
            agent_defs = self.dcop.agents
            loads: Dict[str, float] = {}
            for comp in sorted(
                orphaned, key=lambda c: -self._footprint(c)
            ):
                fitting = [
                    a for a in candidates[comp]
                    if self._remaining_capacity(a) - loads.get(a, 0.0)
                    >= self._effective_repair_footprint(comp, a)
                ]
                pool = fitting or candidates[comp]
                best = min(
                    pool,
                    key=lambda a: (
                        (agent_defs[a].hosting_cost(comp)
                         if a in agent_defs else 0.0),
                        loads.get(a, 0.0),
                    ),
                )
                placement[comp] = best
                loads[best] = loads.get(best, 0.0) + \
                    self._effective_repair_footprint(comp, best)
        return placement

    def _footprint(self, comp_name: str) -> float:
        from pydcop_tpu.algorithms import load_algorithm_module

        try:
            module = load_algorithm_module(self.algo.algo)
            return float(
                module.computation_memory(self.cg.computation(comp_name))
            )
        except Exception:
            return 1.0

    def pause_agents(self):
        for agent in self.distribution.agents:
            self.mgt.post_msg(f"_mgt_{agent}", PauseMessage([]), MSG_MGT)

    def resume_agents(self):
        for agent in self.distribution.agents:
            self.mgt.post_msg(f"_mgt_{agent}", ResumeMessage([]), MSG_MGT)

    def stop_agents(self, timeout: float = 5):
        # Every agent that registered gets a stop — idle agents (no
        # hosted computation, e.g. spare resilient agents) must exit
        # too.
        for agent in (set(self.distribution.agents)
                      | self.mgt.ready_agents) - self._removed_agents:
            self.mgt.post_msg(
                f"_mgt_{agent}", StopAgentMessage(), MSG_MGT
            )
        self._all_stopped_evt.wait(timeout)

    # -- callbacks from mgt -------------------------------------------- #

    def _on_progress(self):
        pass

    def _check_all_finished(self):
        if set(self._expected_computations) <= \
                self.mgt.finished_computations:
            self._finished_evt.set()

    def _on_agent_stopped(self, agent: str):
        self._stopped_agents.add(agent)
        expected = {
            a for a in self.distribution.agents
            if self.distribution.computations_hosted(a)
        }
        if expected <= self._stopped_agents:
            self._all_stopped_evt.set()

    # -- results ------------------------------------------------------- #

    def current_global_cost(self):
        metrics = self.mgt.global_metrics(self.status)
        return metrics["cost"], metrics["violation"]

    def end_metrics(self) -> Dict:
        return self.mgt.global_metrics(self.status)

"""Tensorized (level-batched, jitted) DPOP vs the numpy sweep.

The jit path must be bit-compatible with the per-node host sweep on the
solution *cost* (assignments can differ only on exact-tie optima, which
the seeded float costs below make improbable).  Reference semantics:
pydcop/algorithms/dpop.py:313-439.
"""

import numpy as np
import pytest

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.algorithms.dpop import solve_on_device
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation


def random_dcop(n, d, seed, extra_edges=0, objective="min", wide=True):
    """Random spanning tree + optional extra (cycle-creating) edges."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", list(range(d)))
    dcop = DCOP("t", objective=objective)
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    k = 0
    for i in range(1, n):
        p = rng.integers(0, i) if wide else rng.integers(max(0, i - 2), i)
        m = rng.random((d, d))
        dcop.add_constraint(
            NAryMatrixRelation([vs[p], vs[i]], m, f"c{k}")
        )
        k += 1
    for _ in range(extra_edges):
        i, j = rng.choice(n, size=2, replace=False)
        m = rng.random((d, d))
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], m, f"c{k}")
        )
        k += 1
    return dcop


def _solve(dcop, engine):
    algo = AlgorithmDef.build_with_default_param(
        "dpop", {"engine": engine}, mode=dcop.objective
    )
    return solve_on_device(dcop, algo)


class TestJitNumpyParity:
    @pytest.mark.parametrize("seed", [0, 1, 2])
    @pytest.mark.parametrize("d", [2, 4])
    def test_tree_parity(self, seed, d):
        dcop = random_dcop(60, d, seed)
        r_jit = _solve(dcop, "jit")
        r_np = _solve(dcop, "numpy")
        assert r_jit.metrics["engine"] == "jit"
        assert r_np.metrics["engine"] == "numpy"
        assert r_jit.metrics["device_cost"] == pytest.approx(
            r_np.metrics["device_cost"], abs=1e-3
        )

    @pytest.mark.parametrize("seed", [3, 4])
    def test_cyclic_graph_parity(self, seed):
        """Back edges create pseudo-parents and wider separators."""
        dcop = random_dcop(40, 3, seed, extra_edges=12)
        r_jit = _solve(dcop, "jit")
        r_np = _solve(dcop, "numpy")
        assert r_jit.metrics["device_cost"] == pytest.approx(
            r_np.metrics["device_cost"], abs=1e-3
        )

    def test_max_mode_parity(self):
        dcop = random_dcop(50, 3, 7, extra_edges=5, objective="max")
        r_jit = _solve(dcop, "jit")
        r_np = _solve(dcop, "numpy")
        assert r_jit.metrics["device_cost"] == pytest.approx(
            r_np.metrics["device_cost"], abs=1e-3
        )

    def test_forest_parity(self):
        """Disconnected components: several roots, independent sweeps."""
        rng = np.random.default_rng(11)
        dom = Domain("c", "", [0, 1, 2])
        dcop = DCOP("f", objective="min")
        vs = [Variable(f"v{i}", dom) for i in range(30)]
        for v in vs:
            dcop.add_variable(v)
        # Three 10-node trees.
        for base in (0, 10, 20):
            for i in range(base + 1, base + 10):
                p = rng.integers(base, i)
                dcop.add_constraint(NAryMatrixRelation(
                    [vs[p], vs[i]], rng.random((3, 3)), f"c{i}"
                ))
        r_jit = _solve(dcop, "jit")
        r_np = _solve(dcop, "numpy")
        assert r_jit.metrics["device_cost"] == pytest.approx(
            r_np.metrics["device_cost"], abs=1e-3
        )
        assert len(r_jit.assignment) == 30

    def test_mixed_domain_sizes(self):
        rng = np.random.default_rng(13)
        doms = [Domain(f"d{k}", "", list(range(k))) for k in (2, 3, 5)]
        dcop = DCOP("m", objective="min")
        vs = [Variable(f"v{i}", doms[i % 3]) for i in range(24)]
        for v in vs:
            dcop.add_variable(v)
        for i in range(1, 24):
            p = rng.integers(0, i)
            shape = (len(vs[p].domain), len(vs[i].domain))
            dcop.add_constraint(NAryMatrixRelation(
                [vs[p], vs[i]], rng.random(shape), f"c{i}"
            ))
        r_jit = _solve(dcop, "jit")
        r_np = _solve(dcop, "numpy")
        assert r_jit.metrics["device_cost"] == pytest.approx(
            r_np.metrics["device_cost"], abs=1e-3
        )

    def test_ternary_constraints(self):
        rng = np.random.default_rng(17)
        dom = Domain("c", "", [0, 1, 2])
        dcop = DCOP("t3", objective="min")
        vs = [Variable(f"v{i}", dom) for i in range(12)]
        for v in vs:
            dcop.add_variable(v)
        for i in range(2, 12):
            dcop.add_constraint(NAryMatrixRelation(
                [vs[i - 2], vs[i - 1], vs[i]],
                rng.random((3, 3, 3)), f"c{i}",
            ))
        r_jit = _solve(dcop, "jit")
        r_np = _solve(dcop, "numpy")
        assert r_jit.metrics["device_cost"] == pytest.approx(
            r_np.metrics["device_cost"], abs=1e-3
        )


class TestGuards:
    def test_util_too_large_refused(self):
        from pydcop_tpu.computations_graph import pseudotree as pt
        from pydcop_tpu.ops.dpop import UtilTooLargeError, compile_tree

        rng = np.random.default_rng(19)
        dom = Domain("c", "", list(range(30)))
        dcop = DCOP("wide", objective="min")
        # A clique of 8 30-value variables: separator width 7 at the
        # deepest node -> 30^8 elements, far beyond the cap.
        vs = [Variable(f"v{i}", dom) for i in range(8)]
        for v in vs:
            dcop.add_variable(v)
        k = 0
        for i in range(8):
            for j in range(i + 1, 8):
                dcop.add_constraint(NAryMatrixRelation(
                    [vs[i], vs[j]], rng.random((30, 30)), f"c{k}"
                ))
                k += 1
        graph = pt.build_computation_graph(dcop)
        with pytest.raises(UtilTooLargeError):
            compile_tree(graph, "min")

    def test_auto_prefers_numpy_on_deep_chains(self):
        dcop = random_dcop(40, 3, 23, wide=False)
        res = _solve(dcop, "auto")
        assert res.metrics["engine"] == "numpy"

    def test_auto_prefers_jit_on_wide_trees(self):
        dcop = random_dcop(300, 3, 29, wide=True)
        res = _solve(dcop, "auto")
        assert res.metrics["engine"] == "jit"

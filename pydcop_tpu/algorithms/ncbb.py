"""NCBB: No-Commitment Branch and Bound (Chechetka & Sycara, 2006).

Reference parity: pydcop/algorithms/ncbb.py (:139-350) — one computation
per variable on a DFS pseudo-tree, binary constraints only, synchronous.
Two phases: INIT (VALUE messages flow root→leaves, each variable greedily
picks the value optimal w.r.t. its already-assigned ancestors; leaves
start COST messages that accumulate subtree upper bounds on the way back
up, :216-330) and SEARCH.  The reference's search phase is a stub
(``search()`` is ``pass``, ncbb.py:341), so its observable result is the
greedy INIT assignment; here the engine path runs a *complete* search —
AND/OR branch-and-bound over the pseudo-tree, where sibling subtrees are
solved independently given their ancestor context (the "concurrent
search in different partitions" of the original article) with the INIT
upper bound used for pruning — and therefore returns the optimum.

Engine path: sequential host search (branch & bound is inherently
sequential, like syncbb); constraint tables are pre-materialized dense
numpy arrays so per-node evaluation is array indexing, and static
per-subtree lower bounds provide admissible pruning.

Agent mode implements the SEARCH phase as a distributed AND/OR search
with message passing over the pseudo-tree (sibling subtrees explored
concurrently, memoized per ancestor context) and also returns the
optimum — see infrastructure/agent_algorithms.NcbbComputation.

Example (doctest, runs on the CPU backend under ``make doctest``)::

    >>> from pydcop_tpu.api import solve
    >>> from pydcop_tpu.dcop.dcop import DCOP
    >>> from pydcop_tpu.dcop.objects import Domain, Variable
    >>> from pydcop_tpu.dcop.relations import constraint_from_str
    >>> d = Domain('d', '', [0, 1])
    >>> x, y = Variable('x', d), Variable('y', d)
    >>> dcop = DCOP('doc', objective='min')
    >>> dcop.add_constraint(constraint_from_str('c', '(x + y - 1)**2', [x, y]))
    >>> res = solve(dcop, 'ncbb')
    >>> round(res['cost'], 3)
    0.0
"""

from typing import Dict, List, Optional

import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef
from pydcop_tpu.computations_graph import pseudotree as pt
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.engine.runner import DeviceRunResult
from pydcop_tpu.infrastructure.computations import ComputationException

GRAPH_TYPE = "pseudotree"

algo_params = []


def computation_memory(node) -> float:
    return pt.computation_memory(node)


def communication_load(src, target: str) -> float:
    return pt.communication_load(src, target)


def build_computation(comp_def):
    from pydcop_tpu.infrastructure.computations import build_algo_computation

    return build_algo_computation("ncbb", comp_def)


def _check_binary(graph) -> None:
    """Reference ncbb.py:169-177: only binary constraints are supported
    (unary costs ride on the variable's cost vector instead)."""
    for node in graph.nodes:
        for c in node.constraints:
            if c.arity > 2:
                raise ComputationException(
                    f"Invalid constraint {c} with arity {c.arity} for "
                    f"variable {node.name}, NCBB only supports binary "
                    "constraints."
                )


def solve_on_device(dcop: DCOP, algo_def: AlgorithmDef,
                    max_cycles: int = 0, mesh=None,
                    n_devices: Optional[int] = None,
                    **_) -> DeviceRunResult:
    import time

    t0 = time.perf_counter()
    mode = dcop.objective
    sign = 1.0 if mode == "min" else -1.0
    graph = pt.build_computation_graph(dcop)
    _check_binary(graph)
    nodes = {n.name: n for n in graph.nodes}

    # Dense per-node data, sign-adjusted so the search always minimizes.
    # Each constraint is charged at the lowest (deepest) node of its
    # scope — for binary constraints on a pseudo-tree the other scope
    # variable is always an ancestor of that node.
    domains: Dict[str, list] = {}
    unary: Dict[str, np.ndarray] = {}
    charged: Dict[str, list] = {}  # name -> [(ancestor or None, table)]
    for name, node in nodes.items():
        domains[name] = list(node.variable.domain)
        unary[name] = sign * node.variable.cost_vector()
        charged[name] = []
        for c in node.constraints:
            table = sign * np.asarray(c.to_array(), dtype=np.float64)
            if c.arity == 1:
                unary[name] = unary[name] + table
                continue
            other = next(n for n in c.scope_names if n != name)
            # Order the table as [other, self] for uniform indexing.
            if c.scope_names[0] == name:
                table = table.T
            charged[name].append((other, table))

    # Static admissible lower bound per subtree (used for pruning).
    lb_subtree: Dict[str, float] = {}

    def _lb(name: str) -> float:
        if name not in lb_subtree:
            node = nodes[name]
            own = float(np.min(unary[name]))
            for _, table in charged[name]:
                own += float(np.min(table))
            lb_subtree[name] = own + sum(_lb(ch) for ch in node.children)
        return lb_subtree[name]

    for name in nodes:
        _lb(name)

    # ---- INIT phase: greedy top-down, exactly the reference's VALUE
    # propagation (each variable optimizes w.r.t. assigned ancestors).
    greedy: Dict[str, int] = {}
    roots = [n.name for n in graph.nodes if n.parent is None]
    order: List[str] = []
    stack = list(roots)
    while stack:
        name = stack.pop()
        order.append(name)
        costs = unary[name].copy()
        for other, table in charged[name]:
            if other in greedy:
                costs = costs + table[greedy[other], :]
        greedy[name] = int(np.argmin(costs))
        stack.extend(nodes[name].children)
    upper_bound = _assignment_cost(greedy, unary, charged)
    msg_count = 2 * len(order)  # VALUE down + COST up

    # ---- SEARCH phase: AND/OR branch and bound.  Sibling subtrees are
    # independent given the ancestor context, so each is searched on its
    # own with a budget derived from the current bound.
    steps = 0

    def search(name: str, context: Dict[str, int], budget: float):
        """Best (cost, assignment) for the subtree rooted at ``name``
        given ancestor values ``context``; (inf, None) if nothing beats
        ``budget``."""
        nonlocal steps
        node = nodes[name]
        costs = unary[name].copy()
        for other, table in charged[name]:
            costs = costs + table[context[other], :]
        children = node.children
        children_lb = sum(lb_subtree[ch] for ch in children)
        best_cost, best_assign = np.inf, None
        # Visit values cheapest-first so good bounds arrive early.
        for v in np.argsort(costs, kind="stable"):
            steps += 1
            own = float(costs[v])
            bound = min(budget, best_cost)
            if own + children_lb >= bound:
                break  # sorted order: no later value can do better
            total = own
            assign = {name: int(v)}
            ctx = {**context, name: int(v)}
            ok = True
            for i, ch in enumerate(children):
                rest_lb = sum(lb_subtree[c] for c in children[i + 1:])
                ch_cost, ch_assign = search(
                    ch, ctx, bound - total - rest_lb
                )
                if ch_assign is None:
                    ok = False
                    break
                total += ch_cost
                assign.update(ch_assign)
            if ok and total < best_cost:
                best_cost, best_assign = total, assign
        return best_cost, best_assign

    assignment_idx: Dict[str, int] = {}
    total_cost = 0.0
    for root in roots:
        # Give each root the greedy bound for its own tree plus slack of
        # what other trees can still save; independent trees, so just use
        # the global upper bound minus other trees' lower bounds.
        others_lb = sum(lb_subtree[r] for r in roots if r != root)
        cost, assign = search(root, {}, upper_bound - others_lb + 1e-9)
        if assign is None:
            # Greedy was already optimal for this subtree.
            sub = _subtree_names(nodes, root)
            assign = {n: greedy[n] for n in sub}
            cost = _assignment_cost(
                assign, unary, charged, restrict=set(sub)
            )
        assignment_idx.update(assign)
        total_cost += cost

    elapsed = time.perf_counter() - t0
    assignment = {
        name: domains[name][idx] for name, idx in assignment_idx.items()
    }
    cost, _ = dcop.solution_cost(assignment)
    return DeviceRunResult(
        assignment=assignment,
        cycles=steps,
        converged=True,
        time_s=elapsed,
        compile_time_s=0.0,
        metrics={
            "msg_count": msg_count + steps,
            "device_cost": cost,
            "upper_bound": float(sign * upper_bound),
        },
    )


def _subtree_names(nodes, root: str) -> List[str]:
    out, stack = [], [root]
    while stack:
        n = stack.pop()
        out.append(n)
        stack.extend(nodes[n].children)
    return out


def _assignment_cost(assign: Dict[str, int], unary, charged,
                     restrict=None) -> float:
    total = 0.0
    for name, v in assign.items():
        if restrict is not None and name not in restrict:
            continue
        total += float(unary[name][v])
        for other, table in charged[name]:
            total += float(table[assign[other], v])
    return total

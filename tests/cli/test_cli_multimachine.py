"""CLI tests for process mode + standalone orchestrator/agent commands.

This is how multi-node behavior is tested without a cluster (reference
strategy, tests/dcop_cli/test_solve.py:55-58): HTTP transports on
localhost ports.
"""

import json
import os
import subprocess
import sys
import time

from fixtures_paths import LOCAL_INSTANCES as INSTANCES
ENV = {
    **os.environ,
    "JAX_PLATFORMS": "cpu",
    "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
}
FIXTURE = os.path.join(INSTANCES, "coloring_4agents_10vars.yaml")


def test_solve_mode_process():
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "5",
         "solve", "-a", "dsa", "-d", "adhoc", "-m", "process",
         FIXTURE],
        timeout=180, env=ENV,
    )
    result = json.loads(out)
    assert result["backend"] == "process"
    assert len(result["assignment"]) == 10
    assert result["msg_count"] > 0


def test_orchestrator_and_agent_commands(tmp_path):
    port = 19340
    agent_proc = subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "40",
         "agent", "-n", "a1", "a2", "a3", "a4",
         "-o", f"127.0.0.1:{port}", "-p", str(port + 1),
         "--capacity", "100"],
        env=ENV, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        time.sleep(0.5)
        out = subprocess.check_output(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "4",
             "orchestrator", "-a", "dsa", "-d", "adhoc",
             "--port", str(port), FIXTURE],
            timeout=120, env=ENV, stderr=subprocess.DEVNULL,
        )
        result = json.loads(out)
        assert result["backend"] == "multi-machine"
        assert len(result["assignment"]) == 10
        # Agents exit once the orchestrator stops them.
        assert agent_proc.wait(timeout=30) == 0
    finally:
        if agent_proc.poll() is None:
            agent_proc.kill()


def test_solve_mode_process_maxsum():
    """MaxSum over HTTP: factor/variable computations and their custom
    wire format (MaxSumMessage costs dict) cross real process + JSON
    boundaries.  MaxSum has no stop condition, so the run always lasts
    the full -t: large enough to converge under machine load (8 s was
    flaky during parallel benches), small enough to keep the suite
    quick."""
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "12",
         "solve", "-a", "maxsum", "-d", "adhoc", "-m", "process",
         os.path.join(INSTANCES, "coloring_chain.yaml")],
        timeout=180, env=ENV,
    )
    result = json.loads(out)
    assert result["backend"] == "process"
    assert set(result["assignment"]) == {"w1", "w2", "w3", "w4"}
    # Converged to a feasible coloring of the 4-chain (maxsum folds the
    # unary preferences in, so any proper coloring costs <= 0.6).
    assert result["cost"] <= 0.6 + 1e-6


def test_solve_mode_process_mgm2():
    """MGM2's 5-phase protocol (value/offer/response/gain/go) over the
    HTTP transport: offers are tuple-triples that JSON converts to
    lists, so this exercises sequence-robust message handling."""
    out = subprocess.check_output(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "10",
         "solve", "-a", "mgm2", "-d", "adhoc", "-m", "process",
         "-p", "stop_cycle:20",
         os.path.join(INSTANCES, "coloring_chain.yaml")],
        timeout=180, env=ENV,
    )
    result = json.loads(out)
    assert result["backend"] == "process"
    assert set(result["assignment"]) == {"w1", "w2", "w3", "w4"}


def test_orchestrator_scenario_repair_over_http(tmp_path):
    """Dynamic multi-machine run: standalone orchestrator with a
    scenario that removes agent a1 mid-run, 2-replication, repair over
    real HTTP transports — the full reference resilience flow
    (orchestrator.py:955-1178) end to end."""
    port = 19480
    scenario = os.path.join(
        os.path.dirname(__file__), "..", "instances",
        "scenario_remove_a1.yaml")
    agent_proc = subprocess.Popen(
        [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "90",
         "agent", "-n", "a1", "a2", "a3", "a4",
         "-o", f"127.0.0.1:{port}", "-p", str(port + 1),
         "--capacity", "100", "--replication"],
        env=ENV, stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )
    try:
        time.sleep(0.5)
        out = subprocess.check_output(
            [sys.executable, "-m", "pydcop_tpu.dcop_cli", "-t", "15",
             "orchestrator", "-a", "dsa", "-d", "adhoc",
             "-k", "2", "-s", scenario, "--port", str(port),
             FIXTURE],
            timeout=120, env=ENV, stderr=subprocess.DEVNULL,
        )
        result = json.loads(out)
        assert result["backend"] == "multi-machine"
        # All 10 variables still assigned despite a1's departure.
        assert len(result["assignment"]) == 10
        replication = result["replication"]
        assert replication["ktarget"] == 2
        # a1 hosted computations; they must have been repaired onto
        # surviving agents.
        assert replication["repaired"]
        assert agent_proc.wait(timeout=45) == 0
    finally:
        if agent_proc.poll() is None:
            agent_proc.kill()

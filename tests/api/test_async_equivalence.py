"""Statistical comparison of the device (lockstep) async algorithms
vs the thread (true-async) runtime, across 20 seeds with paired
confidence intervals (round-3 verdict: the old claim rested on one
seed per algorithm).

amaxsum and adsa are genuinely asynchronous in agent mode; on device
they run as lockstep BSP (documented in algorithms/amaxsum.py and
adsa.py).  Measured findings these tests pin down:

- amaxsum: no systematic quality difference at native budgets — the
  95% CI upper bound of the paired cost difference stays within 5% of
  the constraint count.
- adsa at MATCHED cycle budgets (60 vs 60): lockstep is measurably a
  little worse (mean paired gap ~+3% of the constraint count across
  runs) — simultaneous neighbor flips thrash in ways the clock-skewed
  async updates avoid.  The test BOUNDS this known gap at 10% rather
  than asserting a false equivalence.
- adsa STAGGERED schedule at matched budgets (round-5 attempt to close
  that gap): the variable graph is greedily colored and one class
  flips per superstep (one cycle = one full sweep), so neighbors never
  flip simultaneously — the device-side emulation of async clock skew.
  RECORDED NEGATIVE RESULT: the schedule neither helps nor hurts.
  Across repeated 20-seed batteries the thread-paired mean wandered
  (-2.3, then +1.25) inside the thread-side noise floor (per-seed sd
  ~15 → CI half-width ~7 = 4.5% of constraints, so a 5% bound is not
  certifiable at n=20 regardless of the true mean); the DETERMINISTIC
  device-device pairing (no thread noise, same seeds) measures
  staggered - lockstep = +1.45 mean — statistically flat.  Mechanism:
  at p=0.7 flip probability on this sparse family (~3.9 avg degree),
  simultaneous-neighbor flips are too rare for schedule skew to
  matter, which also means the round-4 "+3% lockstep gap" attribution
  was itself within measurement noise.  Asserted: 10% vs thread (the
  certifiable bound), and a deterministic |mean| <= 3%-of-constraints
  device-device equivalence below.
- adsa at NATIVE budgets (device 200 cycles vs thread 60): the mean
  gap disappears (~0 across runs) — device cycles are ~free, so the
  lockstep engine simply runs more of them; this is the practically
  relevant comparison.  The asserted bound is 10% (the smallest
  effect n=20 can reliably exonerate given per-seed sd ~15).

Both engines' cost trajectories oscillate, so each observation is a
noisy sample (sd ~ 10 cost units at this size); 20 paired samples
shrink the CI enough to separate systematic gaps from per-seed
lottery.  Problem size matters too: at ~150 constraints the sampling
noise is a few percent of total cost, where on tiny problems it
swamps the comparison.
"""

import math

import pytest

from pydcop_tpu.api import solve
from pydcop_tpu.generators.graphcoloring import generate_graph_coloring

SEEDS = list(range(1, 21))
N_VARS = 80
N_COLORS = 3
P_EDGE = 0.045
# two-sided t quantile, 97.5%, df = len(SEEDS) - 1 = 19
T_975 = 2.093


def _problem(seed):
    return generate_graph_coloring(
        N_VARS, N_COLORS, graph="random", soft=True, p_edge=P_EDGE,
        allow_subgraph=True, seed=seed,
    )


def _ci_upper(diffs):
    n = len(diffs)
    mean = sum(diffs) / n
    var = sum((d - mean) ** 2 for d in diffs) / (n - 1)
    half = T_975 * math.sqrt(var / n)
    return mean, mean + half


def _paired_diffs(algo, dev_cycles, dev_params, thread_kw):
    diffs = []
    n_constraints = None
    for seed in SEEDS:
        dcop_dev = _problem(seed)
        n_constraints = len(dcop_dev.constraints)
        params = dict(dev_params) if dev_params else None
        if params is not None and "seed" in params:
            params["seed"] = seed
        res_dev = solve(dcop_dev, algo, max_cycles=dev_cycles,
                        algo_params=params)
        res_thr = solve(_problem(seed), algo, backend="thread",
                        distribution="adhoc", **thread_kw)
        diffs.append(res_dev["cost"] - res_thr["cost"])
    return diffs, n_constraints


@pytest.mark.slow
@pytest.mark.parametrize("algo,dev_cycles,dev_params,thread_kw,tol_frac", [
    # amaxsum, native budgets: equivalence.
    ("amaxsum", 200, None, {"timeout": 6}, 0.05),
    # adsa, matched 60-cycle budgets: bound the known lockstep gap.
    ("adsa", 200, {"seed": 0, "stop_cycle": 60},
     {"timeout": 12, "algo_params": {"stop_cycle": 60, "period": 0.05}},
     0.10),
    # adsa staggered schedule, matched budgets: same certifiable bound
    # as lockstep (10%) — see the module docstring's negative result.
    ("adsa", 200,
     {"seed": 0, "stop_cycle": 60, "schedule": "staggered"},
     {"timeout": 12, "algo_params": {"stop_cycle": 60, "period": 0.05}},
     0.10),
    # adsa, native budgets: device's extra (near-free) cycles close
    # the gap (mean diff ~0 across runs).  The bound is 10%, not 5%:
    # per-seed sd is ~15 cost units under CI load, so the 95% CI
    # half-width at n=20 is ~7 — a 5% (7.7) bound would fail on CI
    # width alone even with a zero mean.  10% is the smallest
    # systematic effect this sample size can reliably exonerate.
    ("adsa", 200, {"seed": 0},
     {"timeout": 12, "algo_params": {"stop_cycle": 60, "period": 0.05}},
     0.10),
])
def test_lockstep_vs_async_quality(algo, dev_cycles, dev_params,
                                   thread_kw, tol_frac):
    diffs, n_constraints = _paired_diffs(
        algo, dev_cycles, dev_params, thread_kw)
    mean, upper = _ci_upper(diffs)
    tol = tol_frac * n_constraints
    assert upper <= tol, (
        f"{algo}: lockstep quality gap beyond the documented bound: "
        f"paired diffs {diffs}, mean {mean:.2f}, CI upper "
        f"{upper:.2f} > tol {tol:.2f}"
    )


@pytest.mark.slow
def test_staggered_matches_lockstep_deterministically():
    """Device-device pairing of the two adsa schedules: both sides are
    seeded jax kernels, so this comparison has NO thread-side sampling
    noise and is bit-reproducible.  The staggered schedule must be
    statistically flat vs lockstep (recorded negative result, module
    docstring): |paired mean| <= 3% of the constraint count (measured
    +1.45 ≈ 0.9% on this battery's family)."""
    diffs = []
    n_constraints = None
    for seed in SEEDS:
        dcop = _problem(seed)
        n_constraints = len(dcop.constraints)
        r_lock = solve(dcop, "adsa", max_cycles=200, algo_params={
            "seed": seed, "stop_cycle": 60})
        r_stag = solve(dcop, "adsa", max_cycles=200, algo_params={
            "seed": seed, "stop_cycle": 60, "schedule": "staggered"})
        diffs.append(r_stag["cost"] - r_lock["cost"])
    mean = sum(diffs) / len(diffs)
    assert abs(mean) <= 0.03 * n_constraints, (
        f"staggered vs lockstep drifted: diffs {diffs}, mean {mean:.2f}"
    )

"""``pydcop distribute`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/distribute.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("distribute", help="distribute (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop distribute: not implemented yet in pydcop-tpu")
    return 3

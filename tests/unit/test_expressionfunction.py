"""Dedicated ExpressionFunction battery (reference scope:
tests/unit/test_utils_expressionfunction.py — behaviors re-derived from
the module contract, not ported): expression vs function-body forms,
AST name discovery, partial application, external source files, wire
format."""

import os

import pytest

from pydcop_tpu.utils.expressionfunction import ExpressionFunction
from pydcop_tpu.utils.simple_repr import from_repr, simple_repr


class TestNameDiscovery:
    def test_simple_expression_names(self):
        f = ExpressionFunction("a + b * 2")
        assert sorted(f.variable_names) == ["a", "b"]

    def test_builtins_not_variables(self):
        f = ExpressionFunction("abs(x) + len(ys) + round(z)")
        assert sorted(f.variable_names) == ["x", "ys", "z"]

    def test_math_module_not_a_variable(self):
        f = ExpressionFunction("math.sqrt(v) + math.pi")
        assert list(f.variable_names) == ["v"]

    def test_repeated_name_counted_once(self):
        f = ExpressionFunction("x + x * x")
        assert list(f.variable_names) == ["x"]

    def test_comprehension_target_not_a_variable(self):
        f = ExpressionFunction("sum(i * w for i in range(3))")
        assert list(f.variable_names) == ["w"]

    def test_ternary_collects_all_branches(self):
        f = ExpressionFunction("a if c else b")
        assert sorted(f.variable_names) == ["a", "b", "c"]

    def test_name_order_is_appearance_order(self):
        f = ExpressionFunction("beta + alpha")
        assert list(f.variable_names) == ["beta", "alpha"]


class TestEvaluation:
    def test_keyword_call(self):
        assert ExpressionFunction("a - b")(a=10, b=4) == 6

    def test_positional_follow_appearance_order(self):
        f = ExpressionFunction("a - b")
        assert f(10, 4) == 6

    def test_string_values(self):
        f = ExpressionFunction("1 if v1 == v2 else 0")
        assert f(v1="R", v2="R") == 1
        assert f(v1="R", v2="G") == 0

    def test_math_functions_available(self):
        f = ExpressionFunction("math.floor(x)")
        assert f(x=2.7) == 2

    def test_missing_variable_raises(self):
        f = ExpressionFunction("a + b")
        with pytest.raises((NameError, KeyError)):
            f(a=1)


class TestFunctionBodyForm:
    def test_return_body(self):
        f = ExpressionFunction("if a > b:\n    return a\nreturn b")
        assert sorted(f.variable_names) == ["a", "b"]
        assert f(a=3, b=5) == 5
        assert f(a=9, b=5) == 9

    def test_body_with_local_assignment(self):
        f = ExpressionFunction("d = x - y\nreturn d * d")
        # The local d is assigned, so it is NOT a variable.
        assert sorted(f.variable_names) == ["x", "y"]
        assert f(x=5, y=2) == 9


class TestPartial:
    def test_partial_removes_fixed_name(self):
        f = ExpressionFunction("a + b + c")
        g = f.partial(b=10)
        assert sorted(g.variable_names) == ["a", "c"]
        assert g(a=1, c=2) == 13

    def test_partial_chains(self):
        f = ExpressionFunction("a + b + c").partial(a=1).partial(b=2)
        assert list(f.variable_names) == ["c"]
        assert f(c=3) == 6

    def test_partial_does_not_mutate_original(self):
        f = ExpressionFunction("a + b")
        f.partial(a=1)
        assert sorted(f.variable_names) == ["a", "b"]

    def test_call_can_override_nothing_fixed(self):
        g = ExpressionFunction("a * b").partial(b=4)
        assert g(3) == 12  # positional binds the remaining name


class TestIdentity:
    def test_eq_same_expression(self):
        assert ExpressionFunction("a + 1") == ExpressionFunction("a + 1")

    def test_neq_different_fixed_vars(self):
        f = ExpressionFunction("a + b")
        assert f.partial(a=1) != f.partial(a=2)

    def test_hashable_and_consistent(self):
        f1, f2 = ExpressionFunction("x * 2"), ExpressionFunction("x * 2")
        assert hash(f1) == hash(f2)
        assert len({f1, f2}) == 1

    def test_name_is_expression(self):
        assert ExpressionFunction("a+1").__name__ == "a+1"


class TestExternalSource:
    def test_source_file_functions_usable(self, tmp_path):
        src = tmp_path / "ext.py"
        src.write_text("def double(v):\n    return 2 * v\n")
        f = ExpressionFunction("source.double(x) + 1",
                               source_file=str(src))
        assert list(f.variable_names) == ["x"]
        assert f(x=5) == 11

    def test_missing_source_file_raises(self):
        with pytest.raises((FileNotFoundError, OSError)):
            ExpressionFunction("source.f(x)",
                               source_file="/nonexistent/ext.py")


class TestWireFormat:
    def test_simple_repr_roundtrip(self):
        f = ExpressionFunction("a + b").partial(b=3)
        r = simple_repr(f)
        g = from_repr(r)
        assert g == f
        assert g(a=1) == 4

    def test_roundtrip_with_source_file(self, tmp_path):
        src = tmp_path / "ext2.py"
        src.write_text("def inc(v):\n    return v + 1\n")
        f = ExpressionFunction("source.inc(x)", source_file=str(src))
        g = from_repr(simple_repr(f))
        assert g(x=41) == 42
        assert g.source_file == str(src)

"""``pydcop run``: solve a *dynamic* DCOP — scenario events (agent
departures) fire during the run, replicas keep computations alive.

Reference parity: pydcop/commands/run.py (run_cmd :314: solve +
``--scenario`` events + replication ``--ktarget``).  Result JSON shape
matches ``pydcop solve``; replication/repair state is reported under
``replication``.
"""

import logging

from pydcop_tpu.commands._utils import build_algo_def, emit_result

logger = logging.getLogger("pydcop.cli.run")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "run", help="run a dynamic DCOP with scenario events")
    parser.add_argument("dcop_files", nargs="+", help="dcop yaml file(s)")
    parser.add_argument("-a", "--algo", required=True,
                        help="algorithm name")
    parser.add_argument("-p", "--algo_params", action="append",
                        help="algorithm parameter as name:value")
    parser.add_argument("-d", "--distribution", default="oneagent",
                        help="distribution method or file")
    parser.add_argument("-s", "--scenario", required=True,
                        help="scenario yaml file")
    parser.add_argument("-k", "--ktarget", type=int, default=3,
                        help="number of replicas per computation")
    parser.add_argument("-m", "--mode", default="thread",
                        choices=["thread"],
                        help="execution mode (dynamic runs are "
                             "agent-based)")
    parser.add_argument("-c", "--cycles", type=int, default=0,
                        help="max cycles (0: unbounded)")
    parser.add_argument("--collect_on", default="value_change",
                        choices=["value_change", "cycle_change", "period"])
    parser.add_argument("--period", type=float, default=1.0)
    parser.add_argument("--run_metrics", default=None)
    parser.add_argument("--end_metrics", default=None)
    parser.add_argument("--infinity", type=float, default=float("inf"))
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    from pydcop_tpu.algorithms import load_algorithm_module
    from pydcop_tpu.computations_graph import load_graph_module
    from pydcop_tpu.dcop.yamldcop import (
        load_dcop_from_file,
        load_scenario_from_file,
    )
    from pydcop_tpu.infrastructure.run import (
        _build_distribution,
        run_local_thread_dcop,
    )

    from pydcop_tpu.algorithms import AlgorithmDef

    dcop = load_dcop_from_file(args.dcop_files)
    scenario = load_scenario_from_file(args.scenario)
    algo_def = build_algo_def(args.algo, args.algo_params, dcop.objective)
    algo_module = load_algorithm_module(algo_def.algo)
    # -c bounds algorithms exposing a stop_cycle parameter (same
    # mapping as solve, infrastructure/run.py solve_with_agents).
    if args.cycles:
        param_names = {p.name for p in algo_module.algo_params}
        if ("stop_cycle" in param_names
                and not algo_def.params.get("stop_cycle")):
            params = algo_def.params
            params["stop_cycle"] = args.cycles
            algo_def = AlgorithmDef(algo_def.algo, params, algo_def.mode)
    cg = load_graph_module(
        algo_module.GRAPH_TYPE).build_computation_graph(dcop)
    distribution = _build_distribution(
        dcop, cg, algo_module, args.distribution
    )

    collector = None
    if args.run_metrics:
        from pydcop_tpu.commands.metrics_io import add_csvline

        def collector(metrics):
            add_csvline(args.run_metrics, args.collect_on, metrics)

    timeout = args.timeout if args.timeout is not None else 20.0
    orchestrator = run_local_thread_dcop(
        algo_def, cg, distribution, dcop, infinity=args.infinity,
        replication=True, collector=collector,
        collect_moment=args.collect_on, collect_period=args.period,
    )
    stopped = False
    try:
        if not orchestrator.wait_ready(10):
            print("Error: agents did not become ready")
            return 3
        orchestrator.deploy_computations()
        replica_dist = orchestrator.start_replication(args.ktarget)
        orchestrator.run(scenario=scenario, timeout=timeout)
        orchestrator.stop_agents(5)
        stopped = True
        metrics = orchestrator.end_metrics()
        result = {
            "status": metrics["status"],
            "assignment": {
                k: v for k, v in metrics["assignment"].items()
                if k in dcop.variables
            },
            "cost": metrics["cost"],
            "violation": metrics["violation"],
            "time": metrics["time"],
            "msg_count": metrics["msg_count"],
            "msg_size": metrics["msg_size"],
            "cycle": metrics["cycle"],
            "agt_metrics": metrics["agt_metrics"],
            "replication": {
                "ktarget": args.ktarget,
                "replica_distribution": replica_dist.mapping,
                "repaired": sorted(
                    orchestrator.mgt.repaired_computations
                ),
            },
            "backend": "thread",
        }
    finally:
        if not stopped:
            orchestrator.stop_agents(5)
        orchestrator.stop()

    if args.run_metrics or args.end_metrics:
        from pydcop_tpu.commands.metrics_io import add_csvline

        # Run metrics streamed live above; both files always get the
        # final summary row so they exist even on event-less runs.
        for path in (args.run_metrics, args.end_metrics):
            if path:
                add_csvline(path, args.collect_on, result)

    emit_result(result, args.output)
    return 0

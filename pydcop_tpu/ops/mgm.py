"""MGM (Maximum Gain Message) step kernel — monotone local search.

Reference parity: pydcop/algorithms/mgm.py:213-609.  Per cycle (the
reference's value-phase + gain-phase collapsed into one lockstep step):

- each variable computes its best local response and gain
  (= current cost - best cost, :375) given neighbors' previous values;
  its proposed new value is a uniform-random optimal value when gain > 0,
  else its current value (:377-381);
- gains are "exchanged" (here: neighborhood reductions) and only the
  variable with the strictly largest gain in its neighborhood moves;
  equal gains are broken by lexical variable order or per-cycle random
  draws (break_mode, :547-590).

Monotonicity: at most one variable per neighborhood moves, and only for
a non-negative gain, so the global cost never increases.
"""

from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from pydcop_tpu.engine.compile import CompiledFactorGraph
from pydcop_tpu.ops.localsearch import (
    assignment_cost,
    candidate_costs,
    neighborhood_winners,
    random_initial_values,
)


class MgmState(NamedTuple):
    values: jnp.ndarray  # [V+1] int32
    key: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph, seed: int = 0) -> MgmState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return MgmState(
        values=random_initial_values(k0, graph),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def mgm_step(state: MgmState, graph: CompiledFactorGraph, *,
             lexic_ranks: jnp.ndarray, break_mode: str) -> MgmState:
    """One lockstep MGM cycle (value + gain phases)."""
    key, k_choice, k_rand = jax.random.split(state.key, 3)
    values = state.values

    if break_mode == "random":
        # Fresh draw every cycle (reference :547-553 random_nb).
        ranks = jax.random.uniform(k_rand, values.shape)
    else:
        ranks = lexic_ranks

    cand = candidate_costs(graph, values)                 # [V+1, D]
    gain, proposed, _, wins = neighborhood_winners(
        graph, cand, values, k_choice, ranks
    )
    new_vals = jnp.where(gain > 0, proposed, values)
    values = jnp.where(wins, new_vals, values)
    return MgmState(values=values, key=key, cycle=state.cycle + 1)


def run_mgm(graph: CompiledFactorGraph, max_cycles: int, *,
            lexic_ranks: jnp.ndarray, break_mode: str = "lexic",
            seed: int = 0,
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full MGM run in one XLA program.

    Returns (values [V], final cost, cycles)."""
    state = init_state(graph, seed)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: mgm_step(
            s, graph, lexic_ranks=lexic_ranks, break_mode=break_mode
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle

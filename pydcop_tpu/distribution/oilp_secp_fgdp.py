"""oilp_secp_fgdp: optimal ILP, SECP flavor, factor graph.

Reference parity: pydcop/distribution/oilp_secp_fgdp.py (:72).
"""

from pydcop_tpu.distribution.ilp_compref import (  # noqa: F401
    distribute,
    distribution_cost,
)

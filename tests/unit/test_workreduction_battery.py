"""Work-reduction battery (ISSUE 10): branch-and-bound message
pruning, segmented decimation, and the whole-algorithm portfolio racer.

The contracts pinned here:

- **Pruning never changes values.**  On integer cost tables the pruned
  trajectory is BIT-IDENTICAL to the dense one — every state leaf,
  not just the assignment — across all aggregation strategies and
  under ``shards=N`` (the per-shard local reductions prune with a
  globally-agreed phase predicate).
- **Traced solves stop at the fixpoint** like untraced ones (the
  pre-PR-10 trace paid full ``max_cycles`` after convergence), with
  the cost curve's tail holding the final value.
- **Decimation is anytime-sane** on graph coloring: the final cost is
  within tolerance of the best intermediate and of the colorable
  optimum, every variable ends clamped, and ``active_edges`` reports
  the shrunk work set.
- **Checkpoint/resume mid-decimation equals uninterrupted** — the
  clamp set travels with the snapshot (DecimationState).
- **The portfolio racer caches by structure**: hit/replay with no
  re-race (also through ``api.solve(algo="auto")`` — the acceptance
  assertion), invalid cache entries re-measure, different shapes
  never share a decision.
"""

import json
import os
import tempfile
from functools import partial

import jax
import numpy as np
import pytest

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation
from pydcop_tpu.engine.compile import compile_dcop
from pydcop_tpu.ops import maxsum as maxsum_ops


def loopy_dcop(n=40, d=16, seed=0, density=1.8, spread=40):
    """Loopy coloring with INTEGER tables and a domain large enough
    to engage pruning (compile.PRUNE_MIN_DOMAIN): equality penalty
    per edge, integer unary costs via a unary matrix relation — the
    bit-identity instance family."""
    rng = np.random.default_rng(seed)
    dom = Domain("d", "", list(range(d)))
    dcop = DCOP(f"wr{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    eye = np.eye(d)
    seen, k = set(), 0
    while k < int(n * density):
        i, j = rng.choice(n, 2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], eye, f"c{k}"))
        k += 1
    # Integer unary costs as unary matrix relations (keeps the
    # instance's tables integral end to end).
    for i, v in enumerate(vs):
        u = rng.integers(0, spread, size=(d,)).astype(float)
        dcop.add_constraint(NAryMatrixRelation([v], u, f"u{i}"))
    dcop.add_agents([AgentDef("a0")])
    return dcop


def coloring_dcop(n=36, seed=1, density=1.6):
    """3-colorable-ish loopy coloring (no unaries) — the decimation
    quality instance."""
    rng = np.random.default_rng(seed)
    dom = Domain("c", "", [0, 1, 2])
    dcop = DCOP(f"col{n}_{seed}", objective="min")
    vs = [Variable(f"v{i}", dom) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    eq = np.eye(3)
    seen, k = set(), 0
    while k < int(n * density):
        i, j = rng.choice(n, 2, replace=False)
        key = (min(i, j), max(i, j))
        if key in seen:
            continue
        seen.add(key)
        dcop.add_constraint(
            NAryMatrixRelation([vs[i], vs[j]], eq, f"c{k}"))
        k += 1
    dcop.add_agents([AgentDef("a0")])
    return dcop


def _leaves_equal(a, b) -> bool:
    return all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(a),
                        jax.tree_util.tree_leaves(b))
    )


class TestPruningBitIdentity:
    @pytest.mark.parametrize("aggregation",
                             ["scatter", "sorted", "ell", "boundary"])
    def test_identical_across_aggregations(self, aggregation):
        dcop = loopy_dcop()
        graph, _meta = compile_dcop(
            dcop, noise_level=0.0, aggregation=aggregation,
            use_cache=False)
        g = jax.device_put(graph)
        runs = {}
        for prune in (False, True):
            fn = jax.jit(partial(
                maxsum_ops.run_maxsum, max_cycles=120,
                stop_on_convergence=False, prune=prune))
            runs[prune] = jax.block_until_ready(fn(g))
        assert _leaves_equal(runs[False], runs[True]), (
            f"pruned trajectory diverged from dense under "
            f"aggregation={aggregation}")

    def test_identical_with_engine_and_noise(self):
        """The real engine path (tie-break noise on): engine-level
        prune=True produces the identical solve."""
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = loopy_dcop(seed=3)
        results = {}
        for prune in (False, True):
            res = build_engine(
                dcop, {"prune": prune}).run(
                    max_cycles=150, stop_on_convergence=False)
            results[prune] = res
        assert results[False].assignment == results[True].assignment
        assert results[False].cycles == results[True].cycles
        assert results[False].converged == results[True].converged

    def test_identical_under_shards(self):
        """Partitioned engine: per-shard pruned reductions with the
        global phase predicate stay bit-identical to dense."""
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = loopy_dcop(n=48, seed=5)
        engines = {
            prune: build_engine(dcop, {"prune": prune,
                                       "noise": 0.0}, shards=4)
            for prune in (False, True)
        }
        states = {}
        for prune, eng in engines.items():
            st, values = eng._ops.run_maxsum(
                eng.graph, 100, stop_on_convergence=False,
                prune=eng.prune)
            states[prune] = (st, values)
        assert np.array_equal(np.asarray(states[False][1]),
                              np.asarray(states[True][1]))
        assert _leaves_equal(states[False][0], states[True][0])

    def test_segmented_equals_plain_with_prune(self):
        """The segmented runner's pruned segments reproduce the
        one-program pruned solve (the checkpointing contract holds
        with pruning on)."""
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = loopy_dcop(seed=7)
        plain = build_engine(dcop, {"prune": True}).run(
            max_cycles=140)
        seg = build_engine(dcop, {"prune": True}).run_checkpointed(
            max_cycles=140, segment_cycles=20)
        assert plain.assignment == seg.assignment
        assert plain.cycles == seg.cycles


class TestTraceEarlyExit:
    def test_traced_and_untraced_cycles_agree(self):
        """The PR-10 satellite: run_maxsum_trace used to ignore
        stop_on_convergence, paying full max_cycles after the
        fixpoint."""
        dcop = loopy_dcop(seed=2)
        graph, _meta = compile_dcop(dcop, noise_level=0.0,
                                    use_cache=False)
        g = jax.device_put(graph)
        st_run, v_run = jax.block_until_ready(jax.jit(partial(
            maxsum_ops.run_maxsum, max_cycles=400))(g))
        st_tr, v_tr, costs = jax.block_until_ready(jax.jit(partial(
            maxsum_ops.run_maxsum_trace, max_cycles=400))(g))
        assert int(st_tr.cycle) == int(st_run.cycle)
        assert int(st_run.cycle) < 400, \
            "instance never converged; the agreement check is vacuous"
        assert np.array_equal(np.asarray(v_tr), np.asarray(v_run))
        # The tail of the curve holds the final cost.
        costs = np.asarray(costs)
        conv = int(st_tr.cycle)
        assert np.all(costs[conv:] == costs[conv - 1])

    def test_engine_run_trace_agrees_with_run(self):
        from pydcop_tpu.algorithms.maxsum import build_engine

        dcop = loopy_dcop(seed=4)
        eng_a = build_engine(dcop, {})
        eng_b = build_engine(dcop, {})
        run = eng_a.run(max_cycles=400)
        trace = eng_b.run_trace(max_cycles=400)
        assert trace.cycles == run.cycles
        assert trace.assignment == run.assignment
        assert len(trace.metrics["cost_trace"]) == 400


class TestDecimation:
    def test_anytime_and_final_cost_on_coloring(self):
        """Decimated coloring: final cost within tolerance of the
        best intermediate (anytime sanity) and of the colorable
        optimum; every variable clamped; active_edges shrinks to 0."""
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.engine.runner import DecimationPlan

        dcop = coloring_dcop()

        segment_costs = []

        class CostProbe:
            def on_segment(self, state, values, run_s, compile_s):
                vals = np.asarray(jax.device_get(values))
                asg = {f"v{i}": int(vals[i])
                       for i in range(len(vals))}
                segment_costs.append(dcop.solution_cost(asg)[0])

        eng = build_engine(dcop, {})
        res = eng.run_checkpointed(
            max_cycles=1500, segment_cycles=25,
            decimation=DecimationPlan(frac_per_round=0.2,
                                      cycles_per_round=25),
            probe=CostProbe(),
        )
        final_cost, violations = dcop.solution_cost(res.assignment)
        assert res.converged
        assert res.metrics["decimated_vars"] == len(dcop.variables)
        assert res.metrics["decimated_fraction"] == 1.0
        assert res.metrics["active_edges"] == 0
        assert res.metrics["decimation_rounds"] >= 2
        # Anytime sanity: the run never ends worse than its best
        # validated intermediate (one conflict of slack for the last
        # clamp round).
        assert final_cost <= min(segment_costs) + 1
        # Quality: a sparse loopy coloring is (near-)colorable.
        assert final_cost <= 2

    def test_resume_mid_decimation_equals_uninterrupted(self):
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.engine.runner import DecimationPlan
        from pydcop_tpu.resilience.checkpoint import (
            CheckpointManager,
            resume_from_checkpoint,
        )

        dcop = coloring_dcop(seed=6)
        plan = DecimationPlan(frac_per_round=0.25,
                              cycles_per_round=20)
        full = build_engine(dcop, {}).run_checkpointed(
            max_cycles=1500, segment_cycles=20, decimation=plan)
        with tempfile.TemporaryDirectory() as td:
            manager = CheckpointManager(td, every=20, keep=50)
            part = build_engine(dcop, {}).run_checkpointed(
                max_cycles=1500, segment_cycles=20, decimation=plan,
                manager=manager, max_segments=3)
            assert part.metrics["interrupted"]
            assert 0 < part.metrics["decimated_vars"] \
                < len(dcop.variables)
            resumed = resume_from_checkpoint(
                build_engine(dcop, {}), manager, max_cycles=1500,
                segment_cycles=20, decimation=plan)
            assert resumed.metrics["resumed_from_cycle"] > 0
        assert resumed.assignment == full.assignment
        assert resumed.cycles == full.cycles
        assert resumed.metrics["decimated_vars"] \
            == full.metrics["decimated_vars"]

    def test_guard_trip_on_first_segment_rolls_back_cleanly(self):
        """A trip on the VERY FIRST segment must roll the clamp set
        back to the (empty) initial snapshot, not crash unpacking a
        never-retained one (regression: the initial recovery retain
        used to skip the decimation bookkeeping)."""
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.engine.runner import DecimationPlan
        from pydcop_tpu.resilience.recovery import RecoveryPolicy

        dcop = coloring_dcop(seed=11)
        res = build_engine(dcop, {}).run_checkpointed(
            max_cycles=900, segment_cycles=15,
            decimation=DecimationPlan(frac_per_round=0.25,
                                      cycles_per_round=15),
            recovery=RecoveryPolicy(trip_cycles=(1,)))
        assert res.metrics["guard_trips"] == 1
        assert res.metrics["decimation_rollbacks"] == 1
        assert res.metrics["decimated_vars"] == len(dcop.variables)

    def test_decimation_rejected_on_sharded_and_lane(self):
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.engine.runner import DecimationPlan

        dcop = coloring_dcop(seed=8)
        eng = build_engine(dcop, {}, shards=2)
        with pytest.raises(ValueError, match="decimation"):
            eng.run_checkpointed(
                max_cycles=100, decimation=DecimationPlan())

    def test_resume_without_plan_refused(self):
        """A DecimationState snapshot must not silently resume as a
        plain run (the clamp set would be dropped)."""
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.engine.runner import (
            DecimationState,
            MaxSumEngine,
        )

        dcop = coloring_dcop(seed=9)
        eng = build_engine(dcop, {})
        assert isinstance(eng, MaxSumEngine)
        fake = DecimationState(
            solver=eng.init_state(),
            fixed=np.zeros(len(dcop.variables), bool),
            var_costs=np.asarray(
                jax.device_get(eng.graph.var_costs)),
        )
        with pytest.raises(ValueError, match="clamp set"):
            eng.run_checkpointed(max_cycles=50, initial_state=fake)


class TestPortfolio:
    def _graph(self, n=30, seed=0):
        dcop = coloring_dcop(n=n, seed=seed)
        graph, _ = compile_dcop(dcop, noise_level=0.01,
                                use_cache=False)
        return dcop, graph

    def test_measure_then_replay(self):
        from pydcop_tpu.engine.autotune import (
            PORTFOLIO_CANDIDATES,
            autotune_portfolio,
            dpop_portfolio_runner,
        )

        dcop = coloring_dcop(n=30, seed=0)
        graph, meta = compile_dcop(dcop, noise_level=0.01,
                                   use_cache=False)
        with tempfile.TemporaryDirectory() as td:
            cache = os.path.join(td, "tune.json")
            dpop_runner = dpop_portfolio_runner(dcop, graph, meta)
            info = autotune_portfolio(
                graph, race_cycles=30, cache_file=cache,
                extra_runners={"dpop": dpop_runner})
            assert info["algo"] in PORTFOLIO_CANDIDATES
            assert info["portfolio_source"] == "measured"
            timed = [n for n, t in
                     info["portfolio_timings_ms"].items()
                     if t is not None]
            # "dpop" only races when the structure is width-feasible
            # (runner is None past the gate) — every unconditional
            # candidate must have been timed either way.
            expected = set(PORTFOLIO_CANDIDATES) - (
                set() if dpop_runner is not None else {"dpop"})
            assert set(timed) == expected
            assert info["portfolio_target_cost"] is not None
            replay = autotune_portfolio(
                graph, race_cycles=30, cache_file=cache)
            assert replay["portfolio_source"] == "cache"
            assert replay["algo"] == info["algo"]

    def test_invalid_cache_entry_remeasures(self):
        from pydcop_tpu.engine.autotune import (
            autotune_portfolio,
            graph_shape_key,
            portfolio_key,
        )

        _dcop, graph = self._graph(seed=1)
        with tempfile.TemporaryDirectory() as td:
            cache = os.path.join(td, "tune.json")
            key = portfolio_key(graph_shape_key(graph))
            with open(cache, "w") as f:
                json.dump({key: {"algo": "not-a-kernel"}}, f)
            info = autotune_portfolio(
                graph, race_cycles=30, cache_file=cache)
            assert info["portfolio_source"] == "measured"

    def test_different_shape_different_key(self):
        from pydcop_tpu.engine.autotune import graph_shape_key

        _d1, g1 = self._graph(n=30, seed=0)
        _d2, g2 = self._graph(n=32, seed=0)
        assert graph_shape_key(g1) != graph_shape_key(g2)

    def test_api_auto_replays_on_second_solve(self, monkeypatch):
        """The ISSUE 10 acceptance: api.solve(algo='auto') picks a
        cached portfolio decision on the second same-structure solve
        — no re-race."""
        from pydcop_tpu.api import solve

        def ring_instance(table_seed):
            """Fixed topology (same structure signature), seeded
            random tables (a different problem instance)."""
            rng = np.random.default_rng(table_seed)
            dom = Domain("c", "", [0, 1, 2])
            dcop = DCOP(f"ring{table_seed}", objective="min")
            vs = [Variable(f"v{i}", dom) for i in range(24)]
            for v in vs:
                dcop.add_variable(v)
            edges = [(i, (i + 1) % 24) for i in range(24)]
            edges += [(i, (i + 12) % 24) for i in range(0, 24, 3)]
            for k, (i, j) in enumerate(edges):
                dcop.add_constraint(NAryMatrixRelation(
                    [vs[i], vs[j]],
                    rng.integers(0, 10, (3, 3)).astype(float),
                    f"c{k}"))
            dcop.add_agents([AgentDef("a0")])
            return dcop

        with tempfile.TemporaryDirectory() as td:
            monkeypatch.setenv(
                "PYDCOP_AGG_AUTOTUNE_CACHE",
                os.path.join(td, "tune.json"))
            first = solve(ring_instance(2), "auto", max_cycles=120)
            second = solve(ring_instance(3), "auto", max_cycles=120)
        assert first["metrics"]["portfolio"][
            "portfolio_source"] == "measured"
        assert second["metrics"]["portfolio"][
            "portfolio_source"] == "cache"
        assert second["metrics"]["portfolio"]["algo"] \
            == first["metrics"]["portfolio"]["algo"]
        assert first["status"] == "FINISHED" or first["cost"] >= 0

    def test_auto_rejected_off_device(self):
        from pydcop_tpu.api import solve

        with pytest.raises(ValueError, match="auto"):
            solve(coloring_dcop(n=12, seed=4), "auto",
                  backend="thread")


class TestServingConsumption:
    def test_prune_auto_resolves_from_portfolio_cache(self,
                                                      monkeypatch):
        """The serving dispatch path consumes the racer's cached
        decision: prune='auto' resolves to the pruned program when
        maxsum_prune won, and the batched answer still equals the
        solo solve (pruning never changes values)."""
        from pydcop_tpu.api import solve
        from pydcop_tpu.engine.autotune import _store_cache
        from pydcop_tpu.engine.autotune import (
            graph_shape_key,
            portfolio_key,
        )
        from pydcop_tpu.serving.service import SolveService

        dcop = coloring_dcop(n=24, seed=5)
        with tempfile.TemporaryDirectory() as td:
            cache = os.path.join(td, "tune.json")
            monkeypatch.setenv("PYDCOP_AGG_AUTOTUNE_CACHE", cache)
            graph, _ = compile_dcop(dcop, noise_level=0.01)
            _store_cache(cache, {
                portfolio_key(graph_shape_key(graph)): {
                    "algo": "maxsum_prune"}})
            service = SolveService(batch_window_s=0.005,
                                   max_batch=8).start()
            try:
                rid = service.submit(
                    dcop, params={"max_cycles": 60,
                                  "prune": "auto"})
                res = service.result(rid, wait=60)
                assert res["status"] == "FINISHED"
                assert service.stats()["portfolio_resolved"] == 1
            finally:
                service.stop(drain=False)
            solo = solve(dcop, "maxsum", max_cycles=60)
            assert res["assignment"] == solo["assignment"]

    def test_prune_param_rides_the_bin_key(self):
        from pydcop_tpu.serving import binning

        dcop = coloring_dcop(n=18, seed=6)
        graph, _ = compile_dcop(dcop, noise_level=0.01)
        k0 = binning.bin_key(
            graph, binning.normalize_params({"prune": 0}))
        k1 = binning.bin_key(
            graph, binning.normalize_params({"prune": 1}))
        assert k0 != k1

    def test_bad_prune_param_rejected(self):
        from pydcop_tpu.serving import binning

        with pytest.raises(ValueError, match="prune"):
            binning.normalize_params({"prune": "sometimes"})

"""``pydcop batch`` — placeholder, implemented later this round.

Reference parity target: pydcop/commands/batch.py.
"""


def set_parser(subparsers):
    parser = subparsers.add_parser("batch", help="batch (not yet implemented)")
    parser.set_defaults(func=run_cmd)


def run_cmd(args) -> int:
    print("pydcop batch: not implemented yet in pydcop-tpu")
    return 3

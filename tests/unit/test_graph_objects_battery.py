"""Battery over computations_graph base objects and the four graph
builders' structural invariants (reference test_graph_* depth)."""

import numpy as np
import pytest

from pydcop_tpu.computations_graph import (
    constraints_hypergraph as chg,
    factor_graph as fg,
    ordered_graph as og,
    pseudotree as pt,
)
from pydcop_tpu.computations_graph.objects import (
    ComputationGraph,
    ComputationNode,
    Link,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Domain, Variable
from pydcop_tpu.dcop.relations import NAryMatrixRelation

d2 = Domain("d", "", [0, 1])


def chain_dcop(n=4):
    dcop = DCOP("t")
    vs = [Variable(f"v{i}", d2) for i in range(n)]
    for v in vs:
        dcop.add_variable(v)
    for i in range(n - 1):
        dcop.add_constraint(NAryMatrixRelation(
            [vs[i], vs[i + 1]], np.zeros((2, 2)), f"c{i}"))
    return dcop


class TestBaseObjects:
    def test_link_nodes_and_membership(self):
        link = Link(["a", "b"])
        assert set(link.nodes) == {"a", "b"}
        assert link.has_node("a") and not link.has_node("c")

    def test_link_equality_ignores_order(self):
        assert Link(["a", "b"]) == Link(["b", "a"])
        assert Link(["a", "b"]) != Link(["a", "c"])
        assert Link(["a", "b"], "other") != Link(["a", "b"])

    def test_node_neighbors_from_links(self):
        n = ComputationNode("x", "t", links=[
            Link(["x", "y"]), Link(["x", "z"])])
        assert set(n.neighbors) == {"y", "z"}
        assert "x" not in n.neighbors

    def test_graph_dedups_links(self):
        shared = Link(["a", "b"])
        na = ComputationNode("a", "t", links=[shared])
        nb = ComputationNode("b", "t", links=[Link(["a", "b"])])
        g = ComputationGraph("t", [na, nb])
        assert len(g.links) == 1

    def test_graph_lookup(self):
        na = ComputationNode("a", "t")
        g = ComputationGraph("t", [na])
        assert g.computation("a") is na
        assert g.has_computation("a")
        assert not g.has_computation("zz")
        assert len(g) == 1

    def test_density_bounds(self):
        assert ComputationGraph("t").density() == 0.0
        na = ComputationNode("a", "t", links=[Link(["a", "b"])])
        nb = ComputationNode("b", "t", links=[Link(["a", "b"])])
        assert ComputationGraph("t", [na, nb]).density() == 1.0


class TestFactorGraph:
    def test_bipartite_structure(self):
        g = fg.build_computation_graph(chain_dcop(3))
        var_nodes = [n for n in g.nodes
                     if isinstance(n, fg.VariableComputationNode)]
        factor_nodes = [n for n in g.nodes
                        if isinstance(n, fg.FactorComputationNode)]
        assert len(var_nodes) == 3 and len(factor_nodes) == 2
        # every link connects one var node to one factor node
        names_v = {n.name for n in var_nodes}
        for link in g.links:
            a, b = link.nodes
            assert (a in names_v) != (b in names_v)

    def test_variable_node_knows_its_factors(self):
        g = fg.build_computation_graph(chain_dcop(3))
        mid = g.computation("v1")
        assert set(mid.factors) == {"c0", "c1"}

    def test_factor_node_scope(self):
        g = fg.build_computation_graph(chain_dcop(3))
        f = g.computation("c0")
        assert [v.name for v in f.variables] == ["v0", "v1"]


class TestHypergraph:
    def test_one_node_per_variable(self):
        g = chg.build_computation_graph(chain_dcop(4))
        assert sorted(n.name for n in g.nodes) == [
            "v0", "v1", "v2", "v3"]

    def test_neighbors_via_shared_constraints(self):
        g = chg.build_computation_graph(chain_dcop(4))
        assert set(g.computation("v1").neighbors) == {"v0", "v2"}

    def test_footprint_positive_and_monotone_in_degree(self):
        g = chg.build_computation_graph(chain_dcop(4))
        end = chg.computation_memory(g.computation("v0"))
        mid = chg.computation_memory(g.computation("v1"))
        assert 0 < end <= mid


class TestOrderedGraph:
    def test_total_lexical_order(self):
        g = og.build_computation_graph(chain_dcop(4))
        names = [n.name for n in g.nodes]
        assert names == sorted(names)
        # every node except the last has a next; except first a prev
        for i, node in enumerate(g.nodes):
            nexts = [li for li in node.links
                     if getattr(li, "type", "") == "next"
                     and li.source == node.name]
            assert bool(nexts) == (i < len(g.nodes) - 1)


class TestPseudotree:
    def _tree(self, n=6):
        return pt.build_computation_graph(chain_dcop(n))

    def test_single_root(self):
        g = self._tree()
        roots = [n for n in g.nodes if n.parent is None]
        assert len(roots) == 1

    def test_parent_child_symmetry(self):
        g = self._tree()
        for node in g.nodes:
            for child in node.children:
                assert g.computation(child).parent == node.name
            if node.parent:
                assert node.name in g.computation(node.parent).children

    def test_every_constraint_connects_node_to_ancestor(self):
        g = self._tree()

        def ancestors(name):
            out = []
            cur = g.computation(name)
            while cur.parent:
                out.append(cur.parent)
                cur = g.computation(cur.parent)
            return set(out)

        for node in g.nodes:
            for c in node.constraints:
                others = set(c.scope_names) - {node.name}
                # each constraint is attached at its LOWEST node: all
                # other scope members are ancestors of it
                assert others <= ancestors(node.name), (
                    node.name, c.name)

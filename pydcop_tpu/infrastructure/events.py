"""Process-local event bus.

Reference parity: pydcop/infrastructure/Events.py (EventDispatcher :41,
singleton event_bus :98, get_bus :103).  Topics are dot-separated
strings; subscriptions ending in ``*`` match any suffix
(``computations.value.*`` matches ``computations.value.v1``).

Emission is cheap when nobody listens (the common case: metrics off):
one boolean check, no string matching.
"""

import logging
import threading
from typing import Callable, Dict, List

logger = logging.getLogger("pydcop.events")


class EventDispatcher:
    """Topic-based pub/sub with ``*``-suffix wildcards."""

    def __init__(self):
        self._exact: Dict[str, List[Callable]] = {}
        self._prefix: Dict[str, List[Callable]] = {}
        self._lock = threading.Lock()
        self.enabled = False

    def subscribe(self, topic: str, cb: Callable) -> Callable:
        with self._lock:
            if topic.endswith("*"):
                self._prefix.setdefault(topic[:-1], []).append(cb)
            else:
                self._exact.setdefault(topic, []).append(cb)
            self.enabled = True
        return cb

    def unsubscribe(self, cb: Callable):
        with self._lock:
            for subs in (self._exact, self._prefix):
                for topic in list(subs):
                    if cb in subs[topic]:
                        subs[topic].remove(cb)
                    if not subs[topic]:
                        del subs[topic]
            self.enabled = bool(self._exact or self._prefix)

    def emit(self, topic: str, data=None):
        if not self.enabled:
            return
        with self._lock:
            cbs = list(self._exact.get(topic, []))
            for prefix, subs in self._prefix.items():
                if topic.startswith(prefix):
                    cbs.extend(subs)
        for cb in cbs:
            try:
                cb(topic, data)
            except Exception:
                logger.exception("Event callback error for %s", topic)

    def reset(self):
        with self._lock:
            self._exact.clear()
            self._prefix.clear()
            self.enabled = False


event_bus = EventDispatcher()


def get_bus() -> EventDispatcher:
    return event_bus

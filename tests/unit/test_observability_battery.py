"""Observability subsystem battery: tracer, metrics registry, stats
shim, engine probe, and the end-to-end chaos-trace contract.

Acceptance targets (ISSUE 2): a chaos run's trace contains agent
step, message send/recv, injected fault drop, breaker trip and
checkpoint write spans and summarizes cleanly; metrics snapshots carry
a monotone cycle counter and a MaxSum cost-vs-cycle curve whose final
point equals the reported cost; Prometheus output is well-formed; and
disabled tracing adds no events and no per-call allocations.
"""

import json
import os
import re
import threading

import pytest

from pydcop_tpu.observability.metrics import (
    CycleSnapshotter,
    MetricsRegistry,
)
from pydcop_tpu.observability.trace import (
    NOOP_SPAN,
    Tracer,
    check_well_nested,
    load_trace_file,
    summarize_spans,
    tracer,
)

from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import AgentDef, Domain, Variable
from pydcop_tpu.dcop.relations import constraint_from_str
from pydcop_tpu.distribution.objects import Distribution


# ------------------------------------------------------------------ #
# fixtures


def _coloring_dcop(n_vars=4, n_agents=5):
    d = Domain("colors", "", ["R", "G", "B"])
    dcop = DCOP("obs", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    for i in range(n_vars - 1):
        dcop.add_constraint(constraint_from_str(
            f"diff_{i}_{i + 1}",
            f"10 if v{i} == v{i + 1} else 0",
            [variables[i], variables[i + 1]],
        ))
    dcop.add_agents([
        AgentDef(f"a{i}", capacity=100, default_hosting_cost=i)
        for i in range(n_agents)
    ])
    return dcop


def _ring_dcop(n_vars=6):
    d = Domain("c", "", list(range(3)))
    dcop = DCOP("ring", objective="min")
    variables = [Variable(f"v{i}", d) for i in range(n_vars)]
    for v in variables:
        dcop.add_variable(v)
    edges = [(i, (i + 1) % n_vars) for i in range(n_vars)] + [(0, 3)]
    for i, j in edges:
        dcop.add_constraint(constraint_from_str(
            f"c_{i}_{j}", f"5 if v{i} == v{j} else 0",
            [variables[i], variables[j]],
        ))
    dcop.add_agents([AgentDef("a0")])
    return dcop


@pytest.fixture(autouse=True)
def _tracer_off():
    """Every test starts and ends with the process tracer disabled."""
    tracer.disable()
    tracer.clear()
    yield
    tracer.disable()
    tracer.clear()


# ------------------------------------------------------------------ #
# tracer


class TestTracer:
    def test_span_nesting_and_parent_ids(self):
        t = Tracer()
        t.enable()
        with t.span("outer", "test", a=1):
            with t.span("inner", "test"):
                t.instant("point", "test", b=2)
        events = t.events()
        # Sorted by ts; spans are start-stamped (recorded on exit).
        assert [e["name"] for e in events] == [
            "outer", "inner", "point"]
        outer = next(e for e in events if e["name"] == "outer")
        inner = next(e for e in events if e["name"] == "inner")
        point = next(e for e in events if e["name"] == "point")
        assert outer["parent"] == 0
        assert inner["parent"] == outer["id"]
        assert point["parent"] == inner["id"]
        assert inner["ts"] >= outer["ts"]
        assert inner["dur"] <= outer["dur"]

    def test_enable_clears_previous_session(self):
        t = Tracer()
        t.enable()
        t.instant("old", "test")
        t.enable()
        t.instant("new", "test")
        assert [e["name"] for e in t.events()] == ["new"]

    def test_export_chrome_loads_and_nests(self, tmp_path):
        t = Tracer()
        t.enable()
        with t.span("a", "test"):
            with t.span("b", "test"):
                pass
            with t.span("c", "test"):
                pass
        path = str(tmp_path / "trace.json")
        t.export_chrome(path)
        data = json.load(open(path, encoding="utf-8"))
        names = {e["name"] for e in data["traceEvents"]}
        assert {"a", "b", "c", "thread_name"} <= names
        events = load_trace_file(path)
        check_well_nested(events)
        # Every exported event carries pid/tid and spans carry dur.
        for ev in events:
            assert "pid" in ev and "tid" in ev
            if ev["ph"] == "X":
                assert ev["dur"] >= 0

    def test_export_jsonl(self, tmp_path):
        t = Tracer()
        t.enable()
        t.instant("x", "test", k="v")
        path = str(tmp_path / "trace.jsonl")
        t.export_jsonl(path)
        rows = [json.loads(line) for line in open(path)]
        # Line 1 is the process-identity/clock-anchor header (ISSUE 5
        # multi-process merge); events follow.
        assert "pydcop_trace_header" in rows[0]
        assert rows[1]["name"] == "x"
        assert rows[1]["args"] == {"k": "v"}
        assert "thread" in rows[1]
        # load_trace_file returns events only (header excluded).
        assert load_trace_file(path)[0]["name"] == "x"

    def test_multithreaded_buffers(self):
        t = Tracer()
        t.enable()

        def work(i):
            for _ in range(50):
                t.instant(f"ev{i}", "test")

        threads = [threading.Thread(target=work, args=(i,))
                   for i in range(4)]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        events = t.events()
        assert len(events) == 200
        assert len({e["tid"] for e in events}) == 4

    def test_check_well_nested_rejects_overlap(self):
        events = [
            {"ph": "X", "name": "a", "ts": 0.0, "dur": 100.0, "tid": 1},
            {"ph": "X", "name": "b", "ts": 50.0, "dur": 100.0, "tid": 1},
        ]
        with pytest.raises(ValueError, match="overlaps"):
            check_well_nested(events)

    def test_summarize_spans(self):
        events = [
            {"ph": "X", "name": "a", "cat": "t", "ts": 0, "dur": 2000.0},
            {"ph": "X", "name": "a", "cat": "t", "ts": 0, "dur": 4000.0},
            {"ph": "i", "name": "b", "cat": "t", "ts": 0},
        ]
        rows = summarize_spans(events, top=5)
        assert rows[0]["name"] == "a"
        assert rows[0]["count"] == 2
        assert rows[0]["total_ms"] == pytest.approx(6.0)
        assert rows[0]["max_ms"] == pytest.approx(4.0)
        assert rows[1] == {"name": "b", "count": 1, "total_ms": 0.0,
                           "mean_ms": 0.0, "max_ms": 0.0}


class TestZeroOverheadWhenOff:
    """Disabled tracing must be one flag check: no events, no per-call
    span allocation (the shared NOOP singleton), instrumented hot
    sites short-circuit.

    Since PR 9 the always-on flight recorder keeps ``tracer.active``
    true (events flow to its ring even while file tracing is off), so
    the zero-overhead contract applies to the FULLY-off state: ring
    detached AND session disabled.  The fixture detaches the default
    ring for the duration; TestFlightRecorder covers the ring-attached
    behavior."""

    @pytest.fixture(autouse=True)
    def _detach_flight(self):
        saved = tracer.flight
        tracer.set_flight(None)
        yield
        tracer.set_flight(saved)

    def test_span_returns_shared_noop_singleton(self):
        assert not tracer.enabled
        s1 = tracer.span("x", "t")
        s2 = tracer.span("y", "t", arg=1)
        assert s1 is NOOP_SPAN and s2 is NOOP_SPAN

    def test_no_events_recorded_while_off(self):
        tracer.instant("x", "t", a=1)
        with tracer.span("y", "t"):
            pass
        assert tracer.events() == []

    def test_instrumented_runtime_sites_emit_nothing(self):
        from pydcop_tpu.infrastructure.communication import (
            InProcessCommunicationLayer,
            Messaging,
        )
        from pydcop_tpu.infrastructure.computations import Message

        messaging = Messaging("zoh", InProcessCommunicationLayer())
        messaging.register_computation("c")
        for _ in range(10):
            messaging.post_msg("x", "c", Message("algo", 1))
        assert tracer.events() == []

    def test_noop_span_reused_across_many_calls(self):
        # The identity check IS the zero-allocation assertion: every
        # disabled call returns the same singleton, so no span object
        # is ever allocated while off.
        spans = {id(tracer.span(f"s{i}", "t")) for i in range(100)}
        assert spans == {id(NOOP_SPAN)}


# ------------------------------------------------------------------ #
# metrics registry


_PROM_SAMPLE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\")*\})?"
    r" (-?[0-9.e+-]+|\+Inf)"
    # Optional OpenMetrics exemplar: ` # {trace_id="..."} value ts`
    # (bucket samples carry one once anything observed with an
    # exemplar — e.g. the serve plane's latency histogram).
    r"( # \{[a-zA-Z_][a-zA-Z0-9_]*=\"[^\"]*\"\}"
    r" -?[0-9.e+-]+( [0-9.]+)?)?$"
)


class TestMetricsRegistry:
    def test_counter_monotone(self):
        reg = MetricsRegistry()
        c = reg.counter("c_total", "help")
        c.inc()
        c.inc(2.5, kind="x")
        assert c.value() == 1
        assert c.value(kind="x") == 2.5
        with pytest.raises(ValueError, match="cannot decrease"):
            c.inc(-1)

    def test_gauge_and_bound_handles(self):
        reg = MetricsRegistry()
        g = reg.gauge("g", "")
        bound = g.bind(agent="a1")
        bound.set(3.0)
        bound.inc(1.0)
        assert g.value(agent="a1") == 4.0
        assert bound.value() == 4.0

    def test_histogram_buckets(self):
        reg = MetricsRegistry()
        h = reg.histogram("h_seconds", "", buckets=(0.01, 1.0))
        h.observe(0.005)
        h.observe(0.5)
        h.observe(30.0)
        assert h.count() == 3
        assert h.sum() == pytest.approx(30.505)

    def test_kind_conflict_raises(self):
        reg = MetricsRegistry()
        reg.counter("m", "")
        with pytest.raises(ValueError, match="already registered"):
            reg.gauge("m", "")

    def test_get_or_create_is_idempotent(self):
        reg = MetricsRegistry()
        assert reg.counter("c", "") is reg.counter("c", "")

    def test_prometheus_text_wellformed(self):
        reg = MetricsRegistry()
        reg.counter("msgs_total", "Messages").inc(
            3, type="value", direction="in")
        reg.gauge("depth", "Queue depth").set(7, agent="a1")
        h = reg.histogram("lat_seconds", "Latency", buckets=(0.1, 1.0))
        h.observe(0.05, op="send")
        text = reg.to_prometheus()
        lines = text.strip().splitlines()
        families = set()
        for line in lines:
            if line.startswith("# HELP "):
                families.add(line.split()[2])
            elif line.startswith("# TYPE "):
                parts = line.split()
                assert parts[2] in families, "TYPE before HELP"
                assert parts[3] in ("counter", "gauge", "histogram")
            else:
                assert _PROM_SAMPLE.match(line), line
        assert {"msgs_total", "depth", "lat_seconds"} <= families
        assert 'lat_seconds_bucket{le="+Inf",op="send"} 1' in lines
        assert "lat_seconds_count" in text

    def test_snapshot_jsonl(self, tmp_path):
        reg = MetricsRegistry()
        reg.counter("c_total", "").inc(4)
        path = str(tmp_path / "m.jsonl")
        reg.write_snapshot(path, cycle=10)
        reg.write_snapshot(path, cycle=20)
        rows = [json.loads(line) for line in open(path)]
        assert [r["cycle"] for r in rows] == [10, 20]
        sample = rows[0]["metrics"]["c_total"]["samples"][0]
        assert sample == {"labels": {}, "value": 4}


class TestCycleSnapshotter:
    def test_monotone_counter_and_cadence(self, tmp_path):
        reg = MetricsRegistry()
        path = str(tmp_path / "m.jsonl")
        snap = CycleSnapshotter(path, every=5, reg=reg)
        snap(2)      # below cadence from 0? delta=2 -> first write
        snap(3)      # +1 < 5: skipped
        snap(1)      # regression: skipped (counter must stay monotone)
        snap(8)      # +6: written
        snap(8)      # no advance: skipped
        rows = [json.loads(line) for line in open(path)]
        assert [r["cycle"] for r in rows] == [2, 8]
        assert reg.value("pydcop_cycles_total") == 8
        assert reg.value("pydcop_cycle") == 8

    def test_cost_fn_called_only_on_snapshot(self, tmp_path):
        reg = MetricsRegistry()
        calls = []

        def cost():
            calls.append(1)
            return 42.0

        snap = CycleSnapshotter(str(tmp_path / "m.jsonl"), every=10,
                                reg=reg, cost_fn=cost)
        for cycle in range(1, 10):
            snap(cycle)
        assert calls == [1]  # only the first write (cycle 1) fired
        snap(11)
        assert len(calls) == 2
        assert reg.value("pydcop_cost") == 42.0


# ------------------------------------------------------------------ #
# stats shim (reference CSV parity + atomic swap regression)


class TestStatsShim:
    def test_forwards_rows_to_tracer(self, tmp_path):
        from pydcop_tpu.infrastructure import stats

        tracer.enable()
        try:
            path = str(tmp_path / "steps.csv")
            stats.set_stats_file(path)
            try:
                stats.trace_computation("v1", 0.02, 1, 3, 2, 4,
                                        value="R")
            finally:
                stats.set_stats_file(None)
        finally:
            tracer.disable()
        events = [e for e in tracer.events()
                  if e["name"] == "computation_step"]
        assert len(events) == 1
        assert events[0]["args"]["computation"] == "v1"
        assert events[0]["args"]["value"] == "R"
        # And the CSV row still landed (reference parity).
        lines = open(path).read().strip().splitlines()
        assert lines[1].split(",")[1] == "v1"

    def test_forwards_without_csv_file(self):
        from pydcop_tpu.infrastructure import stats

        tracer.enable()
        try:
            stats.trace_computation("v2", 0.01)
        finally:
            tracer.disable()
        assert [e["args"]["computation"] for e in tracer.events()
                if e["name"] == "computation_step"] == ["v2"]

    def test_failed_switch_keeps_previous_writer(self, tmp_path):
        """Regression: a failing open() mid-switch used to close the
        old file first and leave the globals half-cleared — callers
        believed tracing was on while every row vanished."""
        from pydcop_tpu.infrastructure import stats

        good = str(tmp_path / "good.csv")
        stats.set_stats_file(good)
        try:
            stats.trace_computation("before", 0.01)
            with pytest.raises(OSError):
                stats.set_stats_file(
                    str(tmp_path / "no_such_dir" / "bad.csv"))
            # Previous state intact: still enabled, still writing to
            # the original file.
            assert stats.tracing_enabled()
            stats.trace_computation("after", 0.01)
        finally:
            stats.set_stats_file(None)
        rows = open(good).read().strip().splitlines()
        assert [r.split(",")[1] for r in rows[1:]] == ["before",
                                                       "after"]

    def test_close_is_idempotent(self, tmp_path):
        from pydcop_tpu.infrastructure import stats

        stats.set_stats_file(str(tmp_path / "x.csv"))
        stats.close()
        stats.close()
        assert not stats.tracing_enabled()
        stats.trace_computation("v", 0.01)  # no-op, must not raise


# ------------------------------------------------------------------ #
# engine probe (device-mode cost/convergence telemetry)


class TestEngineProbe:
    def test_probed_solve_curve_matches_reported_cost(self, tmp_path):
        from pydcop_tpu.api import solve

        from pydcop_tpu.observability.metrics import registry

        metrics_file = str(tmp_path / "m.jsonl")
        trace_file = str(tmp_path / "t.json")
        # The cycle counter is process-global and monotone across
        # solves: assert this solve's DELTA, not an absolute value
        # that depends on what ran before in the process.
        cycles_before = registry.value("pydcop_cycles_total")
        res = solve(
            _ring_dcop(), "maxsum", backend="device", max_cycles=80,
            trace=trace_file, metrics_file=metrics_file,
            metrics_every=10,
        )
        curve = res["metrics"]["cost_curve"]
        assert curve, "probed solve produced no cost curve"
        cycles = [c for c, _ in curve]
        assert cycles == sorted(cycles)
        assert cycles[-1] == res["cycles"]
        # The acceptance contract: the curve's final point equals the
        # solver's reported cost.
        assert curve[-1][1] == pytest.approx(res["cost"])
        # JSONL snapshots: monotone cycle counter, parsable lines.
        rows = [json.loads(line) for line in open(metrics_file)]
        snap_cycles = [r["cycle"] for r in rows]
        assert snap_cycles == sorted(snap_cycles)
        total = rows[-1]["metrics"]["pydcop_cycles_total"]
        assert total["samples"][0]["value"] - cycles_before \
            == snap_cycles[-1]
        # Prometheus dump parses.
        prom = open(metrics_file + ".prom").read()
        assert "# HELP pydcop_cycles_total" in prom
        assert "# TYPE pydcop_cycles_total counter" in prom
        for line in prom.strip().splitlines():
            if not line.startswith("#"):
                assert _PROM_SAMPLE.match(line), line
        # Trace: engine chunks + segments present, well nested.
        events = load_trace_file(trace_file)
        names = {e["name"] for e in events}
        assert {"solve", "engine_segment", "chunk"} <= names
        check_well_nested(events)

    def test_probe_without_files_collects_points(self):
        from pydcop_tpu.algorithms.maxsum import build_engine
        from pydcop_tpu.observability.engine_probe import EngineProbe
        from pydcop_tpu.observability.metrics import MetricsRegistry

        engine = build_engine(_ring_dcop(), {})
        probe = EngineProbe(engine, registry=MetricsRegistry())
        res = engine.run_checkpointed(
            max_cycles=40, segment_cycles=10, probe=probe)
        assert len(probe.chunks) == res.metrics["segments"]
        assert all(s >= 0 for _, _, _, s in probe.chunks)
        assert probe.cost_curve()[-1][0] == res.cycles


# ------------------------------------------------------------------ #
# agent metrics parity (registry-sourced totals)


class TestAgentMetricsParity:
    def test_totals_match_per_computation_dicts(self):
        from pydcop_tpu.algorithms import AlgorithmDef
        from pydcop_tpu.infrastructure.run import solve_with_agents

        algo = AlgorithmDef.build_with_default_param(
            "dsa", {"stop_cycle": 15}, mode="min")
        res = solve_with_agents(
            _coloring_dcop(), algo,
            distribution=Distribution({
                "a0": ["v0"], "a1": ["v1"], "a2": ["v2"],
                "a3": ["v3"], "a4": [],
            }),
            timeout=6,
        )
        agt_metrics = res["agt_metrics"]
        assert agt_metrics
        for name, metrics in agt_metrics.items():
            assert metrics["msg_count"] == sum(
                metrics["count_ext_msg"].values()), name
            assert metrics["msg_size"] == sum(
                metrics["size_ext_msg"].values()), name
            activity = metrics["activity"]
            assert activity["active_s"] >= 0
            assert activity["total_s"] >= activity["active_s"]
            assert metrics["activity_ratio"] == pytest.approx(
                activity["active_s"] / activity["total_s"], rel=1e-6)
        # Orchestrator end-metrics aggregate the same counters.
        assert res["msg_count"] == sum(
            m["msg_count"] for m in agt_metrics.values())
        assert res["msg_size"] == sum(
            m["msg_size"] for m in agt_metrics.values())


# ------------------------------------------------------------------ #
# end-to-end: a chaos run is fully reconstructable from one trace


class TestChaosTraceReconstruction:
    def test_one_trace_carries_all_required_span_kinds(self, tmp_path,
                                                       capsys):
        """Agent step, message send/recv, injected fault drop, breaker
        trip and checkpoint write all land in ONE tracing session, the
        exported Chrome trace validates, and ``pydcop trace summary``
        aggregates it without error."""
        from pydcop_tpu.api import solve
        from pydcop_tpu.infrastructure.run import solve_with_agents
        from pydcop_tpu.resilience.faults import FaultPlan
        from pydcop_tpu.resilience.retry import CircuitBreaker

        tracer.enable()
        try:
            # 1. Thread-mode chaos solve: agent steps, send/recv,
            # fault drops.
            solve_with_agents(
                _coloring_dcop(), "amaxsum",
                distribution=Distribution({
                    "a0": ["v0", "diff_0_1"], "a1": ["v1"],
                    "a2": ["v2", "diff_1_2"],
                    "a3": ["v3", "diff_2_3"], "a4": [],
                }),
                timeout=3,
                fault_plan=FaultPlan(seed=42, drop=0.3),
            )
            # 2. Device checkpointed solve: checkpoint_write spans.
            solve(
                _ring_dcop(), "maxsum", backend="device",
                max_cycles=30,
                checkpoint_dir=str(tmp_path / "ckpt"),
                checkpoint_every=10,
            )
            # 3. A destination failing repeatedly: breaker trip.
            breaker = CircuitBreaker(2, 1.0, name="a_dead")
            breaker.record_failure()
            breaker.record_failure()
        finally:
            tracer.disable()
        trace_file = str(tmp_path / "chaos.json")
        tracer.export_chrome(trace_file)
        events = load_trace_file(trace_file)
        names = {e["name"] for e in events}
        required = {"agent_step", "message_send", "message_recv",
                    "fault_drop", "breaker_trip", "checkpoint_write"}
        assert required <= names, f"missing: {required - names}"
        check_well_nested(events)
        # The summary command aggregates it without error.
        from pydcop_tpu.dcop_cli import main

        assert main(["trace", "summary", trace_file]) == 0
        out = capsys.readouterr().out
        assert "agent_step" in out

"""DSA (Distributed Stochastic Algorithm) step kernel — variants A/B/C.

Reference parity: pydcop/algorithms/dsa.py:214-431 (Zhang et al. 2005
semantics): per cycle each variable computes its best local response
given neighbors' previous values; it changes (to a uniform-random choice
among optimal values) with probability p when

- variant A: strict improvement exists (delta > 0, :358);
- variant B: delta > 0, or delta == 0 with some incident constraint not
  at its own optimum (:369, exists_violated_constraint :419) — dropping
  the current value from the candidates when other optima exist (:380);
- variant C: delta >= 0 (:389), same current-value dropping.

The whole population updates in lockstep from previous-cycle values,
matching the reference's current/next cycle maps (:266-268).
"""

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from pydcop_tpu.engine.compile import CompiledFactorGraph
from pydcop_tpu.ops.localsearch import (
    assignment_cost,
    best_candidates,
    candidate_costs,
    factor_current_costs,
    positional_max,
    random_best_choice,
    random_initial_values,
)


class DsaState(NamedTuple):
    values: jnp.ndarray  # [V+1] int32 current value index (sentinel last)
    key: jnp.ndarray
    cycle: jnp.ndarray


def init_state(graph: CompiledFactorGraph, seed: int = 0) -> DsaState:
    key = jax.random.PRNGKey(seed)
    key, k0 = jax.random.split(key)
    return DsaState(
        values=random_initial_values(k0, graph),
        key=key,
        cycle=jnp.asarray(0, dtype=jnp.int32),
    )


def greedy_classes(graph: CompiledFactorGraph
                   ) -> Tuple[np.ndarray, int]:
    """Greedy graph coloring of the variable adjacency (host-side):
    returns ([V+1] int32 class ids, n_classes) such that no two
    variables sharing a constraint get the same class.  Used by the
    staggered (async-emulating) schedule: per superstep only one class
    flips, so neighbors never flip simultaneously."""
    n = int(graph.var_costs.shape[0])
    # Vectorized edge extraction: stack every (position p, position q)
    # column pair of every bucket, dedupe with np.unique — the pure-
    # python per-row loop this replaces was O(rows * arity^2) set ops
    # and dominated startup at large scale (review r5).
    pairs = []
    for bucket in graph.buckets:
        ids = np.asarray(bucket.var_ids)
        arity = ids.shape[1]
        for p in range(arity):
            for q in range(p + 1, arity):
                pairs.append(ids[:, (p, q)])
    if pairs:
        edges = np.concatenate(pairs, axis=0)
        lo = np.minimum(edges[:, 0], edges[:, 1])
        hi = np.maximum(edges[:, 0], edges[:, 1])
        keep = lo != hi  # drop self/sentinel-padding pairs
        edges = np.unique(
            np.stack([lo[keep], hi[keep]], axis=1), axis=0)
    else:
        edges = np.zeros((0, 2), dtype=np.int64)
    # CSR-style adjacency from the symmetric edge list.
    sym = np.concatenate([edges, edges[:, ::-1]], axis=0)
    order_idx = np.argsort(sym[:, 0], kind="stable")
    srcs, dsts = sym[order_idx, 0], sym[order_idx, 1]
    starts = np.searchsorted(srcs, np.arange(n + 1))
    degree = starts[1:] - starts[:-1]
    # The sentinel row (last) absorbs padding edges; colour it freely.
    classes = np.full(n, -1, dtype=np.int32)
    # Highest degree first keeps the class count near the graph's
    # chromatic bound (degree+1 worst case).
    for v in np.argsort(-degree[:-1], kind="stable"):
        neigh = dsts[starts[v]:starts[v + 1]]
        taken = set(int(c) for c in classes[neigh] if c >= 0)
        c = 0
        while c in taken:
            c += 1
        classes[v] = c
    classes[n - 1] = 0
    n_classes = int(classes.max()) + 1 if n > 1 else 1
    return classes, n_classes


def _factor_optima(graph: CompiledFactorGraph) -> Tuple[jnp.ndarray, ...]:
    """Per bucket, each factor's optimal (min) cost over all assignments
    (reference best_constraints_costs, dsa.py:273)."""
    return tuple(
        jnp.min(b.costs, axis=tuple(range(1, b.costs.ndim)))
        for b in graph.buckets
    )


def violated_vars(graph: CompiledFactorGraph,
                  values: jnp.ndarray) -> jnp.ndarray:
    """[V+1] bool: has an incident constraint not at its optimal cost
    (reference exists_violated_constraint, dsa.py:419)."""
    per_bucket = []
    for bucket, cur, opt in zip(
        graph.buckets, factor_current_costs(graph, values),
        _factor_optima(graph),
    ):
        viol = (cur != opt).astype(jnp.int32)
        per_bucket.append(jnp.broadcast_to(
            viol[:, None], bucket.var_ids.shape))
    return positional_max(graph, per_bucket, jnp.int32(0)) > 0


def dsa_step(state: DsaState, graph: CompiledFactorGraph, *,
             variant: str, probability: jnp.ndarray,
             classes: Optional[jnp.ndarray] = None,
             n_classes: int = 1) -> DsaState:
    """One lockstep DSA cycle.  `probability` is scalar or [V+1]
    (per-variable, for p_mode=arity).

    With ``classes``/``n_classes`` set (staggered schedule, adsa), only
    the variables whose graph-coloring class equals ``cycle mod
    n_classes`` may flip this superstep — neighbors never flip
    simultaneously, emulating the clock skew of the true-async runtime
    (see algorithms/adsa.py)."""
    key, k_choice, k_change = jax.random.split(state.key, 3)
    values = state.values

    cand = candidate_costs(graph, values)               # [V+1, D]
    cur = jnp.take_along_axis(cand, values[:, None], axis=1).squeeze(1)
    best, is_best = best_candidates(graph, cand)
    delta = cur - best                                   # >= 0

    if variant == "A":
        eligible = delta > 0
        choice_mask = is_best
    else:
        n_best = jnp.sum(is_best, axis=1)
        one_hot_cur = (
            jnp.arange(cand.shape[1])[None, :] == values[:, None]
        )
        drop_cur = ((delta == 0) & (n_best > 1))[:, None] & one_hot_cur
        choice_mask = is_best & ~drop_cur
        if variant == "B":
            eligible = (delta > 0) | (
                (delta == 0) & violated_vars(graph, values)
            )
        else:  # C
            eligible = delta >= 0

    new_vals = random_best_choice(k_choice, choice_mask)
    u = jax.random.uniform(k_change, (values.shape[0],))
    change = eligible & (u < probability)
    if classes is not None and n_classes > 1:
        change = change & (classes == state.cycle % n_classes)
    values = jnp.where(change, new_vals, values)
    return DsaState(values=values, key=key, cycle=state.cycle + 1)


def run_dsa(graph: CompiledFactorGraph, max_cycles: int, *,
            variant: str = "B", probability=0.7, seed: int = 0,
            classes: Optional[jnp.ndarray] = None, n_classes: int = 1,
            ) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Full DSA run in one XLA program.

    ``max_cycles`` counts supersteps; with a staggered schedule the
    caller scales it by ``n_classes`` so every variable keeps the same
    number of update opportunities (one per full class sweep).

    Returns (values [V], final cost, cycles)."""
    state = init_state(graph, seed)
    state = jax.lax.fori_loop(
        0, max_cycles,
        lambda i, s: dsa_step(
            s, graph, variant=variant, probability=probability,
            classes=classes, n_classes=n_classes,
        ),
        state,
    )
    cost = assignment_cost(graph, state.values)
    return state.values[:-1], cost, state.cycle

"""Computation model: messages, message-passing computations, BSP mixin.

Reference parity: pydcop/infrastructure/computations.py (Message :53,
message_type :122, ComputationMetaClass :237, MessagePassingComputation
:261, register :576, SynchronousComputationMixin :633, DcopComputation
:832, VariableComputation :967, ExternalVariableComputation :1093,
build_computation :1156).
"""

import logging
import random
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

from pydcop_tpu.infrastructure.events import event_bus
from pydcop_tpu.observability.trace import tracer
from pydcop_tpu.utils.simple_repr import SimpleRepr

MSG_ALGO = 20
MSG_VALUE = 15
MSG_MGT = 10


class ComputationException(Exception):
    pass


class Message(SimpleRepr):
    """Base class for all messages exchanged between computations."""

    def __init__(self, msg_type: str, content: Any = None):
        self._msg_type = msg_type
        self._content = content

    @property
    def type(self) -> str:
        return self._msg_type

    @property
    def content(self) -> Any:
        return self._content

    @property
    def size(self) -> int:
        """Message size, used by communication-load metrics."""
        return 1

    def __eq__(self, other):
        return (
            type(self) is type(other)
            and self._msg_type == other._msg_type
            and self._content == other._content
        )

    def __repr__(self):
        return f"Message({self._msg_type}, {self._content})"


def message_type(name: str, fields: List[str]):
    """Class factory for simple message types (reference
    computations.py:122).

    >>> ValueMsg = message_type('value_msg', ['value', 'cost'])
    >>> m = ValueMsg(value=2, cost=1.5)
    >>> m.value, m.type
    (2, 'value_msg')
    """

    def __init__(self, *args, **kwargs):
        if args:
            kwargs.update(zip(fields, args))
        for f in fields:
            if f not in kwargs:
                raise ValueError(f"Missing field {f!r} for {name} message")
            setattr(self, "_" + f, kwargs[f])
        Message.__init__(self, name, None)

    def _simple_repr(self):
        r = {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
        }
        from pydcop_tpu.utils.simple_repr import simple_repr

        for f in fields:
            r[f] = simple_repr(getattr(self, "_" + f))
        return r

    def _size(self):
        return len(fields)

    attrs = {
        "__init__": __init__,
        "_simple_repr": _simple_repr,
        "size": property(_size),
        "__repr__": lambda self: f"{name}({ {f: getattr(self, '_' + f) for f in fields} })",
        "__eq__": lambda self, other: (
            type(self) is type(other)
            and all(
                getattr(self, "_" + f) == getattr(other, "_" + f)
                for f in fields
            )
        ),
    }
    for f in fields:
        attrs[f] = property(lambda self, _f=f: getattr(self, "_" + _f))
    cls = type(name, (Message,), attrs)
    # Anchor the class in its *defining* module (not this factory's)
    # and expose it there under the wire name, so from_repr can resolve
    # "<defining module>.<name>" when deserializing over HTTP.
    import sys

    caller_globals = sys._getframe(1).f_globals
    cls.__module__ = caller_globals.get("__name__", cls.__module__)
    caller_globals.setdefault(name, cls)
    return cls


def register(msg_type: str):
    """Decorator declaring a method as the handler for a message type
    (reference computations.py:576)."""

    def decorate(handler):
        handler._registered_handler_for = msg_type
        return handler

    return decorate


class _RetryEntry(NamedTuple):
    """A paused-buffer entry that already failed ``attempts`` resume
    flushes (see MessagePassingComputation._flush_paused)."""

    entry: Tuple
    attempts: int


class ComputationMetaClass(type):
    """Collects @register-ed handlers into ``_decorated_handlers``."""

    def __new__(mcs, name, bases, namespace):
        cls = super().__new__(mcs, name, bases, namespace)
        handlers: Dict[str, Callable] = {}
        for base in reversed(cls.__mro__):
            for attr in base.__dict__.values():
                msg_type = getattr(attr, "_registered_handler_for", None)
                if msg_type:
                    handlers[msg_type] = attr
        cls._decorated_handlers = handlers
        return cls


class MessagePassingComputation(metaclass=ComputationMetaClass):
    """A named computation exchanging messages through its agent.

    Lifecycle: created -> start() -> running; pause()/resume(); stop().
    Messages received while paused are buffered and delivered on resume
    (reference computations.py:354-446).  Single-threaded by design: the
    hosting agent delivers messages sequentially, so handlers need no
    locking (reference :279-281).
    """

    def __init__(self, name: str):
        self._name = name
        self._msg_sender: Optional[Callable] = None
        self._periodic_action_handler = None
        self._periodic_remove_handler = None
        self._running = False
        self._is_paused = False
        self._paused_messages_post: List[Tuple] = []
        self._paused_messages_recv: List[Tuple] = []
        self.logger = logging.getLogger(f"pydcop.computation.{name}")
        # (period, action, pause-guarded wrapper the agent runs).
        self._periodic_actions: List[
            Tuple[float, Callable, Callable]] = []

    @property
    def name(self) -> str:
        return self._name

    @property
    def is_running(self) -> bool:
        return self._running

    @property
    def is_paused(self) -> bool:
        return self._is_paused

    @property
    def message_sender(self) -> Optional[Callable]:
        return self._msg_sender

    @message_sender.setter
    def message_sender(self, sender: Callable):
        if self._msg_sender is not None and sender is not self._msg_sender:
            raise ComputationException(
                f"Computation {self.name} already has a message sender"
            )
        self._msg_sender = sender

    def start(self):
        self._running = True
        self.on_start()

    def stop(self):
        if self._running:
            self._running = False
            self.on_stop()

    def pause(self, paused: bool = True):
        if paused == self._is_paused:
            return
        self._is_paused = paused
        if paused:
            self.on_pause(True)
        else:
            self.on_pause(False)
            # BOTH buffers are drained even if the first drain saw an
            # error (aborting between them would strand the posts on a
            # now-unpaused computation); the first error across both
            # is re-raised at the end.
            #
            # Receptions flush THROUGH on_message, not _dispatch:
            # synchronous computations wrap algo messages in "_cycle"
            # envelopes that only their on_message knows how to unwrap
            # (a raw dispatch would raise "No handler for message type
            # '_cycle'").  A poisoned entry (a protocol violation such
            # as a duplicate cycle message, i.e. ComputationException)
            # is dropped — redelivering it would deterministically
            # raise forever.  Entries that fail for any OTHER reason
            # (environmental/transient) are kept like the post buffer's:
            # for a sync-mixin computation a dropped non-duplicate cycle
            # message would permanently stall its cycle barrier.
            recv_error = self._flush_paused(
                "_paused_messages_recv",
                self._redeliver_recv,
                keep_failed=lambda exc: not isinstance(
                    exc, ComputationException),
                max_retries=self.MAX_FLUSH_RETRIES,
            )
            # Buffered posts were already wrapped by the subclass's
            # post_msg before buffering — resend through the BASE
            # post_msg so the sync mixin cannot wrap a second "_cycle"
            # envelope around them.  Post failures are usually
            # environmental (e.g. not attached yet), so the failed
            # entry itself is kept for a later flush — with NO retry
            # cap: losing a post stalls the neighbor's cycle barrier,
            # and unlike the recv path there is no handler to be
            # deterministically buggy.
            post_error = self._flush_paused(
                "_paused_messages_post",
                lambda e, attempts: MessagePassingComputation.post_msg(
                    self, *e),
                keep_failed=True,
                max_retries=None,
            )
            error = recv_error or post_error
            if error is not None:
                raise error

    MAX_FLUSH_RETRIES = 3

    def _redeliver_recv(self, entry, attempts):
        """Deliver a buffered reception; on RETRY attempts the
        message_rcv event is suppressed — it was already emitted when
        the first delivery attempt entered on_message (single-emission
        invariant, see test_paused_send_emitted_once_on_event_bus)."""
        if attempts == 0:
            self.on_message(*entry)
            return
        self._suppress_rcv_emit = True
        try:
            self.on_message(*entry)
        finally:
            self._suppress_rcv_emit = False

    def _flush_paused(self, buffer_attr: str, deliver, keep_failed,
                      max_retries=None):
        """Drain a paused-message buffer in order, delivering EVERY
        entry even when one raises (remaining messages must not be
        stranded — with the sync mixin a lost message stalls a
        neighbor's cycle barrier forever).  ``keep_failed`` — a bool or
        a predicate over the raised exception — decides per entry
        whether a failed one is kept in the buffer or dropped with a
        logged traceback; with ``max_retries`` set, a kept entry
        survives at most that many failed flushes (a deterministically-
        buggy handler must not poison every future pause/resume round;
        the post buffer passes None — unbounded — because its failures
        are environmental and a dropped post is a lost message).  The
        first exception is RETURNED (not raised) so the caller can
        drain every buffer before surfacing it.  The buffer is swapped
        out first: a handler may re-pause, and appending to a list
        being iterated would loop."""
        entries = getattr(self, buffer_attr)
        setattr(self, buffer_attr, [])
        first_error = None
        failed = []
        for item in entries:
            if isinstance(item, _RetryEntry):
                entry, attempts = item.entry, item.attempts
            else:
                entry, attempts = item, 0
            try:
                deliver(entry, attempts)
            except Exception as e:  # noqa: BLE001 - surfaced by caller
                keep = keep_failed(e) if callable(keep_failed) \
                    else keep_failed
                if keep and max_retries is not None \
                        and attempts + 1 >= max_retries:
                    keep = False
                # Log every failure here: only the FIRST error is
                # surfaced to the caller, and a dropped entry would
                # otherwise vanish without a trace.
                self.logger.exception(
                    "Error flushing paused message %s of %s "
                    "(attempt %d, %s)", entry, self.name, attempts + 1,
                    "kept" if keep else "dropped",
                )
                if keep:
                    failed.append(_RetryEntry(entry, attempts + 1))
                if first_error is None:
                    first_error = e
        # Prepend: anything buffered DURING the drain (a handler
        # re-paused) is newer than the failed entries.
        setattr(self, buffer_attr, failed + getattr(self, buffer_attr))
        return first_error

    # Hooks:
    def on_start(self):
        pass

    def on_stop(self):
        pass

    def on_pause(self, paused: bool):
        pass

    def on_message(self, sender: str, msg: Message, t: float):
        """Entry point used by the agent to deliver a message."""
        # Buffer BEFORE emitting: the resume flush re-enters
        # on_message, and emitting on arrival AND on flush would
        # double-count paused-period traffic on the event bus.
        if self._is_paused:
            self._paused_messages_recv.append((sender, msg, t))
            return
        if event_bus.enabled and not getattr(
                self, "_suppress_rcv_emit", False):
            event_bus.emit(
                f"computations.message_rcv.{self.name}", (sender, msg)
            )
        self._dispatch(sender, msg, t)

    def _dispatch(self, sender: str, msg: Message, t: float):
        handler = self._decorated_handlers.get(msg.type)
        if handler is None:
            raise ComputationException(
                f"No handler for message type {msg.type!r} in "
                f"computation {self.name}"
            )
        handler(self, sender, msg, t)

    def post_msg(self, target: str, msg: Message, prio: int = MSG_ALGO,
                 on_error=None):
        # Buffer BEFORE emitting (mirror of on_message): the resume
        # flush re-sends buffered entries, and emitting on buffering
        # AND on flush would double-count paused-period sends.
        if self._is_paused:
            self._paused_messages_post.append((target, msg, prio, on_error))
            return
        if event_bus.enabled:
            event_bus.emit(
                f"computations.message_snd.{self.name}", (target, msg)
            )
        if self._msg_sender is None:
            raise ComputationException(
                f"Computation {self.name} is not attached to an agent, "
                "cannot send messages"
            )
        self._msg_sender(self.name, target, msg, prio, on_error)

    def add_periodic_action(self, period: float, action: Callable):
        """Register `action` to run every `period` seconds on the agent
        thread.  Reference semantics (computations.py:546-566): the
        action is wrapped in a pause guard, so a paused computation's
        periodic actions do not fire."""

        def guarded():
            if not self._is_paused:
                action()

        self._periodic_actions.append((period, action, guarded))
        if self._periodic_action_handler:
            self._periodic_action_handler(period, guarded)
        return action

    def remove_periodic_action(self, action):
        """Unregister every registration of `action` (equality, not
        identity — bound methods compare equal across accesses); takes
        effect immediately even when the computation is already
        deployed on an agent (reference computations.py:568)."""
        kept, removed = [], []
        for entry in self._periodic_actions:
            (removed if entry[1] == action else kept).append(entry)
        self._periodic_actions = kept
        if self._periodic_remove_handler:
            for _, _, guarded in removed:
                self._periodic_remove_handler(guarded)

    def finished(self):
        """Signal the end of this computation (picked up by the hosting
        agent / orchestration)."""
        if getattr(self, "_on_finish_cb", None):
            self._on_finish_cb(self)

    def __repr__(self):
        return f"{type(self).__name__}({self.name})"


class SynchronousComputationMixin:
    """Network-level synchronous (BSP) execution.

    Messages are stamped with the sender's cycle id; a computation
    advances to cycle N+1 once it has one message from *every* neighbor
    for cycle N, then ``on_new_cycle(messages, cycle_id)`` fires.
    Neighbors with nothing to say send a SynchronizationMsg filler
    (reference computations.py:633-830: cycle stamping :731-739,
    collection :684-725, fillers :777-785).  Receiving two messages from
    the same neighbor for one cycle, or a message more than one cycle
    ahead, raises ComputationException.
    """

    SYNC_MSG_TYPE = "_sync"

    def __init_sync(self):
        if not hasattr(self, "_sync_initialized"):
            self._sync_initialized = True
            self._current_cycle_messages: Dict[str, Tuple] = {}
            self._next_cycle_messages: Dict[str, Tuple] = {}
            self._cycle_id = 0
            self._posted_this_cycle = set()

    @property
    def cycle_id(self) -> int:
        self.__init_sync()
        return self._cycle_id

    @property
    def current_cycle(self) -> Dict[str, Tuple]:
        self.__init_sync()
        return self._current_cycle_messages

    def start(self):  # overrides MessagePassingComputation.start
        self.__init_sync()
        self._running = True
        self.on_start()
        # Fire the first cycle immediately so computations with no
        # on_start sends still participate.
        self._fire_cycle()

    def on_message(self, sender: str, msg, t: float):
        self.__init_sync()
        if self._is_paused:
            self._paused_messages_recv.append((sender, msg, t))
            return
        cycle, inner = msg.content if msg.type == "_cycle" else (None, msg)
        if cycle is None:
            # Non-algo message (mgt): dispatch directly.
            self._dispatch(sender, msg, t)
            return
        if cycle == self._cycle_id:
            if sender in self._current_cycle_messages:
                raise ComputationException(
                    f"{self.name}: duplicate message from {sender} for "
                    f"cycle {cycle}"
                )
            self._current_cycle_messages[sender] = (inner, t)
            self._maybe_switch_cycle()
        elif cycle == self._cycle_id + 1:
            if sender in self._next_cycle_messages:
                raise ComputationException(
                    f"{self.name}: duplicate message from {sender} for "
                    f"next cycle {cycle}"
                )
            self._next_cycle_messages[sender] = (inner, t)
        else:
            raise ComputationException(
                f"{self.name}: message from {sender} for cycle {cycle} "
                f"while in cycle {self._cycle_id} (skew > 1)"
            )

    def post_msg(self, target: str, msg, prio: int = MSG_ALGO,
                 on_error=None):
        """Algo messages are wrapped with the current cycle id."""
        self.__init_sync()
        self._posted_this_cycle.add(target)
        wrapped = Message("_cycle", (self._cycle_id, msg))
        MessagePassingComputation.post_msg(
            self, target, wrapped, prio, on_error
        )

    def _fire_cycle(self):
        """Send sync fillers to neighbors we did not message this cycle."""
        self.__init_sync()
        for n in self.neighbors:
            if n not in self._posted_this_cycle:
                filler = Message("_cycle", (self._cycle_id, None))
                MessagePassingComputation.post_msg(
                    self, n, filler, MSG_ALGO, None
                )

    def _maybe_switch_cycle(self):
        neighbors = set(self.neighbors)
        if not neighbors or not self._running:
            return  # neighborless computations never cycle
        if set(self._current_cycle_messages) < neighbors:
            return
        messages = {
            s: (m, t)
            for s, (m, t) in self._current_cycle_messages.items()
            if m is not None
        }
        self._cycle_id += 1
        self._current_cycle_messages = self._next_cycle_messages
        self._next_cycle_messages = {}
        self._posted_this_cycle = set()
        if hasattr(self, "new_cycle"):
            self.new_cycle()
        out = self.on_new_cycle(messages, self._cycle_id - 1)
        if out:
            for target, msg in out:
                self.post_msg(target, msg)
        if self._running:
            self._fire_cycle()
        self._maybe_switch_cycle()

    def on_new_cycle(self, messages: Dict[str, Tuple], cycle_id: int
                     ) -> Optional[List]:
        """Override point: called once per cycle with that cycle's
        messages {sender: (msg, t)}."""
        return None


class DcopComputation(MessagePassingComputation):
    """A computation attached to a node of a computation graph."""

    def __init__(self, name: str, comp_def):
        super().__init__(name)
        self.computation_def = comp_def
        self._cycle_count = 0

    @property
    def neighbors(self) -> List[str]:
        return list(self.computation_def.node.neighbors)

    @property
    def cycle_count(self) -> int:
        return self._cycle_count

    @property
    def mode(self) -> str:
        return self.computation_def.algo.mode

    def new_cycle(self):
        self._cycle_count += 1
        if getattr(self, "_on_cycle_cb", None):
            self._on_cycle_cb(self)
        if event_bus.enabled:
            event_bus.emit(
                f"computations.cycle.{self.name}", self._cycle_count
            )
        if tracer.enabled:
            tracer.instant("cycle", "computation",
                           computation=self.name,
                           cycle=self._cycle_count)

    def footprint(self) -> float:
        from pydcop_tpu.algorithms import load_algorithm_module

        module = load_algorithm_module(self.computation_def.algo.algo)
        return module.computation_memory(self.computation_def.node)

    def post_to_all_neighbors(self, msg: Message, prio: int = MSG_ALGO):
        for n in self.neighbors:
            self.post_msg(n, msg, prio)


class VariableComputation(DcopComputation):
    """A computation responsible for selecting one variable's value."""

    def __init__(self, variable, comp_def):
        super().__init__(variable.name, comp_def)
        self._variable = variable
        self._current_value = None
        self._current_cost = None
        self._previous_val = None

    @property
    def variable(self):
        return self._variable

    @property
    def current_value(self):
        return self._current_value

    @property
    def current_cost(self):
        return self._current_cost

    def value_selection(self, val, cost: float = 0.0):
        """Select a value; fires the value-change callback used by the
        orchestration layer for metrics (reference computations.py:1058)."""
        from pydcop_tpu.infrastructure.events import event_bus

        self._previous_val = self._current_value
        self._current_value = val
        self._current_cost = cost
        if getattr(self, "_on_value_cb", None):
            self._on_value_cb(self)
        if event_bus.enabled:
            event_bus.emit(
                f"computations.value.{self.name}", (val, cost)
            )
        if tracer.enabled:
            tracer.instant("value_selection", "computation",
                           computation=self.name, value=str(val),
                           cost=cost)

    def random_value_selection(self):
        self.value_selection(random.choice(list(self._variable.domain)))


class ExternalVariableComputation(DcopComputation):
    """Read-only computation publishing an external variable's value."""

    def __init__(self, external_var, comp_def=None):
        # External variables have no algorithm; build a minimal def.
        super().__init__(external_var.name, comp_def)
        self._external_var = external_var
        self._subscribers = set()
        external_var.subscribe(self._on_change)

    @property
    def neighbors(self):
        return list(self._subscribers)

    @register("subscribe")
    def _on_subscribe_msg(self, sender, msg, t):
        self._subscribers.add(sender)
        self.post_msg(
            sender, Message("external_value", self._external_var.value)
        )

    def _on_change(self, value):
        for s in self._subscribers:
            self.post_msg(s, Message("external_value", value))


def build_computation(comp_def) -> MessagePassingComputation:
    """Instantiate the right computation for a ComputationDef (reference
    computations.py:1156): delegates to the algorithm module."""
    from pydcop_tpu.algorithms import load_algorithm_module

    module = load_algorithm_module(comp_def.algo.algo)
    return module.build_computation(comp_def)


def build_algo_computation(algo_name: str, comp_def):
    """Agent-mode computation factory used by algorithm modules."""
    from pydcop_tpu.infrastructure import agent_algorithms

    return agent_algorithms.build(algo_name, comp_def)

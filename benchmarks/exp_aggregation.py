"""Aggregation-strategy experiment for the MaxSum superstep's variable
aggregation — the op that dominates past the 100k-var scale cliff
(BENCH_TPU.md: 2 us/cycle at 10k vars vs 8.4 ms/cycle at 100k on a
v5e; the scatter-add and tiny-minor-dim gathers are the suspects).

Four strategies, identical math (up to float reassociation):

- scatter:   jax.ops.segment_sum on unsorted edge ids (current engine,
             ops/maxsum.aggregate_beliefs).
- sorted:    segment_sum on compile-time-sorted ids with
             indices_are_sorted=True (static permutation; the gather of
             messages into sorted order happens per cycle).
- boundary:  compile-time edge sort + cumsum along edges + per-variable
             boundary gathers — no scatter at all.
- ell:       compile-time per-variable edge lists padded to the max
             degree; dense gather + K-way sum — no scatter, no sort
             (TPU scatter-add serializes row updates; this is the
             vectorizable shape).

Run on the target backend:  python benchmarks/exp_aggregation.py
Prints one JSON line per size with ms/iteration for each strategy; use
it to decide whether the engine's aggregation is worth rewriting for
the HBM-bound regime (keep the engine unchanged until the winner is
measured on real hardware).
"""

import json
import os
import sys
import time
from functools import partial

import numpy as np


def build(n_vars, n_edges, d, seed=0):
    rng = np.random.default_rng(seed)
    seg = rng.integers(0, n_vars, size=n_edges).astype(np.int32)
    msgs = rng.random((n_edges, d)).astype(np.float32)
    perm = np.argsort(seg, kind="stable").astype(np.int32)
    sorted_seg = seg[perm]
    # Boundary offsets: starts[v] .. ends[v] index into the sorted
    # edge order (searchsorted on the static sorted ids).
    starts = np.searchsorted(sorted_seg, np.arange(n_vars),
                             side="left").astype(np.int32)
    ends = np.searchsorted(sorted_seg, np.arange(n_vars),
                           side="right").astype(np.int32)
    # ELL: per-variable edge lists padded to the max degree; dummy
    # slots hold n_edges (the kernel clips the index and masks the
    # contribution to zero).
    k_max = max(int((ends - starts).max()), 1)
    ell = np.full((n_vars, k_max), n_edges, np.int32)
    k_pos = np.arange(n_edges) - starts[sorted_seg]
    ell[sorted_seg, k_pos] = perm
    return seg, msgs, perm, sorted_seg, starts, ends, ell


def main():
    from pydcop_tpu.utils.cleanenv import ensure_live_backend

    ensure_live_backend(tag="exp_aggregation")
    import jax
    import jax.numpy as jnp

    from pydcop_tpu.engine.timing import warmed_marginal

    d = 3
    # Differencing over the scan length (engine/timing.py): the axon
    # tunnel's block_until_ready is a partial sync with a fixed
    # ~130 ms round-trip — a naive min-of-3 wall clock reads that
    # constant at every size below ~1M vars, making the A/B columns
    # identical noise.  The slope between two scan lengths cancels it.
    IT_LO, IT_HI = 20, 120

    def timeit(make_fn, *args):
        per_iter, _, out = warmed_marginal(
            lambda n: jax.jit(make_fn(n)), IT_LO, IT_HI,
            args=args, reps=3)
        return per_iter * 1e3, out             # ms per iteration

    # Compile frugality (round 5): each distinct XLA program costs
    # MINUTES of remote compile through the axon tunnel (the original
    # 3-size x 3-strategy x 2-scan-length grid blew a 60-minute budget
    # before reaching its decision rows).  The 1M op-level row is
    # dropped — 100k already characterizes the post-VMEM regime and
    # the engine-level leg below measures 1M end to end.
    for n_vars in (10_000, 100_000):
        n_edges = n_vars * 3
        seg, msgs, perm, sorted_seg, starts, ends, ell = build(
            n_vars, n_edges, d)

        def make_scatter(iters):
            def run(msgs, seg):
                def step(m, _):
                    s = jax.ops.segment_sum(
                        m, seg, num_segments=n_vars)
                    # feed result back so iterations can't collapse
                    return m + 1e-9 * s[seg], None
                m, _ = jax.lax.scan(step, msgs, None, length=iters)
                return jax.ops.segment_sum(m, seg, num_segments=n_vars)
            return run

        def make_sorted(iters):
            def run(msgs, seg_s, perm):
                def agg(m):
                    return jax.ops.segment_sum(
                        m[perm], seg_s, num_segments=n_vars,
                        indices_are_sorted=True)
                def step(m, _):
                    s = agg(m)
                    return m + 1e-9 * s[seg], None
                m, _ = jax.lax.scan(step, msgs, None, length=iters)
                return agg(m)
            return run

        def make_boundary(iters):
            def run(msgs, perm, starts, ends):
                def agg(m):
                    cum = jnp.cumsum(m[perm], axis=0)
                    cz = jnp.concatenate(
                        [jnp.zeros((1, d), jnp.float32), cum], axis=0)
                    return cz[ends] - cz[starts]
                def step(m, _):
                    s = agg(m)
                    return m + 1e-9 * s[seg], None
                m, _ = jax.lax.scan(step, msgs, None, length=iters)
                return agg(m)
            return run

        def make_ell(iters):
            def run(msgs, ell):
                def agg(m):
                    # clip + mask, not a zero-row append: appending
                    # copies the whole message array per iteration.
                    safe = jnp.minimum(ell, n_edges - 1)
                    mask = (ell < n_edges)[..., None]
                    return jnp.sum(
                        jnp.where(mask, m[safe], 0.0), axis=1)
                def step(m, _):
                    s = agg(m)
                    return m + 1e-9 * s[seg], None
                m, _ = jax.lax.scan(step, msgs, None, length=iters)
                return agg(m)
            return run

        t_sc, ref = timeit(make_scatter, jnp.asarray(msgs),
                           jnp.asarray(seg))
        t_so, out_so = timeit(make_sorted, jnp.asarray(msgs),
                              jnp.asarray(sorted_seg),
                              jnp.asarray(perm))
        t_bo, out_bo = timeit(make_boundary, jnp.asarray(msgs),
                              jnp.asarray(perm), jnp.asarray(starts),
                              jnp.asarray(ends))
        t_el, out_el = timeit(make_ell, jnp.asarray(msgs),
                              jnp.asarray(ell))
        err_so = float(jnp.max(jnp.abs(ref - out_so)))
        err_bo = float(jnp.max(jnp.abs(ref - out_bo)))
        err_el = float(jnp.max(jnp.abs(ref - out_el)))
        print(json.dumps({
            "n_vars": n_vars, "n_edges": n_edges,
            "backend": jax.devices()[0].platform,
            "scatter_ms": round(t_sc, 4),
            "sorted_ms": round(t_so, 4),
            "boundary_ms": round(t_bo, 4),
            "ell_ms": round(t_el, 4),
            "sorted_err": err_so, "boundary_err": err_bo,
            "ell_err": err_el,
        }))
        sys.stdout.flush()

    # Engine-level decision leg: the FULL superstep (run_maxsum) per
    # strategy on the 1M-var synthetic coloring — this is the number
    # that decides the headline bench's aggregation choice (the
    # op-level loops above attribute it).  NOTE: "boundary" here is a
    # throughput measurement only — its f32 prefix sum cancels at this
    # edge count (see ops/maxsum.aggregate_beliefs), so even if it
    # wins on speed it needs a numerics redesign before promotion to
    # the solve path.
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench as bench_mod

    # "boundary" is excluded from the engine leg (numerically
    # disqualified for solves — f32 prefix-sum cancellation, see
    # ops/maxsum.aggregate_beliefs) and "sorted" was measured ~=
    # scatter on-chip at the op level; each strategy costs two big
    # remote compiles, so spend them on the two candidates that could
    # actually become the scale-path default: the current scatter and
    # the dense-gather ell.
    for strategy in ("scatter", "ell"):
        t0 = time.perf_counter()
        cps, graph = bench_mod.bench_scale(
            n_vars=1_000_000, cycles=50, aggregation=strategy)
        print(json.dumps({
            "engine_1m_vars": strategy,
            "backend": jax.devices()[0].platform,
            "cycles_per_s": round(cps, 2),
            "ms_per_cycle": round(1e3 / cps, 3) if cps else None,
            "total_s": round(time.perf_counter() - t0, 1),
        }))
        sys.stdout.flush()
        del graph


if __name__ == "__main__":
    main()

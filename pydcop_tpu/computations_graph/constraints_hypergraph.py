"""Constraints hypergraph: one node per variable, a hyperedge per
constraint.

Reference parity: pydcop/computations_graph/constraints_hypergraph.py
(VariableComputationNode :49, ConstraintLink :113, build_computation_graph
:176).  Used by: dsa, adsa, dsatuto, mgm, mgm2, dba, gdba, mixeddsa.
"""

from typing import Iterable, List, Optional

from pydcop_tpu.computations_graph.objects import (
    ComputationGraph,
    ComputationNode,
    Link,
)
from pydcop_tpu.dcop.dcop import DCOP
from pydcop_tpu.dcop.objects import Variable
from pydcop_tpu.dcop.relations import Constraint


class ConstraintLink(Link):
    """Hyperedge linking all variables in one constraint's scope."""

    def __init__(self, constraint_name: str, nodes: Iterable[str]):
        super().__init__(nodes, "constraint_link")
        self._constraint_name = constraint_name

    @property
    def constraint_name(self) -> str:
        return self._constraint_name

    def __eq__(self, other):
        return (
            isinstance(other, ConstraintLink)
            and self._constraint_name == other._constraint_name
            and self.nodes == other.nodes
        )

    def __hash__(self):
        return hash((self._constraint_name, self.nodes))

    def _simple_repr(self):
        return {
            "__module__": self.__class__.__module__,
            "__qualname__": self.__class__.__qualname__,
            "constraint_name": self._constraint_name,
            "nodes": list(self.nodes),
        }

    @classmethod
    def _from_repr(cls, r):
        return cls(r["constraint_name"], r["nodes"])


class VariableComputationNode(ComputationNode):
    def __init__(self, variable: Variable,
                 constraints: Iterable[Constraint],
                 links: Optional[Iterable[ConstraintLink]] = None):
        constraints = list(constraints)
        if links is None:
            links = [
                ConstraintLink(c.name, [v.name for v in c.dimensions])
                for c in constraints
            ]
        super().__init__(variable.name, "VariableComputation", links)
        self._variable = variable
        self._constraints = constraints

    @property
    def variable(self) -> Variable:
        return self._variable

    @property
    def constraints(self) -> List[Constraint]:
        return list(self._constraints)


class ComputationConstraintsHyperGraph(ComputationGraph):
    def __init__(self, nodes: Iterable[VariableComputationNode]):
        super().__init__("constraints_hypergraph", nodes)


def build_computation_graph(
        dcop: Optional[DCOP] = None,
        variables: Optional[Iterable[Variable]] = None,
        constraints: Optional[Iterable[Constraint]] = None,
) -> ComputationConstraintsHyperGraph:
    """One node per variable holding the constraints whose scope
    includes it."""
    if dcop is not None:
        variables = list(dcop.variables.values())
        constraints = list(dcop.constraints.values())
    else:
        variables = list(variables or [])
        constraints = list(constraints or [])

    nodes = []
    for v in variables:
        v_constraints = [
            c for c in constraints
            if v.name in (d.name for d in c.dimensions)
        ]
        nodes.append(VariableComputationNode(v, v_constraints))
    return ComputationConstraintsHyperGraph(nodes)


def computation_memory(node: ComputationNode) -> float:
    """Footprint: the variable's neighborhood (one value per neighbor)."""
    if not isinstance(node, VariableComputationNode):
        raise TypeError(f"Unsupported node {node}")
    neighbors = set()
    for c in node.constraints:
        neighbors.update(
            v.name for v in c.dimensions if v.name != node.name
        )
    return len(neighbors)


def communication_load(src: ComputationNode, target: str) -> float:
    """Local-search messages carry a single value."""
    return 1

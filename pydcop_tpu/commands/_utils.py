"""Shared CLI helpers.

Reference parity: pydcop/commands/_utils.py (build_algo_def, module
loading, algo-params parsing).
"""

import json
from typing import Dict, List, Optional

import numpy as np

from pydcop_tpu.algorithms import AlgorithmDef


def parse_algo_params(param_strs: Optional[List[str]]) -> Dict[str, str]:
    """Parse repeated ``name:value`` CLI parameters."""
    params: Dict[str, str] = {}
    for p in param_strs or []:
        if ":" not in p:
            raise ValueError(
                f"Invalid algo parameter {p!r}: expected name:value"
            )
        name, value = p.split(":", 1)
        params[name.strip()] = value.strip()
    return params


def build_algo_def(algo: str, params_strs: Optional[List[str]],
                   objective: str) -> AlgorithmDef:
    return AlgorithmDef.build_with_default_param(
        algo, parse_algo_params(params_strs), mode=objective
    )


class _NumpyEncoder(json.JSONEncoder):
    def default(self, o):
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.integer):
            return int(o)
        if isinstance(o, np.floating):
            return float(o)
        return json.JSONEncoder.default(self, o)


def emit_result(result: dict, output_file: Optional[str] = None):
    """Print results JSON to stdout (and optionally a file), matching the
    reference output shape (commands/solve.py:611-632)."""
    text = json.dumps(result, sort_keys=True, indent="  ",
                      cls=_NumpyEncoder)
    if output_file:
        with open(output_file, "w", encoding="utf-8") as f:
            f.write(text)
    print(text)

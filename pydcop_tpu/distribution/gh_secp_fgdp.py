"""gh_secp_fgdp: SECP-specialized greedy heuristic, factor graph.

Reference parity: pydcop/distribution/gh_secp_fgdp.py — same policy as
gh_secp_cgdp applied to factor-graph computations (variables AND
factors are placed).
"""

from pydcop_tpu.distribution.gh_secp_cgdp import (  # noqa: F401
    distribute,
    distribution_cost,
)

# Test / check targets (reference parity: pydcop Makefile — unit,
# api, cli, doctests, and a static gate; the reference's mypy target
# maps to tools/static_check.py since mypy is not installable here).

PY ?= python

.PHONY: all test unit api cli check bench dryrun

all: check test

test:
	$(PY) -m pytest tests/ -q

unit:
	$(PY) -m pytest tests/unit -q

api:
	$(PY) -m pytest tests/api -q

cli:
	$(PY) -m pytest tests/cli -q

check:
	$(PY) tools/static_check.py

bench:
	$(PY) bench.py

dryrun:
	$(PY) -c "from __graft_entry__ import dryrun_multichip; dryrun_multichip(8)"

"""``pydcop agent``: standalone agents connecting to a remote
orchestrator.

Reference parity: pydcop/commands/agent.py (run_cmd :223) — start N
named agents on this machine, each with its own HTTP transport,
registering with the orchestrator given by ``--orchestrator host:port``.
``--restart`` relaunches the agents after a run ends (long-lived
worker machines surviving successive runs).
"""

import logging
import time

logger = logging.getLogger("pydcop.cli.agent")


def set_parser(subparsers):
    parser = subparsers.add_parser(
        "agent", help="standalone agents for multi-machine runs")
    parser.add_argument("-n", "--names", nargs="+", required=True,
                        help="agent names (one agent per name)")
    parser.add_argument("-o", "--orchestrator", required=True,
                        help="orchestrator address as host:port")
    parser.add_argument("--address", default="127.0.0.1",
                        help="local address to listen on")
    parser.add_argument("-p", "--port", type=int, default=9001,
                        help="first local port (one per agent)")
    parser.add_argument("--capacity", type=int, default=100,
                        help="agent capacity")
    parser.add_argument("--replication", action="store_true",
                        default=False,
                        help="host a replication computation "
                             "(resilient agents)")
    parser.add_argument("--restart", action="store_true", default=False,
                        help="restart agents after each run")
    parser.add_argument("--delay", type=float, default=None,
                        help="delay (s) between message deliveries "
                             "(live observation; reference agent "
                             "--delay)")
    parser.add_argument("--uiport", type=int, default=None,
                        help="first websocket UI port (one per agent)")
    parser.set_defaults(func=run_cmd)


def _start_agents(args, orchestrator_address):
    from pydcop_tpu.dcop.objects import AgentDef
    from pydcop_tpu.infrastructure.communication import (
        HttpCommunicationLayer,
    )
    from pydcop_tpu.infrastructure.orchestratedagents import (
        OrchestratedAgent,
    )

    agents = []
    port = args.port
    ui_port = args.uiport
    for name in args.names:
        comm = HttpCommunicationLayer((args.address, port))
        agent = OrchestratedAgent(
            AgentDef(name, capacity=args.capacity), comm,
            orchestrator_address, replication=args.replication,
            delay=args.delay, ui_port=ui_port,
        )
        agent.start()
        logger.info("Agent %s on %s:%s", name, args.address, port)
        agents.append(agent)
        port += 1
        if ui_port:
            ui_port += 1
    return agents


def run_cmd(args) -> int:
    host, _, port_str = args.orchestrator.partition(":")
    orchestrator_address = (host, int(port_str or 9000))
    while True:
        agents = _start_agents(args, orchestrator_address)
        try:
            # Block until every agent has been stopped by the
            # orchestrator (StopAgentMessage) or the global timeout.
            deadline = (
                time.monotonic() + args.timeout
                if args.timeout else None
            )
            while any(a._thread.is_alive() for a in agents):
                for a in agents:
                    a.join(0.5)
                if deadline and time.monotonic() > deadline:
                    for a in agents:
                        a.clean_shutdown(2)
                    break
        finally:
            for a in agents:
                a.messaging.shutdown()
        if not args.restart:
            return 0
        logger.info("Run finished; restarting agents (--restart)")
        time.sleep(0.5)
